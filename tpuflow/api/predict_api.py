"""Serving: load a trained artifact and predict flow for new data.

The reference's implied serving path (SURVEY.md §3.2): the web component
reads the model artifact at ``{storagePath}models/{name}.mdl`` after a
training job (reference cnn.py:39,122) and serves predictions. Here the
artifact is completed into a self-contained deployable: best params
(Orbax) **plus** a JSON sidecar with the model config and the fitted
preprocessor state, so serving needs no training-time context — exactly
what the reference's save-params-only artifact was missing.

Serving accepts **unlabeled** data: a CSV may carry all trained columns or
all-but-the-target (the usual case — the target is what's being
predicted); the column count picks the schema variant.
"""

from __future__ import annotations

import json
import sys
from dataclasses import dataclass

import jax
import numpy as np

from tpuflow.data.csv_io import read_csv
from tpuflow.data.features import FeaturePipeline
from tpuflow.data.schema import ColumnSpec, Schema
from tpuflow.models import build_model
from tpuflow.train.checkpoint import make_checkpointer
from tpuflow.train.steps import make_predict
from tpuflow.utils.paths import join_path, open_file


def _meta_path(storage_path: str, name: str) -> str:
    return join_path(storage_path, "meta", f"{name}.json")


def save_artifact_meta(
    storage_path: str,
    name: str,
    model: str,
    model_kwargs: dict,
    kind: str,
    preprocessor: dict,
    sample_shape: tuple,
) -> None:
    """Write the serving sidecar next to the checkpoint tree."""
    path = _meta_path(storage_path, name)
    with open_file(path, "w", encoding="utf-8") as f:
        json.dump(
            {
                "model": model,
                "model_kwargs": model_kwargs,
                "kind": kind,  # "tabular" | "windowed"
                "preprocessor": preprocessor,
                "sample_shape": list(sample_shape),
            },
            f,
        )


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


@dataclass
class WindowIndex:
    """Maps windowed predictions back to input rows: prediction ``i`` is
    the window of ``window`` steps starting at ``starts[i]`` (a row index
    into the original input) of well ``wells[i]``."""

    wells: list
    starts: np.ndarray


@dataclass
class Predictor:
    """A loaded artifact: jitted forward + preprocessor, ready to serve."""

    model_name: str
    kind: str
    _predict_fn: object
    _params: object
    _meta: dict
    _pipeline: FeaturePipeline | None = None  # tabular only, cached
    warm_buckets: tuple = ()  # pow-2 batch sizes pre-compiled by warmup()

    @classmethod
    def load(
        cls, storage_path: str, name: str, donate_forward: bool = False
    ) -> "Predictor":
        """``donate_forward=True`` donates the input batch buffer to the
        jitted forward (serving fast path: each padded batch is built
        fresh per dispatch and never reused after the call)."""
        from tpuflow.storage import is_store_uri, read_json

        if is_store_uri(storage_path):
            # Store-resident artifacts (fake:// today) read through the
            # object-store seam; everything else keeps the fsspec shim.
            meta = read_json(_meta_path(storage_path, name))
        else:
            with open_file(
                _meta_path(storage_path, name), "r", encoding="utf-8"
            ) as f:
                meta = json.load(f)
        # Static sidecar/config compatibility BEFORE touching the
        # checkpoint: a stale or hand-edited sidecar fails here naming
        # the bad field, not deep in Orbax restore as a pytree mismatch.
        from tpuflow.analysis.artifact import ensure_artifact_meta

        ensure_artifact_meta(meta, where=_meta_path(storage_path, name))
        model = build_model(meta["model"], **meta["model_kwargs"])
        sample = np.zeros([2] + list(meta["sample_shape"][1:]), np.float32)
        template = model.init(jax.random.PRNGKey(0), sample)["params"]
        ckpt = make_checkpointer(storage_path, name)
        params = ckpt.restore_best(template)
        ckpt.close()
        pipeline = (
            FeaturePipeline.from_dict(meta["preprocessor"])
            if meta["kind"] == "tabular"
            else None
        )
        return cls(
            model_name=name,
            kind=meta["kind"],
            _predict_fn=make_predict(model.apply, donate_input=donate_forward),
            _params=params,
            _meta=meta,
            _pipeline=pipeline,
        )

    # --- input preparation ---

    def _features_windowed(
        self, columns: dict[str, np.ndarray]
    ) -> tuple[np.ndarray, WindowIndex]:
        p = self._meta["preprocessor"]
        names = p["feature_names"]
        window, stride = p["window"], p["stride"]
        series = np.stack(
            [np.asarray(columns[n], np.float32) for n in names], axis=1
        )
        if p.get("append_gilbert"):
            # Physics-informed sequence artifact: the raw per-timestep
            # Gilbert prediction rides as the last channel via the SAME
            # helper the training pipeline used (its stored stats are
            # identity, so the normalization below leaves it raw).
            from tpuflow.core.gilbert import append_gilbert_channel

            series = append_gilbert_channel(series, names)
        mean = np.asarray(p["mean"], np.float32)
        std = np.asarray(p["std"], np.float32)
        well_col = p.get("well_column")
        if well_col and well_col in columns:
            ids = np.asarray(columns[well_col])
            # One-pass grouping (O(n log n), not O(wells x rows)): a stable
            # argsort of the inverse codes clusters each well's rows while
            # preserving their original (time) order; groups are emitted in
            # first-appearance order so predictions come out in input
            # order, not sorted-id order.
            uniq, first_idx, inverse, counts = np.unique(
                ids, return_index=True, return_inverse=True, return_counts=True
            )
            clustered = np.argsort(inverse, kind="stable")
            slices = np.split(clustered, np.cumsum(counts)[:-1])
            groups = [
                (uniq[i], slices[i]) for i in np.argsort(first_idx)
            ]
        else:
            groups = [(None, np.arange(len(series)))]
        chunks, wells_out, starts_out = [], [], []
        for well, rows in groups:
            s = series[rows]
            if len(s) < window:
                print(
                    f"tpuflow.predict: well {well!r} has {len(s)} rows "
                    f"< window={window}; skipped",
                    file=sys.stderr,
                )
                continue
            starts = np.arange(0, len(s) - window + 1, stride)
            chunks.append(np.stack([s[i : i + window] for i in starts]))
            wells_out.extend([well] * len(starts))
            starts_out.append(rows[starts])
        if not chunks:
            raise ValueError(f"no full {window}-step windows in input")
        x = np.concatenate(chunks, axis=0)
        x = ((x - mean) / std).astype(np.float32)
        return x, WindowIndex(wells_out, np.concatenate(starts_out))

    def schema(self, with_target: bool = True) -> Schema:
        """The trained schema; ``with_target=False`` = serving variant for
        unlabeled CSVs."""
        p = self._meta["preprocessor"]
        if self.kind == "tabular":
            cols = list(zip(p["names"], p["kinds"]))
            target = p["target"]
        else:
            cols = [(c["name"], c["kind"]) for c in p["schema_columns"]]
            target = p["target"]
        if not with_target:
            cols = [(n, k) for n, k in cols if n != target]
            target = None
        return Schema(
            columns=tuple(ColumnSpec(n, k) for n, k in cols), target=target
        )

    # --- serving entry points ---

    def _forward_batched(self, x: np.ndarray, batch_size: int) -> np.ndarray:
        """Chunked jitted forward with pow-2 padding on the ragged tail, so
        compile count stays O(log batch_size) across request sizes."""
        outs = []
        for s in range(0, len(x), batch_size):
            chunk = x[s : s + batch_size]
            n = len(chunk)
            padded = min(_next_pow2(n), batch_size)
            if padded > n:
                chunk = np.concatenate(
                    [chunk, np.repeat(chunk[-1:], padded - n, axis=0)]
                )
            pred = np.asarray(self._predict_fn(self._params, chunk))
            outs.append(pred[:n])
        return np.concatenate(outs, axis=0)

    def prepare_columns(
        self, columns: dict[str, np.ndarray]
    ) -> tuple[np.ndarray, WindowIndex | None]:
        """Raw input columns -> model-ready feature rows (the per-request
        half of serving; the forward half can then be coalesced across
        requests by the service's micro-batcher). Request-shaped errors
        (missing columns, short windows) surface HERE, before any batch
        a request might have joined."""
        if self.kind == "tabular":
            x = self._pipeline.transform(columns)
            if self._meta["preprocessor"].get("append_gilbert"):
                # Physics-informed artifact: raw Gilbert prediction rides
                # as the last feature column (GilbertResidualMLP contract;
                # same helper as the training pipeline).
                from tpuflow.core.gilbert import append_gilbert_column

                x = append_gilbert_column(x, columns)
            return x, None
        return self._features_windowed(columns)

    def forward_prepared(
        self, x: np.ndarray, batch_size: int = 4096
    ) -> np.ndarray:
        """Jitted forward over prepared feature rows, denormalized to raw
        target units — one output row per input row."""
        if len(x) == 0:
            return np.zeros((0,), np.float32)
        p = self._meta["preprocessor"]
        y = self._forward_batched(x, batch_size)
        return y * float(p["target_std"]) + float(p["target_mean"])

    def warmup(self, top: int = 2, max_rows: int = 4096) -> list[int]:
        """Pre-compile the ``top`` largest pow-2 forward buckets <=
        ``max_rows``, largest first, so the first requests after a cold
        load (or a post-retrain reload) don't eat an XLA compile each.

        Runs the real jitted forward on zeros (populating jit's actual
        call cache, which ``lower().compile()`` would not) and blocks
        until each compile lands. Returns the warmed bucket sizes; also
        recorded on ``self.warm_buckets`` for metrics."""
        buckets: list[int] = []
        b = _next_pow2(max(max_rows, 1))
        if b > max_rows:  # max_rows not itself pow-2: start below it
            b >>= 1
        while b >= 1 and len(buckets) < max(top, 0):
            buckets.append(b)
            b >>= 1
        tail = list(self._meta["sample_shape"][1:])
        for size in buckets:
            zeros = np.zeros([size] + tail, np.float32)
            jax.block_until_ready(self._predict_fn(self._params, zeros))
        self.warm_buckets = tuple(buckets)
        return buckets

    def predict_columns(
        self,
        columns: dict[str, np.ndarray],
        batch_size: int = 4096,
        return_index: bool = False,
    ):
        """Predict RAW-unit flow from raw input columns.

        For windowed models, ``return_index=True`` additionally returns a
        ``WindowIndex`` mapping each prediction to its well + start row.
        """
        x, index = self.prepare_columns(columns)
        y = self.forward_prepared(x, batch_size)
        if return_index:
            return y, index
        return y

    def columns_from_csv(self, path: str) -> dict[str, np.ndarray]:
        """Read a headerless CSV into raw columns — with or without the
        target column (field count selects the schema variant)."""
        with open(path, "r", encoding="utf-8") as f:
            first = f.readline()
        nfields = len(first.rstrip("\n").rstrip("\r").split(","))
        full = self.schema(with_target=True)
        serving = self.schema(with_target=False)
        if nfields == len(full.columns):
            schema = full
        elif nfields == len(serving.columns):
            schema = serving
        else:
            raise ValueError(
                f"{path}: first line has {nfields} fields; expected "
                f"{len(full.columns)} (with target "
                f"{full.target!r}) or {len(serving.columns)} (without)"
            )
        return read_csv(path, schema)

    def predict_csv(
        self, path: str, batch_size: int = 4096, return_index: bool = False
    ):
        """Predict from a headerless CSV — with or without the target column
        (field count selects the schema variant)."""
        return self.predict_columns(
            self.columns_from_csv(path),
            batch_size=batch_size,
            return_index=return_index,
        )


def predict(
    storage_path: str,
    name: str,
    data_path: str | None = None,
    columns: dict[str, np.ndarray] | None = None,
    return_index: bool = False,
):
    """One-call serving: load artifact, predict raw-unit flow."""
    pred = Predictor.load(storage_path, name)
    if data_path is not None:
        return pred.predict_csv(data_path, return_index=return_index)
    if columns is not None:
        return pred.predict_columns(columns, return_index=return_index)
    raise ValueError("pass data_path or columns")
