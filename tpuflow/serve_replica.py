"""Multi-replica serving data plane: one predictor per device, one
dispatch lane per replica, join-shortest-queue selection.

The control plane (``tpuflow/serve_async.py``) scaled admission and
coalescing; the data plane was still ONE predictor on ONE device behind
one dispatch lane per artifact — the MMLSpark lesson (PAPERS.md) is
that serving throughput past that point is a replica-placement problem.
This module is the placement: a :class:`ReplicaSet` wraps a loaded
:class:`~tpuflow.api.predict_api.Predictor` and places N clones of its
params across local devices (``parallel/placement.py`` — host-side,
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` fans one CPU
into N schedulable devices), each clone owning its OWN continuous
dispatch lane (``microbatch.py``: lane key = artifact key + replica
index), so N forwards can be in flight at once instead of one.

Placement is jax's committed-arguments semantics doing the work: each
replica's params are ``device_put`` COMMITTED to its device, so the
shared jitted forward runs wherever the params live — no per-replica
model code, no sharding, just N copies of the same artifact pinned to N
devices.

Lane selection is **join-shortest-queue** over per-lane outstanding
rows (queued + currently dispatching, ``lane_outstanding``): under load
the least-busy replica gets the next request; ties rotate so an idle
set doesn't pile onto replica 0. Every pick increments
``serve_replica_requests_total{replica=...}`` and publishes each lane's
observed depth as ``serve_replica_queue_depth_rows{replica=...}`` — the
balance is visible in ``/metrics``, not asserted.

The batcher's contracts carry over untouched: each replica is a
distinct predictor INSTANCE, so instance-grouped dispatch, stale-
scatter protection across a reload, and error scatter all hold
per-replica for free. A reload or LRU spill retires ALL of an
artifact's replica lanes (``close_lanes_for`` — the lane keys share the
artifact key as a prefix) with queued entries draining first: zero
dropped, the reload-under-replicas drill in
``tests/test_serve_replica.py``.
"""

from __future__ import annotations

import copy
import dataclasses


def clone_to_device(pred, device):
    """One replica: the same predictor with its params COMMITTED to
    ``device``. The jitted forward is shared (jit caches per placement);
    everything host-side (preprocessor, sidecar meta) is shared by
    reference — only the params move."""
    params = getattr(pred, "_params", None)
    if params is None:
        # Stub predictors (tests) carry no params; a plain copy still
        # yields the distinct INSTANCE the per-lane contracts need.
        return copy.copy(pred)
    from tpuflow.parallel.placement import place

    placed = place(params, device)
    if dataclasses.is_dataclass(pred):
        return dataclasses.replace(pred, _params=placed)
    clone = copy.copy(pred)
    clone._params = placed
    return clone


class ReplicaSet:
    """N placed replicas of one artifact, with JSQ lane selection.

    Duck-types the Predictor surface the service's request pipeline
    touches (``prepare_columns`` / ``columns_from_csv`` /
    ``forward_prepared`` / ``warmup`` / ``degraded``), so a cached
    ReplicaSet flows through ``begin_request`` → ``transform_request``
    unchanged; only the enqueue step asks it to :meth:`pick_lane`.
    """

    degraded = False  # only successful (non-fallback) loads are wrapped

    def __init__(
        self, base, key: tuple, n: int, *, devices=None, registry=None,
        clone=None,
    ):
        from tpuflow.parallel.placement import replica_devices

        self.base = base
        self.key = tuple(key)
        # Validates n against what the hardware can place (a ValueError
        # naming the device count and the host-side recipe).
        devices = replica_devices(n, devices=devices)
        clone = clone_to_device if clone is None else clone
        self.replicas = [clone(base, d) for d in devices]
        self.devices = devices
        self._clone = clone  # kept so resize() can place new replicas
        self._rr = 0  # tie-rotation cursor, so an idle set spreads
        self._requests = self._depth = None
        if registry is not None:
            self._requests = registry.counter(
                "serve_replica_requests_total",
                "requests routed to a replica lane by join-shortest-"
                "queue, by replica index",
            )
            self._depth = registry.gauge(
                "serve_replica_queue_depth_rows",
                "outstanding rows (queued + dispatching) per replica "
                "lane, as observed at the last lane selection",
            )

    def __len__(self) -> int:
        return len(self.replicas)

    def lane_keys(self) -> list[tuple]:
        """The replica lane keys: artifact key + replica index (the
        artifact key is the shared prefix ``close_lanes_for`` drains)."""
        return [self.key + (i,) for i in range(len(self.replicas))]

    def resize(self, n: int) -> list[tuple]:
        """Grow or shrink to ``n`` replicas in place — the autoscaler's
        data-plane seam. Single-writer (the controller); readers see the
        list swap atomically (one reference store), so a concurrent
        ``pick_lane`` works against either the old or the new set, never
        a torn one. Growing validates placement and clones the tail;
        shrinking drops the highest indices (their committed params are
        released with the reference) and returns the retired lane keys
        the caller must drain (``retire_lane``) — the lanes keep
        draining queued work, they just stop receiving new picks."""
        from tpuflow.parallel.placement import replica_devices

        n = int(n)
        current = self.replicas
        old = len(current)
        if n == old:
            return []
        if n > old:
            devices = replica_devices(n, devices=None)
            grown = list(current)
            grown.extend(
                self._clone(self.base, devices[i]) for i in range(old, n)
            )
            self.replicas = grown
            self.devices = devices
            return []
        if n < 1:
            raise ValueError(f"resize(n={n}): need at least one replica")
        self.replicas = current[:n]
        self.devices = self.devices[:n]
        return [self.key + (i,) for i in range(n, old)]

    def pick_lane(self, batcher) -> tuple[tuple, object]:
        """Join-shortest-queue: (lane_key, replica) of the lane with the
        fewest outstanding rows; ties rotate round-robin. All R depths
        come from ONE ``lane_stats`` snapshot (a single acquisition of
        the batcher's lock, which the lane threads contend on — this
        runs on every request's hot path); an absent/idle lane reads as
        depth 0. Publishes what it saw. ONE snapshot of the replica
        list up front: a concurrent :meth:`resize` swaps the list
        reference, and indexing a different list than we counted could
        pick a retired replica."""
        replicas = self.replicas
        n = len(replicas)
        if hasattr(batcher, "lane_stats"):
            stats = batcher.lane_stats(self.key)
            depths = []
            for i in range(n):
                s = stats.get(self.key + (i,))
                depths.append(
                    s["queued_rows"] + s["inflight_rows"] if s else 0
                )
        else:  # depth-only test doubles
            depths = [
                batcher.lane_outstanding(self.key + (i,))
                for i in range(n)
            ]
        start = self._rr
        self._rr = (self._rr + 1) % n
        best = min(
            range(n), key=lambda i: (depths[i], (i - start) % n)
        )
        if self._requests is not None:
            self._requests.inc(replica=str(best))
            for i, d in enumerate(depths):
                self._depth.set(d, replica=str(i))
        return self.key + (best,), replicas[best]

    # ---- Predictor surface the request pipeline touches ----

    def prepare_columns(self, columns):
        return self.base.prepare_columns(columns)

    def columns_from_csv(self, path: str):
        return self.base.columns_from_csv(path)

    def forward_prepared(self, x, batch_size: int = 4096):
        # The no-rows fast path (and any caller that never picked a
        # lane) answers from replica 0 — same params, same answer.
        return self.replicas[0].forward_prepared(x, batch_size)

    def predict_columns(self, columns, **kwargs):
        return self.replicas[0].predict_columns(columns, **kwargs)

    def warmup(self, top: int = 2, max_rows: int = 4096) -> list[int]:
        """Warm EVERY replica's forward buckets: each device compiles
        its own executable, so warming only the base would leave
        replicas 1..N-1 eating an XLA compile on their first dispatch.
        Returns one entry per (replica, bucket) — the honest count of
        compiles done."""
        warmed: list[int] = []
        for rep in self.replicas:
            warmed.extend(rep.warmup(top=top, max_rows=max_rows))
        return warmed
