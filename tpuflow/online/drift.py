"""Streaming data watchdog: windowed drift scoring against the serving
artifact's reference statistics.

The mold is ``obs/health.py::NumericsWatchdog`` — host-side, post-hoc,
warmup-gated, policy-light — pointed at the DATA instead of the
optimizer. The reference statistics are the ones the artifact already
carries: every serving sidecar records the feature means/stds and target
mean/std the preprocessor was fitted with at artifact-build time
(``api/predict_api.py::save_artifact_meta``), so "what the model was
trained on" needs no extra bookkeeping — a retrained-and-swapped
artifact automatically refreshes the baseline.

Scoring is strictly host-side numpy: the watchdog sits INSIDE the
streaming-window consumer loop, and a device sync per window would stall
ingest (the executable TPF010 lint contract — see
``tpuflow/analysis/linter.py``). Per window it computes:

- **feature_shift** — per-feature standardized mean shift
  ``|mean(win) - ref_mean| / ref_std`` (a z-score of the window mean's
  location against the training distribution);
- **feature_variance** — the window-variance / reference-variance ratio
  (a regime can shift its spread without moving its mean);
- **target_shift** — the same standardized shift for the label column;
- **residual_degradation** — the mean of a caller-supplied residual
  array (serving-side ``|prediction - y|``, or the Gilbert-physics
  residual when no predictor is on hand) against an EWMA of previous
  healthy windows. Anomalous windows never update the EWMA — a
  degradation must not raise its own bar — and ``warmup_windows``
  healthy windows must seed it first, so the detector never trips on
  its own baseline.

Every anomaly increments ``online_drift_events_total{kind=...}``, lands
in the forensics ring, and the per-feature scores publish as
``online_drift_score{feature=...}`` gauges regardless of whether they
trip (the dashboards want the scores BEFORE they cross the line).
``observe_window(..., raise_on_drift=True)`` raises the typed
:class:`DriftDetected`; the online controller consumes the returned
anomaly list instead.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

import numpy as np

from tpuflow.obs.forensics import record_event
from tpuflow.obs.metrics import default_registry
from tpuflow.resilience import fault_point

# Numeric column kinds in the tabular sidecar's schema vocabulary —
# the columns the fitted pipeline standardized (features.py contract).
_NUMERIC_KINDS = ("int", "float")


class DriftDetected(RuntimeError):
    """The data watchdog flagged a drifted window.

    ``window`` is the window index the anomaly landed on; ``anomalies``
    is the trail of ``{"kind", "feature"?, "score", "window"}`` dicts.
    Typed (like ``NumericsDivergence``) so callers can classify it:
    drift is a *signal* to adapt, not a failure to restart through.
    """

    def __init__(self, message: str, window: int | None = None,
                 anomalies=()):
        super().__init__(message)
        self.window = window
        self.anomalies = list(anomalies)


@dataclass
class ReferenceStats:
    """What the serving artifact was trained on: per-feature mean/std
    plus target mean/std, as recorded in the artifact sidecar at build
    time."""

    feature_names: tuple
    mean: np.ndarray
    std: np.ndarray
    target_mean: float
    target_std: float
    target: str | None = None  # label column name, when the sidecar has it


def reference_stats_from_sidecar(storage_path: str, name: str) -> ReferenceStats:
    """Read the drift baseline out of the artifact sidecar.

    Works for both artifact kinds: windowed sidecars carry explicit
    channel stats (``feature_names``/``mean``/``std``); tabular sidecars
    carry the fitted pipeline's numeric-column stats. Raises a ValueError
    naming the sidecar when the stats are absent (an artifact with no
    numeric features has nothing to score drift against).
    """
    from tpuflow.utils.paths import join_path, open_file

    path = join_path(storage_path, "meta", f"{name}.json")
    with open_file(path, "r", encoding="utf-8") as f:
        meta = json.load(f)
    p = meta.get("preprocessor") or {}
    if meta.get("kind") == "windowed":
        names = tuple(p["feature_names"])
        mean = np.asarray(p["mean"], np.float64)
        std = np.asarray(p["std"], np.float64)
        target = p.get("target")
    else:
        # Tabular sidecar: the fitted pipeline's mean/std cover the
        # ASSEMBLED feature vector — one-hot blocks first, then the
        # continuous columns in schema order (features.py::_assemble) —
        # so the continuous columns' stats are the TAIL of mean/std.
        target = p.get("target")
        names = tuple(
            n for n, k in zip(p.get("names", ()), p.get("kinds", ()))
            if k in _NUMERIC_KINDS and n != target
        )
        if p.get("mean") is None or not names:
            raise ValueError(
                f"{path}: sidecar carries no numeric feature stats "
                "(mean/std) — nothing to score drift against"
            )
        mean = np.asarray(p["mean"], np.float64)[-len(names):]
        std = np.asarray(p["std"], np.float64)[-len(names):]
    if len(names) != len(mean) or len(mean) != len(std):
        raise ValueError(
            f"{path}: sidecar stats are inconsistent — "
            f"{len(names)} feature names vs {len(mean)} means / "
            f"{len(std)} stds"
        )
    return ReferenceStats(
        feature_names=names,
        mean=mean,
        std=np.where(std < 1e-12, 1.0, std),
        target_mean=float(p.get("target_mean", 0.0)),
        target_std=float(p.get("target_std", 1.0)) or 1.0,
        target=target,
    )


def admission_score(ref: ReferenceStats, columns: dict) -> float | None:
    """One request's out-of-distribution score against the artifact's
    reference stats: the MAX standardized mean shift across the request
    columns that match reference features (the ``feature_shift`` z-score
    of :class:`DataDriftWatchdog`, collapsed to a scalar a front door
    can threshold on).

    The serving admission gate (``serve_async.py``) calls this per
    request BEFORE the request can occupy a dispatch slot — strictly
    host-side numpy, no device work (the TPF010 discipline applies to
    the admission path exactly as it does to the consumer loop).
    Returns None when no reference feature is present in ``columns``
    (nothing to score — the gate must not guess). A column carrying a
    non-finite value (json.loads admits ``NaN``) scores **inf**: the
    training data was finite, so nothing is further out of
    distribution — and because ``nan > threshold`` is False, treating
    it as anything less would let the single most malformed payload
    sail through a shed-policy gate (and a leading NaN would mask
    every later column's real shift)."""
    best: float | None = None
    for i, name in enumerate(ref.feature_names):
        if name not in columns:
            continue
        v = np.asarray(columns[name])
        if v.dtype.kind not in "fiu" or v.size == 0:
            continue
        v = v.astype(np.float64, copy=False).reshape(-1)
        if not np.isfinite(v).all():
            return float("inf")
        z = abs(float(v.mean()) - ref.mean[i]) / ref.std[i]
        if best is None or z > best:
            best = float(z)
    return best


class DataDriftWatchdog:
    """Windowed drift scoring against :class:`ReferenceStats`.

    Call :meth:`observe_window` once per streaming window with the
    window's raw feature columns (and optionally the label column and a
    residual array). Returns the window's anomaly list (empty =
    healthy). All arithmetic is host-side numpy (TPF010).
    """

    def __init__(
        self,
        ref: ReferenceStats,
        *,
        threshold: float = 4.0,
        var_factor: float = 4.0,
        residual_factor: float = 3.0,
        warmup_windows: int = 3,
        ewma_alpha: float = 0.3,
        registry=None,
        logger=None,
        model_name: str = "model",
    ):
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {threshold}")
        if var_factor < 1.0 or residual_factor < 1.0:
            raise ValueError(
                "var_factor/residual_factor are ratios and must be >= 1, "
                f"got {var_factor}/{residual_factor}"
            )
        self.ref = ref
        self.threshold = float(threshold)
        self.var_factor = float(var_factor)
        self.residual_factor = float(residual_factor)
        self.warmup_windows = int(warmup_windows)
        self.ewma_alpha = float(ewma_alpha)
        self.logger = logger
        self.model_name = model_name
        self.windows_scored = 0
        self.anomalies: list[dict] = []
        self._ewma_residual: float | None = None
        self._healthy_windows = 0
        reg = registry or default_registry()
        self._score = reg.gauge(
            "online_drift_score",
            "standardized per-feature mean shift of the last scored "
            "window vs the serving artifact's reference stats "
            "(feature label; 'target' for the label column)",
        )
        self._events = reg.counter(
            "online_drift_events_total",
            "drift anomalies flagged by the data watchdog, by kind",
        )

    @property
    def residual_baseline(self) -> float | None:
        """The healthy-residual EWMA (None until seeded) — the
        controller snapshots it before a swap to judge the NEW
        artifact's post-swap residuals against the incumbent's."""
        return self._ewma_residual

    @property
    def warmed(self) -> bool:
        return self.windows_scored >= self.warmup_windows

    # --- scoring -------------------------------------------------------

    def _feature_columns(self, columns) -> list[tuple[int, str, np.ndarray]]:
        """(ref index, name, values) for each scoreable feature. Accepts
        a column dict (names matched against the reference) or a 2D
        ``[rows, features]`` array ordered like ``ref.feature_names``."""
        if isinstance(columns, dict):
            out = []
            for i, name in enumerate(self.ref.feature_names):
                if name in columns:
                    v = np.asarray(columns[name], np.float64)
                    if v.dtype.kind in "fiu":
                        out.append((i, name, v.reshape(-1)))
            return out
        x = np.asarray(columns, np.float64)
        if x.ndim < 2 or x.shape[-1] != len(self.ref.feature_names):
            raise ValueError(
                f"window array has trailing dim {x.shape[-1:]}, expected "
                f"{len(self.ref.feature_names)} features "
                f"({self.ref.feature_names})"
            )
        flat = x.reshape(-1, x.shape[-1])
        return [
            (i, name, flat[:, i])
            for i, name in enumerate(self.ref.feature_names)
        ]

    def observe_window(
        self,
        columns,
        y=None,
        residuals=None,
        *,
        index: int | None = None,
        raise_on_drift: bool = False,
    ) -> list[dict]:
        """Score one window; returns its anomalies (empty = healthy).

        ``columns``: raw feature values (dict of columns, or an array
        ordered like the reference). ``y``: the raw label column when
        the stream carries it. ``residuals``: per-row ``|prediction -
        truth|`` (serving-side, or Gilbert-physics) for the degradation
        tracker. ``index`` is the window's reproducibility key (the
        ``online.drift`` fault site's ``at=`` match and the anomaly
        record's ``window``).
        """
        idx = self.windows_scored if index is None else int(index)
        fault_point("online.drift", index=idx)
        warmed = self.warmed
        found: list[dict] = []

        for i, name, values in self._feature_columns(columns):
            if not len(values):
                continue
            z = abs(float(values.mean()) - self.ref.mean[i]) / self.ref.std[i]
            self._score.set(z, feature=name)
            if warmed and z > self.threshold:
                found.append({
                    "kind": "feature_shift", "feature": name,
                    "score": round(float(z), 4),
                })
            ref_var = self.ref.std[i] ** 2
            vr = float(values.var()) / max(ref_var, 1e-12)
            if warmed and (
                vr > self.var_factor or vr < 1.0 / self.var_factor
            ):
                found.append({
                    "kind": "feature_variance", "feature": name,
                    "score": round(float(vr), 4),
                })

        if y is not None:
            yv = np.asarray(y, np.float64).reshape(-1)
            if len(yv):
                z = abs(float(yv.mean()) - self.ref.target_mean) \
                    / self.ref.target_std
                self._score.set(z, feature="target")
                if warmed and z > self.threshold:
                    found.append({
                        "kind": "target_shift", "feature": "target",
                        "score": round(float(z), 4),
                    })

        if residuals is not None:
            rv = np.asarray(residuals, np.float64).reshape(-1)
            if len(rv):
                mean_resid = float(np.abs(rv).mean())
                self._score.set(
                    mean_resid / max(self.ref.target_std, 1e-12),
                    feature="residual",
                )
                degraded = (
                    warmed
                    and self._healthy_windows >= self.warmup_windows
                    and self._ewma_residual is not None
                    and mean_resid > self.residual_factor
                    * max(self._ewma_residual, 1e-12)
                )
                if degraded:
                    found.append({
                        "kind": "residual_degradation", "feature": "residual",
                        "score": round(
                            mean_resid / max(self._ewma_residual, 1e-12), 4
                        ),
                    })
                else:
                    # Healthy (or still warming): seed/advance the EWMA.
                    # An anomalous window never updates it — a
                    # degradation must not raise its own bar.
                    a = self.ewma_alpha
                    self._ewma_residual = (
                        mean_resid if self._ewma_residual is None
                        else a * mean_resid + (1 - a) * self._ewma_residual
                    )

        self.windows_scored += 1
        if not found:
            self._healthy_windows += 1
            return found
        for anomaly in found:
            anomaly["window"] = idx
            self.anomalies.append(anomaly)
            self._events.inc(kind=anomaly["kind"])
            record_event("drift_anomaly", model=self.model_name, **anomaly)
            if self.logger is not None:
                self.logger.write("drift_anomaly", **anomaly)
        if raise_on_drift:
            kinds = ", ".join(
                f"{a['kind']}({a.get('feature')})={a['score']:g}"
                for a in found
            )
            raise DriftDetected(
                f"data watchdog flagged window {idx} of "
                f"{self.model_name}: {kinds}",
                window=idx,
                anomalies=self.anomalies,
            )
        return found
