"""CLI sidecar entry: ``python -m tpuflow.online spec.json``.

Runs the online learning loop (docs/online.md) against the job spec's
``data_path`` stream and serving artifact. The spec is the same JSON the
job-runner and supervisor accept (``tpuflow.serve.spec_to_config`` —
camelCase or snake_case fields); the loop's knobs come from the spec's
``online`` block and/or the ``TPUFLOW_ONLINE_*`` environment.

Typical sidecar deployment: the serving daemon runs
``python -m tpuflow.cli serve`` while this process tails the live data
feed next to it and nudges it over ``POST /artifacts/reload`` after
every promotion::

    python -m tpuflow.online spec.json --daemon-url http://127.0.0.1:8700

``--max-windows N`` bounds the pass (drills, backfills, smoke tests);
the summary JSON lands on stdout either way.
"""

from __future__ import annotations

import json
import sys


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="tpuflow.online",
        description="continuous-training sidecar: drift detection -> "
        "warm-start retrain -> zero-downtime artifact swap",
    )
    p.add_argument("spec", help="job spec JSON file (serve/supervisor format)")
    p.add_argument(
        "--max-windows", type=int, default=None, metavar="N",
        help="stop after N streaming windows (default: run the stream out)",
    )
    p.add_argument(
        "--daemon-url", default=None, metavar="URL",
        help="serving daemon(s) to POST /artifacts/reload after a swap "
        "(comma-separated; also online.daemon_url / "
        "TPUFLOW_ONLINE_DAEMON_URL)",
    )
    args = p.parse_args(argv)

    from tpuflow.serve import spec_to_config
    from tpuflow.storage import read_json

    try:
        config = spec_to_config(read_json(args.spec))
    except (OSError, ValueError, TypeError) as e:
        print(f"tpuflow.online: bad spec {args.spec!r}: {e}", file=sys.stderr)
        return 2

    from tpuflow.analysis import ensure_preflight

    try:
        ensure_preflight(config, passes=("spec",))
        from tpuflow.online.controller import run_online

        summary = run_online(
            config,
            max_windows=args.max_windows,
            daemon_url=args.daemon_url,
        )
    except (ValueError, FileNotFoundError) as e:
        # Submission-shaped errors (bad online block, missing artifact,
        # missing stream): a message, not a traceback.
        print(f"tpuflow.online: {e}", file=sys.stderr)
        return 2
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
