"""Online learning loop: streaming drift detection → warm-start retrain
→ zero-downtime artifact swap (ROADMAP item 5; docs/online.md).

The paper's system is a live well-monitoring service, not a batch
trainer — yet until this subsystem tpuflow trained once and served
forever, going *degraded* (the Gilbert fallback) rather than *adaptive*
when the world changed. This package wires four existing ingredients
into a continuous-training control loop:

- :mod:`tpuflow.online.drift` — a windowed **data watchdog** in the mold
  of ``obs/health.py::NumericsWatchdog``: reference feature/label
  statistics captured at artifact-build time (they live in the serving
  sidecar), streaming windows scored against them host-side, a
  Gilbert-residual/serving-residual degradation tracker, warmup-gated so
  the detector never trips on its own baseline. Anomalies publish
  ``online_drift_score{feature=...}`` gauges, forensics events, and the
  typed :class:`~tpuflow.online.drift.DriftDetected`.
- :mod:`tpuflow.online.controller` — :class:`OnlineTrainer`: consumes
  the bounded-memory CSV stream (``data/stream.py``), maintains a
  bounded replay of recent windows plus a held-back eval slice, and on
  drift (or a scheduled cadence) launches a warm-start retrain — resume
  from the *serving* artifact via ``train/resume.py::apply_params``
  (``TrainJobConfig.warm_start``), train on the replay, emit a candidate
  artifact — optionally under the existing supervisor so crash-loop /
  divergence classification applies.
- :mod:`tpuflow.online.swap` — promotion with a **shadow-eval gate**
  (candidate vs incumbent on the held-back slice; only a non-regressing
  candidate is promoted), atomic-rename promotion next to the serving
  checkpoint with the previous artifact retained, and **rollback** on
  post-swap regression (tracked via serving-side residuals).
- Serving integration: both daemons accept ``POST /artifacts/reload``
  and reload through the instance-grouped batcher path, so in-flight
  requests finish against the old artifact and no request is dropped.

Fault sites ``online.drift`` / ``online.retrain`` / ``online.swap`` /
``online.rollback`` make the loop drillable (docs/resilience.md).

Run: ``python -m tpuflow.online spec.json`` or
``python -m tpuflow.cli ... --online``.
"""

from __future__ import annotations

# Knob catalog: the ``TrainJobConfig.online`` block's keys, their
# defaults, and (via resolve_online) their TPUFLOW_ONLINE_* env
# spellings. Resolution order: block value > env var > default — the
# block is the job's explicit intent; env is the operator's fleet-wide
# dial. Every env read is validated at read time through the shared
# tpuflow/utils/env.py helpers (the TPUFLOW_SERVE_*/TPUFLOW_RETRY_*
# precedent).
ONLINE_DEFAULTS: dict = {
    # Streaming/scoring
    "window_rows": 256,       # rows per scored drift window
    "threshold": 4.0,         # standardized mean-shift trip point (z)
    "var_factor": 4.0,        # variance-ratio trip point (x or 1/x)
    "residual_factor": 3.0,   # residual-degradation trip point (x EWMA)
    "warmup_windows": 3,      # windows before the detector may trip
    # Replay / eval holdback
    "replay_windows": 16,     # bounded replay of recent windows
    "eval_every": 5,          # every Nth window held back for shadow eval
    "eval_windows": 4,        # bound on retained eval windows
    # Retrain policy
    "retrain_every": 0,       # scheduled cadence in windows (0 = drift-only)
    "retrain_epochs": 20,     # max_epochs of each warm-start retrain
    "min_retrain_gap": 2,     # windows between consecutive retrains
    "mode": "inprocess",      # "inprocess" | "supervised" (subprocess +
                              # crash-loop/divergence classification)
    "max_restarts": 1,        # supervised mode's restart budget
    # Promotion / rollback
    "margin": 0.05,           # shadow-eval non-regression margin (frac)
    "rollback": True,         # auto-rollback on post-swap regression
    "rollback_windows": 8,    # post-swap regression watch budget
    "daemon_url": None,       # serving daemon(s) to notify, comma-sep
}

_MODES = ("inprocess", "supervised")

# env var name per knob (daemon_url included: a sidecar deployment sets
# the fleet's daemon address once, in the environment).
_ENV_NAMES = {
    "window_rows": "TPUFLOW_ONLINE_WINDOW_ROWS",
    "threshold": "TPUFLOW_ONLINE_THRESHOLD",
    "var_factor": "TPUFLOW_ONLINE_VAR_FACTOR",
    "residual_factor": "TPUFLOW_ONLINE_RESIDUAL_FACTOR",
    "warmup_windows": "TPUFLOW_ONLINE_WARMUP",
    "replay_windows": "TPUFLOW_ONLINE_REPLAY",
    "eval_every": "TPUFLOW_ONLINE_EVAL_EVERY",
    "eval_windows": "TPUFLOW_ONLINE_EVAL_WINDOWS",
    "retrain_every": "TPUFLOW_ONLINE_RETRAIN_EVERY",
    "retrain_epochs": "TPUFLOW_ONLINE_RETRAIN_EPOCHS",
    "min_retrain_gap": "TPUFLOW_ONLINE_MIN_RETRAIN_GAP",
    "mode": "TPUFLOW_ONLINE_MODE",
    "max_restarts": "TPUFLOW_ONLINE_MAX_RESTARTS",
    "margin": "TPUFLOW_ONLINE_MARGIN",
    "rollback": "TPUFLOW_ONLINE_ROLLBACK",
    "rollback_windows": "TPUFLOW_ONLINE_ROLLBACK_WINDOWS",
    "daemon_url": "TPUFLOW_ONLINE_DAEMON_URL",
}

# (cast, minimum) per numeric knob — shared by the env reads and the
# block validation so the two paths cannot drift.
_INT_KNOBS = {
    "window_rows": 1, "warmup_windows": 0, "replay_windows": 1,
    "eval_every": 1, "eval_windows": 0, "retrain_every": 0,
    "retrain_epochs": 1, "min_retrain_gap": 0, "max_restarts": 0,
    "rollback_windows": 0,
}
_FLOAT_KNOBS = {
    "threshold": 0.0, "var_factor": 1.0, "residual_factor": 1.0,
    "margin": 0.0,
}


def _env_overrides() -> dict:
    """The TPUFLOW_ONLINE_* values present in the environment, validated
    at read time (a malformed value raises a ValueError naming the
    variable and the expected form — the shared utils/env.py contract)."""
    import os

    from tpuflow.utils.env import env_choice, env_flag, env_num

    out: dict = {}
    for knob, minimum in _INT_KNOBS.items():
        name = _ENV_NAMES[knob]
        if os.environ.get(name, "").strip():
            out[knob] = env_num(name, None, int, minimum=minimum)
    for knob, minimum in _FLOAT_KNOBS.items():
        name = _ENV_NAMES[knob]
        if os.environ.get(name, "").strip():
            out[knob] = env_num(name, None, float, minimum=minimum)
            if knob == "threshold" and out[knob] == 0:
                # The watchdog requires a strictly positive trip point;
                # env_num's minimum is inclusive.
                raise ValueError(
                    f"invalid {name}={os.environ[name]!r}: expected "
                    "a number > 0"
                )
    if os.environ.get(_ENV_NAMES["mode"], "").strip():
        out["mode"] = env_choice(_ENV_NAMES["mode"], "inprocess", _MODES)
    if os.environ.get(_ENV_NAMES["rollback"], "").strip():
        out["rollback"] = env_flag(_ENV_NAMES["rollback"], True)
    raw_url = os.environ.get(_ENV_NAMES["daemon_url"], "").strip()
    if raw_url:
        out["daemon_url"] = raw_url
    return out


def validate_online_block(block) -> list[str]:
    """Validation messages for a ``TrainJobConfig.online`` block (empty =
    valid). Never raises — the preflight spec pass turns each message
    into a Diagnostic so one submission reports every problem at once."""
    if not isinstance(block, dict):
        return [
            f"online must be a dict of knobs, got {type(block).__name__}"
        ]
    msgs = []
    unknown = sorted(set(block) - set(ONLINE_DEFAULTS))
    if unknown:
        msgs.append(
            f"unknown online knob(s) {unknown}; known: "
            f"{sorted(ONLINE_DEFAULTS)}"
        )
    for knob, minimum in _INT_KNOBS.items():
        if knob not in block:
            continue
        v = block[knob]
        if not isinstance(v, int) or isinstance(v, bool) or v < minimum:
            msgs.append(
                f"online.{knob} must be an integer >= {minimum}, "
                f"got {v!r}"
            )
    for knob, minimum in _FLOAT_KNOBS.items():
        if knob not in block:
            continue
        v = block[knob]
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v < minimum:
            msgs.append(
                f"online.{knob} must be a number >= {minimum:g}, "
                f"got {v!r}"
            )
        elif knob == "threshold" and v == 0:
            # The watchdog's trip point is strictly positive — a zero
            # threshold would flag every window as drifted.
            msgs.append(f"online.threshold must be a number > 0, got {v!r}")
    if "mode" in block and block["mode"] not in _MODES:
        msgs.append(
            f"online.mode must be one of {', '.join(_MODES)}, "
            f"got {block['mode']!r}"
        )
    if "rollback" in block and not isinstance(block["rollback"], bool):
        msgs.append(
            f"online.rollback must be a bool, got {block['rollback']!r}"
        )
    if "daemon_url" in block and block["daemon_url"] is not None \
            and not isinstance(block["daemon_url"], str):
        msgs.append(
            f"online.daemon_url must be a string URL (comma-separated "
            f"for several daemons) or null, got {block['daemon_url']!r}"
        )
    return msgs


def resolve_online(block: dict | None) -> dict:
    """The loop's effective knobs: defaults, overlaid by the validated
    TPUFLOW_ONLINE_* environment, overlaid by the job's explicit block.
    A malformed block raises ValueError with every message (callers that
    preflighted never see it)."""
    block = block or {}
    msgs = validate_online_block(block)
    if msgs:
        raise ValueError("invalid online block: " + "; ".join(msgs))
    knobs = dict(ONLINE_DEFAULTS)
    knobs.update(_env_overrides())
    knobs.update(block)
    return knobs


def __getattr__(name: str):
    # Lazy re-exports: the spec preflight imports validate_online_block
    # without paying for jax/predictor imports in the controller.
    if name in ("DriftDetected", "DataDriftWatchdog", "ReferenceStats",
                "reference_stats_from_sidecar", "admission_score"):
        from tpuflow.online import drift

        return getattr(drift, name)
    if name in ("OnlineTrainer", "run_online"):
        from tpuflow.online import controller

        return getattr(controller, name)
    if name in ("shadow_eval", "promote_candidate", "rollback_artifact",
                "notify_daemons", "serving_residuals"):
        from tpuflow.online import swap

        return getattr(swap, name)
    raise AttributeError(f"module 'tpuflow.online' has no attribute {name!r}")
