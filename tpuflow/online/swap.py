"""Artifact promotion with a shadow-eval gate, retained-previous
rollback, and daemon notification.

An artifact is two on-disk pieces (api/predict_api.py): the Orbax
best-params tree under ``{storage}/models/{name}`` and the JSON sidecar
at ``{storage}/meta/{name}.json``. Promotion swaps BOTH from a candidate
storage root into the serving root:

1. the incumbent is moved aside to ``{storage}/online/prev`` (rename —
   same filesystem, no copy) and retained as the rollback target;
2. the candidate's checkpoint tree is renamed into place;
3. the sidecar is rewritten atomically (tmp + ``os.replace``).

The window between steps 1 and 2 is two renames wide. It is invisible to
serving because the daemons never read the disk per request: a loaded
``Predictor`` pins the incumbent's params in memory, the batchers group
by predictor INSTANCE (a swap mid-flight never scatters another
generation's predictions — the docs/serving.md contract), and a reload
happens only when the loop POSTs ``/artifacts/reload`` AFTER the swap
completed. A daemon that does race a load into the gap degrades to the
Gilbert fallback for one TTL rather than erroring — the documented
degraded-serving behavior, not a new failure mode.

``promote_candidate`` fires the ``online.swap`` fault site BEFORE any
file moves, so an injected fault rejects the candidate with the serving
artifact untouched; ``rollback_artifact`` mirrors it with
``online.rollback``. Local moves go through the storage seam's
local-move helpers (``tpuflow/storage/local.py`` — the one audited home
for rename-as-publish); storage roots that resolve through
``tpuflow.storage`` (``fake://`` today, ``gs://`` next) dispatch to the
store-native path instead: **pointer-indirected promotion**
(``tpuflow/storage/artifacts.py``) with zero renames, rollback as a
pointer flip back to the retained previous generation (docs/storage.md).
"""

from __future__ import annotations

import json
import os

import numpy as np

from tpuflow.obs.forensics import record_event
from tpuflow.obs.metrics import default_registry
from tpuflow.resilience import fault_point
from tpuflow.storage import (
    is_store_uri,
    join_key,
    read_json,
    resolve_store,
)
from tpuflow.storage.local import (
    move_tree,
    remove_file,
    remove_tree,
)
from tpuflow.utils.paths import atomic_write_json, is_uri, join_path


def _artifact_paths(storage: str, name: str) -> tuple[str, str]:
    return (
        join_path(storage, "models", name),
        join_path(storage, "meta", f"{name}.json"),
    )


def _require_local(*paths: str) -> None:
    remote = [p for p in paths if is_uri(p) and not is_store_uri(p)]
    if remote:
        raise ValueError(
            f"online artifact swap needs local storage paths or "
            f"store-resolvable URIs (tpuflow.storage); got URI(s) "
            f"{remote} — object-store promotion rides the pointer "
            "indirection of tpuflow/storage/artifacts.py (docs/storage.md)"
        )


def _collect_artifact_objects(storage: str, name: str) -> dict[str, bytes]:
    """Every object of one store-resident artifact (checkpoint tree +
    sidecar), keyed by its serving-layout-relative name."""
    store, prefix = resolve_store(storage)
    files: dict[str, bytes] = {}
    ckpt_prefix = join_key(prefix, "models", name) + "/"
    for key in store.list(ckpt_prefix):
        files[f"models/{name}/" + key[len(ckpt_prefix):]] = store.get(key)
    meta_key = join_key(prefix, "meta", f"{name}.json")
    if not files or not store.exists(meta_key):
        raise FileNotFoundError(
            f"artifact at {storage!r} is incomplete: needs a "
            f"models/{name}/ tree and meta/{name}.json"
        )
    files[f"meta/{name}.json"] = store.get(meta_key)
    return files


def _require_artifact(ckpt: str, meta: str, what: str) -> None:
    missing = [p for p in (ckpt, meta) if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(
            f"{what} artifact is incomplete: missing {missing}"
        )


# --- shadow evaluation -------------------------------------------------


def serving_residuals(pred, columns: dict, target: str) -> np.ndarray:
    """Per-row ``|prediction - truth|`` of one predictor on raw columns
    — THE serving-side residual used by both the shadow-eval gate and
    the post-swap regression tracker.

    Tabular predictors answer row-for-row. Windowed predictors answer
    per WINDOW; each window's prediction (its final step, for
    teacher-forced families) is compared against the truth at the
    window's final source row via the returned ``WindowIndex``.
    """
    y = np.asarray(columns[target], np.float64).reshape(-1)
    feats = {k: v for k, v in columns.items() if k != target}
    if pred.kind == "tabular":
        out = np.asarray(pred.predict_columns(feats), np.float64)
        out = out.reshape(len(out), -1)[:, -1]
        return np.abs(out - y[: len(out)])
    out, idx = pred.predict_columns(feats, return_index=True)
    out = np.asarray(out, np.float64)
    if out.ndim > 1:  # teacher-forced: [windows, steps] -> final step
        out = out[:, -1]
    window = int(pred._meta["preprocessor"]["window"])
    truth = y[np.asarray(idx.starts) + window - 1]
    return np.abs(out - truth)


def artifact_mae(storage: str, name: str, columns: dict, target: str) -> float:
    """One artifact's MAE on raw labeled columns (fresh load, no cache)."""
    from tpuflow.api.predict_api import Predictor

    pred = Predictor.load(storage, name)
    return float(serving_residuals(pred, columns, target).mean())


def shadow_eval(
    incumbent_storage: str,
    candidate_storage: str,
    name: str,
    columns: dict,
    target: str,
    margin: float = 0.05,
) -> dict:
    """Score candidate vs incumbent on the held-back eval slice.

    ``accept`` iff the candidate's MAE is within ``(1 + margin)`` of the
    incumbent's — a candidate must NOT regress to be promoted; it does
    not have to win (the usual reason to retrain is that the incumbent
    is stale, so it usually wins anyway).
    """
    inc = artifact_mae(incumbent_storage, name, columns, target)
    cand = artifact_mae(candidate_storage, name, columns, target)
    return {
        "incumbent_mae": inc,
        "candidate_mae": cand,
        "margin": float(margin),
        "rows": int(len(np.asarray(columns[target]).reshape(-1))),
        "accept": bool(cand <= inc * (1.0 + margin)),
    }


# --- promotion / rollback ----------------------------------------------


def promote_candidate(
    storage: str,
    name: str,
    candidate_storage: str,
    *,
    registry=None,
) -> dict:
    """Atomically promote a candidate artifact into the serving path,
    retaining the incumbent under ``{storage}/online/prev`` for
    rollback. See the module docstring for the swap discipline."""
    fault_point("online.swap")
    _require_local(storage, candidate_storage)
    if is_store_uri(storage):
        return _promote_candidate_store(
            storage, name, candidate_storage, registry=registry
        )
    ckpt, meta = _artifact_paths(storage, name)
    cand_ckpt, cand_meta = _artifact_paths(candidate_storage, name)
    _require_artifact(cand_ckpt, cand_meta, "candidate")
    _require_artifact(ckpt, meta, "incumbent (serving)")

    prev_root = join_path(storage, "online", "prev")
    prev_ckpt, prev_meta = _artifact_paths(prev_root, name)
    # One retained generation: clear the older prev, then move the
    # incumbent aside (seam-routed renames — same filesystem).
    remove_tree(prev_root)
    move_tree(ckpt, prev_ckpt)
    move_tree(meta, prev_meta)
    # Candidate in: checkpoint tree by seam move, sidecar atomically.
    move_tree(cand_ckpt, ckpt)
    atomic_write_json(meta, read_json(cand_meta))
    (registry or default_registry()).counter(
        "online_swaps_total",
        "candidate artifacts promoted into the serving path",
    ).inc()
    rec = {
        "promoted": True,
        "model": name,
        "storage_path": storage,
        "candidate": candidate_storage,
        "prev_retained": prev_root,
    }
    record_event("artifact_swap", **rec)
    return rec


def _promote_candidate_store(
    storage: str, name: str, candidate_storage: str, *, registry=None
) -> dict:
    """The store-native swap: upload the candidate's objects as the
    next generation under ``{storage}/online/{name}/`` and flip the
    CURRENT pointer at its manifest — zero renames, and the incumbent
    generation is retained by NOT being deleted (the rollback target).
    """
    from tpuflow.storage import artifacts

    store, prefix = resolve_store(storage)
    files = _collect_artifact_objects(candidate_storage, name)
    doc = artifacts.promote_files(
        store, files,
        prefix=join_key(prefix, "online", name),
        meta={"model": name, "candidate": candidate_storage},
    )
    (registry or default_registry()).counter(
        "online_swaps_total",
        "candidate artifacts promoted into the serving path",
    ).inc()
    rec = {
        "promoted": True,
        "model": name,
        "storage_path": storage,
        "candidate": candidate_storage,
        "generation": int(doc["generation"]),
        "pointer": doc["target"],
    }
    record_event("artifact_swap", **rec)
    return rec


def rollback_artifact(storage: str, name: str, *, registry=None) -> dict:
    """Restore the retained previous artifact into the serving path; the
    regressed artifact is kept under ``{storage}/online/rejected`` for
    forensics (locally — store roots retain every generation and roll
    back by pointer flip). Raises FileNotFoundError when no previous
    artifact was retained (nothing to roll back to)."""
    fault_point("online.rollback")
    _require_local(storage)
    if is_store_uri(storage):
        return _rollback_store(storage, name, registry=registry)
    ckpt, meta = _artifact_paths(storage, name)
    prev_root = join_path(storage, "online", "prev")
    prev_ckpt, prev_meta = _artifact_paths(prev_root, name)
    _require_artifact(
        prev_ckpt, prev_meta, "retained previous (rollback target)"
    )

    rejected_root = join_path(storage, "online", "rejected")
    rej_ckpt, rej_meta = _artifact_paths(rejected_root, name)
    remove_tree(rejected_root)
    if os.path.exists(ckpt):
        move_tree(ckpt, rej_ckpt)
    if os.path.exists(meta):
        move_tree(meta, rej_meta)
    move_tree(prev_ckpt, ckpt)
    atomic_write_json(meta, read_json(prev_meta))
    remove_file(prev_meta)
    (registry or default_registry()).counter(
        "online_rollbacks_total",
        "post-swap regressions rolled back to the retained artifact",
    ).inc()
    rec = {
        "rolled_back": True,
        "model": name,
        "storage_path": storage,
        "rejected_retained": rejected_root,
    }
    record_event("artifact_rollback", **rec)
    return rec


def _rollback_store(storage: str, name: str, *, registry=None) -> dict:
    """Store-native rollback: one pointer flip back to the previous
    generation (never deleted — that IS the retention policy when
    rename does not exist). The regressed generation's objects stay put
    for forensics, named by the pointer doc's ``rolled_back_from``."""
    from tpuflow.storage import artifacts

    store, prefix = resolve_store(storage)
    doc = artifacts.rollback(
        store, prefix=join_key(prefix, "online", name)
    )
    (registry or default_registry()).counter(
        "online_rollbacks_total",
        "post-swap regressions rolled back to the retained artifact",
    ).inc()
    rec = {
        "rolled_back": True,
        "model": name,
        "storage_path": storage,
        "generation": int(doc["generation"]),
        "rejected_retained": doc["meta"].get("rolled_back_from"),
    }
    record_event("artifact_rollback", **rec)
    return rec


# --- daemon notification -----------------------------------------------


def notify_daemons(
    daemon_url: str | None, storage: str, name: str, timeout: float = 5.0
) -> list[dict]:
    """POST ``/artifacts/reload`` to each comma-separated daemon URL so
    a running daemon drops its cached predictor and reloads the swapped
    artifact on the next request (in-flight requests finish against the
    old instance — the instance-grouped batcher contract). Best-effort
    by design: the swap already landed on disk, and a daemon that
    missed the nudge picks the new artifact up at its next cold load /
    restart. Returns one ``{"url", "ok", ...}`` record per daemon."""
    import urllib.request

    from tpuflow.obs.tracing import current_trace_id

    results = []
    # The bound lifecycle trace rides the nudge as X-Trace-Id: the
    # daemon stamps its reload record with it, closing the drift ->
    # retrain -> swap -> reload chain across the process boundary.
    trace = current_trace_id()
    for url in [u.strip() for u in (daemon_url or "").split(",") if u.strip()]:
        body = json.dumps(
            {"storagePath": storage, "model": name}
        ).encode()
        headers = {"Content-Type": "application/json"}
        if trace is not None:
            headers["X-Trace-Id"] = trace
        req = urllib.request.Request(
            url.rstrip("/") + "/artifacts/reload",
            data=body,
            headers=headers,
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                results.append({
                    "url": url, "ok": resp.status == 200,
                    "status": resp.status,
                })
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            results.append({
                "url": url, "ok": False,
                "error": f"{type(e).__name__}: {e}",
            })
    return results
