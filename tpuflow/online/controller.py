"""The online control loop: stream → score → replay → retrain → swap.

:class:`OnlineTrainer` closes the loop the seed system never had
(ROADMAP item 5): it consumes the bounded-memory CSV stream
(``data/stream.py::stream_csv_columns`` — the same chunked ingest the
streaming trainer rides), scores every window against the serving
artifact's reference stats (``online/drift.py``), and keeps two bounded
buffers:

- a **replay window** of the most recent raw chunks (the retrain
  corpus: the world as it looks NOW), and
- a held-back **eval slice** (every ``eval_every``-th chunk — excluded
  from replay so the shadow-eval gate never scores a candidate on its
  own training data).

On drift (or a scheduled ``retrain_every`` cadence) it launches a
warm-start retrain: the replay is spilled to a headerless CSV in the
job's schema order, and the job's own ``train()`` runs against it with
``warm_start`` pointed at the SERVING artifact — so the candidate
resumes from the weights the fleet is answering with, via
``train/resume.py::apply_params``, and inherits every production
guardrail (preflight, numerics watchdog, forensics). ``mode:
"supervised"`` runs the retrain under ``train/supervisor.py::supervise``
instead, so crash-loop and divergence classification apply to the
continuous loop exactly as they do to batch jobs; ``"inprocess"`` (the
default, and the drills' mode — the elastic runner's precedent) calls
``train()`` directly, where the numerics watchdog's typed
``NumericsDivergence`` still classifies a diverging retrain.

A finished candidate faces the **shadow-eval gate** (``online/swap.py``)
against the incumbent on the held-back slice; only a non-regressing
candidate is promoted (atomic renames, previous artifact retained), the
serving daemons are nudged over ``POST /artifacts/reload``, and for the
next ``rollback_windows`` windows the loop watches the NEW artifact's
serving-side residuals against the incumbent's pre-swap baseline — a
post-swap regression triggers automatic rollback to the retained
artifact. A failed or rejected retrain is counted, recorded, and
survived: a continuous loop must outlive one bad candidate.

Fault sites: ``online.retrain`` (indexed by retrain number) at launch,
``online.drift`` per scored window (in the watchdog), ``online.swap`` /
``online.rollback`` in the swap module.
"""

from __future__ import annotations

import csv
import dataclasses
import os
import threading
import time
from collections import deque

import numpy as np

from tpuflow.online import resolve_online
from tpuflow.online.drift import (
    DataDriftWatchdog,
    reference_stats_from_sidecar,
)
from tpuflow.online.swap import (
    _require_local,
    notify_daemons,
    promote_candidate,
    rollback_artifact,
    serving_residuals,
    shadow_eval,
)
from tpuflow.obs.forensics import record_event
from tpuflow.obs.metrics import default_registry
from tpuflow.obs.tracing import use_trace
from tpuflow.resilience import fault_point
from tpuflow.storage.local import remove_tree
from tpuflow.utils.paths import join_path

# Drift kinds that justify a retrain. feature_variance alone is advisory
# (a noisy sensor widens without the relationship moving); the shift and
# degradation kinds mean the model is answering a different world.
_RETRAIN_KINDS = frozenset(
    {"feature_shift", "target_shift", "residual_degradation"}
)


class OnlineTrainer:
    """One continuous-training loop for one serving artifact.

    ``config`` is the job's :class:`~tpuflow.api.config.TrainJobConfig`
    — ``storage_path`` anchors the serving artifact, ``data_path`` is
    the stream, and ``config.online`` carries the loop knobs
    (``tpuflow.online.ONLINE_DEFAULTS``; every knob also reads a
    ``TPUFLOW_ONLINE_*`` env spelling). ``source`` (tests) overrides the
    stream with any iterator of column-dict chunks; ``notify`` (tests)
    overrides daemon notification with a callable ``(storage, model)``.
    """

    def __init__(
        self, config, *, source=None, registry=None, notify=None,
        trail_path="auto",
    ):
        if not config.storage_path:
            raise ValueError(
                "online training needs storage_path (the serving "
                "artifact is the loop's anchor — warm starts resume "
                "from it, swaps promote into it)"
            )
        if source is None and not config.data_path:
            raise ValueError(
                "online training needs data_path (the stream to score "
                "and retrain on)"
            )
        # Local storage only, enforced AT THE DOOR: promote_candidate
        # would reject a gs:// path anyway, but only after a full
        # retrain — and the replay spill would mkdir a literal local
        # './gs:/...' tree on the way there.
        _require_local(config.storage_path)
        self.config = config
        self.knobs = resolve_online(config.online)
        self.storage = config.storage_path
        self.model = config.model
        self._source = source
        self._notify = notify
        self.registry = registry or default_registry()
        # The loop's on-disk trail (its fleet-timeline lane): drift
        # anomalies, retrain launches, swaps, and rollbacks — each
        # stamped with the triggering window's trace id — appended as
        # JSONL under {storage}/online/, where `python -m tpuflow.obs
        # fleet` finds it next to the workers' and daemons' trails.
        # "auto" = the default path; None disables.
        self._trail = None
        if trail_path is not None:
            from tpuflow.utils.logging import MetricsLogger

            if trail_path == "auto":
                trail_path = os.path.join(
                    self.storage, "online", "metrics.jsonl"
                )
            # MetricsLogger's open_file creates parent dirs itself (and
            # handles URI paths) — no makedirs here.
            self._trail = MetricsLogger(trail_path)

        from tpuflow.data.schema import Schema
        from tpuflow.data.synthetic import (
            SYNTHETIC_COLUMN_NAMES,
            SYNTHETIC_COLUMN_TYPES,
            SYNTHETIC_TARGET,
        )

        self.schema = Schema.from_cli(
            config.column_names or SYNTHETIC_COLUMN_NAMES,
            config.column_types or SYNTHETIC_COLUMN_TYPES,
            config.target or SYNTHETIC_TARGET,
        )
        self.target = self.schema.target
        # The serving artifact's reference stats ARE the drift baseline
        # (captured at artifact build time, stored in the sidecar) —
        # a missing artifact fails here, at the door.
        self.ref = reference_stats_from_sidecar(self.storage, self.model)
        self.watchdog = self._new_watchdog()

        self.replay: deque = deque(maxlen=int(self.knobs["replay_windows"]))
        self.eval_chunks: deque = deque(
            maxlen=max(int(self.knobs["eval_windows"]), 1)
        )
        self._predictor = None
        self.windows_seen = 0
        self.anomaly_count = 0
        self.retrains = 0
        self.swaps = 0
        self.rollbacks = 0
        self.rejected = 0
        self.failures: list[dict] = []
        # Cooperative shutdown handle for a thread-hosted loop (the
        # runtime supervisor's online service): request_stop() ends the
        # run at the next window boundary — mid-window work (a retrain,
        # a swap) always completes, so a drain never strands a
        # half-promoted candidate.
        self._stop = threading.Event()
        self._last_retrain_window = None
        # Post-swap regression watch: windows remaining and the
        # incumbent's healthy-residual baseline snapshotted at swap time.
        self._watch_left = 0
        self._resid_baseline: float | None = None

        self._counters = {
            name: self.registry.counter(f"online_{name}_total", help)
            for name, help in (
                ("windows", "streaming windows consumed by the loop"),
                ("retrains", "warm-start retrains launched"),
                ("swaps_notified", "daemon reload nudges sent"),
                ("candidates_rejected",
                 "candidates rejected (shadow-eval gate, retrain "
                 "failure, or injected fault)"),
            )
        }
        self._replay_gauge = self.registry.gauge(
            "online_replay_rows", "rows currently held in the replay window"
        )

    # --- plumbing ------------------------------------------------------

    def _new_watchdog(self) -> DataDriftWatchdog:
        return DataDriftWatchdog(
            self.ref,
            threshold=self.knobs["threshold"],
            var_factor=self.knobs["var_factor"],
            residual_factor=self.knobs["residual_factor"],
            warmup_windows=self.knobs["warmup_windows"],
            registry=self.registry,
            model_name=self.model,
            logger=self._trail,
        )

    def _event(self, name: str, **fields) -> None:
        """One lifecycle event: the forensics ring always (trace-stamped
        there), mirrored to the on-disk trail when one is configured."""
        rec = record_event(name, **fields)
        if self._trail is not None:
            self._trail.write(
                name,
                **{k: v for k, v in rec.items() if k not in ("event", "time")},
            )

    def _chunks(self):
        if self._source is not None:
            return self._source
        from tpuflow.data.stream import stream_csv_columns

        return stream_csv_columns(
            self.config.data_path, self.schema,
            chunk_rows=int(self.knobs["window_rows"]),
        )

    def _serving_predictor(self):
        """The CURRENT serving artifact, loaded once per generation —
        dropped on every swap/rollback exactly like the daemons drop
        their cache on /artifacts/reload."""
        if self._predictor is None:
            from tpuflow.api.predict_api import Predictor

            self._predictor = Predictor.load(self.storage, self.model)
        return self._predictor

    def _reload_generation(self) -> None:
        """Adopt a new serving generation: fresh predictor, fresh
        reference stats from the new sidecar, fresh (warmup-gated)
        watchdog — the new baseline never inherits the old regime's
        EWMAs, so the detector cannot trip on its own swap."""
        self._predictor = None
        self.ref = reference_stats_from_sidecar(self.storage, self.model)
        self.watchdog = self._new_watchdog()

    def _residuals(self, columns) -> np.ndarray | None:
        """Serving-side residuals of the current artifact on one chunk —
        best-effort: drift scoring must survive a mid-swap predictor
        load failure (degraded serving is the daemons' answer; skipping
        one residual window is the loop's)."""
        if self.target not in columns:
            return None
        try:
            pred = self._serving_predictor()
            return serving_residuals(pred, dict(columns), self.target)
        except Exception as e:  # noqa: BLE001 — scoring must outlive loads
            self._event(
                "online_residuals_skipped",
                error=f"{type(e).__name__}: {e}",
            )
            return None

    def _replay_rows(self) -> int:
        return sum(
            len(next(iter(c.values()))) for c in self.replay
        ) if self.replay else 0

    # --- the loop ------------------------------------------------------

    def run(self, max_windows: int | None = None) -> dict:
        """Consume the stream (bounded by ``max_windows`` when set);
        returns the loop summary. One pass over a finite file is a
        drill/backfill; a sidecar deployment points ``source`` at a
        growing log and never returns."""
        eval_every = max(int(self.knobs["eval_every"]), 1)
        retrain_every = int(self.knobs["retrain_every"])
        min_gap = int(self.knobs["min_retrain_gap"])
        for idx, columns in enumerate(self._chunks()):
            if self._stop.is_set():
                break
            if max_windows is not None and idx >= max_windows:
                break
            # ONE trace per window lifecycle: the drift anomalies this
            # window raises, the retrain they trigger, the shadow-eval
            # verdict, the swap, and the daemon reload nudge all carry
            # the same trace id — so a regime shift reads as one
            # causally-linked trail across every process it touched
            # (the retrain inherits the bound trace through train()/
            # supervise(); the reload carries it as X-Trace-Id).
            with use_trace():
                self._counters["windows"].inc()
                self.windows_seen += 1
                y = columns.get(self.target)
                residuals = self._residuals(columns)
                anomalies = self.watchdog.observe_window(
                    columns, y=y, residuals=residuals, index=idx
                )
                # Loop-level tallies: the watchdog is replaced on every
                # generation change (fresh baseline), so ITS counts
                # reset.
                self.anomaly_count += len(anomalies)

                if self._maybe_rollback(idx, residuals):
                    continue  # this window judged the old swap
                held_back = idx % eval_every == 0
                if held_back:
                    self.eval_chunks.append(columns)
                else:
                    self.replay.append(columns)
                self._replay_gauge.set(float(self._replay_rows()))

                drifted = any(
                    a["kind"] in _RETRAIN_KINDS for a in anomalies
                )
                scheduled = retrain_every > 0 and idx > 0 \
                    and idx % retrain_every == 0
                gap_ok = (
                    self._last_retrain_window is None
                    or idx - self._last_retrain_window >= min_gap
                )
                if (drifted or scheduled) and gap_ok and self.replay:
                    self._retrain_and_swap(idx, reason=(
                        "drift" if drifted else "scheduled"
                    ))
        return self.summary()

    def request_stop(self) -> None:
        """Ask a running loop to stop at its next window boundary —
        thread-safe, idempotent; ``run()`` then returns its summary
        normally. The never-returning sidecar deployment's only clean
        exit path."""
        self._stop.set()

    def summary(self) -> dict:
        return {
            "model": self.model,
            "storage_path": self.storage,
            "windows": self.windows_seen,
            "anomalies": self.anomaly_count,
            "retrains": self.retrains,
            "swaps": self.swaps,
            "rollbacks": self.rollbacks,
            "candidates_rejected": self.rejected,
            "failures": list(self.failures),
        }

    # --- rollback watch ------------------------------------------------

    def arm_rollback_watch(self, baseline: float | None) -> None:
        """Start (or re-start) the post-swap regression watch against a
        healthy-residual ``baseline`` — called internally after every
        promotion; callable by an operator after an out-of-band swap."""
        if not self.knobs["rollback"] or baseline is None:
            self._watch_left = 0
            self._resid_baseline = None
            return
        self._watch_left = int(self.knobs["rollback_windows"])
        self._resid_baseline = float(baseline)

    def _maybe_rollback(self, idx: int, residuals) -> bool:
        """Post-swap regression check: within the watch budget, a window
        whose mean serving residual exceeds ``residual_factor`` x the
        pre-swap healthy baseline rolls the swap back."""
        if self._watch_left <= 0 or self._resid_baseline is None:
            return False
        self._watch_left -= 1
        if residuals is None or not len(residuals):
            return False
        mean_resid = float(np.abs(np.asarray(residuals)).mean())
        factor = float(self.knobs["residual_factor"])
        if mean_resid <= factor * max(self._resid_baseline, 1e-12):
            return False
        try:
            rollback_artifact(
                self.storage, self.model, registry=self.registry
            )
        except Exception as e:  # noqa: BLE001 — the loop must survive
            self.failures.append({
                "window": idx, "stage": "rollback",
                "error": f"{type(e).__name__}: {e}",
            })
            self._event(
                "online_rollback_failed", window=idx,
                error=f"{type(e).__name__}: {e}",
            )
            self._watch_left = 0
            return False
        self.rollbacks += 1
        self._event(
            "online_rollback", window=idx, mean_residual=mean_resid,
            baseline=self._resid_baseline, factor=factor,
        )
        self._watch_left = 0
        self._resid_baseline = None
        self._notify_swap()
        self._reload_generation()
        return True

    # --- retrain → gate → swap -----------------------------------------

    def _retrain_and_swap(self, idx: int, reason: str) -> None:
        n = self.retrains + 1
        try:
            fault_point("online.retrain", index=n)
            candidate = self._train_candidate(idx, n)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # noqa: BLE001 — one bad retrain is survivable
            self.rejected += 1
            self._counters["candidates_rejected"].inc()
            self.failures.append({
                "window": idx, "stage": "retrain",
                "error": f"{type(e).__name__}: {e}",
            })
            self._event(
                "online_retrain_failed", window=idx, retrain=n,
                reason=reason, error=f"{type(e).__name__}: {e}",
            )
            self._last_retrain_window = idx
            return
        self.retrains = n
        self._counters["retrains"].inc()
        self._last_retrain_window = idx

        gate = None
        try:
            if self.eval_chunks:
                ev = _merge_chunks(list(self.eval_chunks))
                gate = shadow_eval(
                    self.storage, candidate, self.model, ev, self.target,
                    margin=float(self.knobs["margin"]),
                )
            if gate is None or not gate["accept"]:
                self.rejected += 1
                self._counters["candidates_rejected"].inc()
                self._event(
                    "online_candidate_rejected", window=idx, retrain=n,
                    reason=(
                        "no held-back eval slice" if gate is None
                        else "shadow-eval regression"
                    ),
                    **(gate or {}),
                )
                return
            baseline = self.watchdog.residual_baseline
            promote_candidate(
                self.storage, self.model, candidate,
                registry=self.registry,
            )
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # noqa: BLE001 — incl. injected online.swap
            self.rejected += 1
            self._counters["candidates_rejected"].inc()
            self.failures.append({
                "window": idx, "stage": "swap",
                "error": f"{type(e).__name__}: {e}",
            })
            self._event(
                "online_swap_failed", window=idx, retrain=n,
                error=f"{type(e).__name__}: {e}",
            )
            return
        self.swaps += 1
        self._event(
            "online_swap", window=idx, retrain=n, reason=reason, **gate
        )
        self._notify_swap()
        self.arm_rollback_watch(baseline)
        self._reload_generation()

    def _notify_swap(self) -> None:
        if self._notify is not None:
            self._notify(self.storage, self.model)
            self._counters["swaps_notified"].inc()
            return
        url = self.knobs.get("daemon_url")
        if url:
            for res in notify_daemons(url, self.storage, self.model):
                # Count only nudges that LANDED: the metric exists so a
                # dashboard can tell "swaps happen but no daemon hears
                # about them" from healthy operation.
                if res.get("ok"):
                    self._counters["swaps_notified"].inc()
                self._event("online_daemon_notified", **res)

    def _train_candidate(self, idx: int, n: int) -> str:
        """Spill the replay to CSV and train the candidate artifact —
        warm-started from the serving artifact — under
        ``{storage}/online/candidate``. Returns the candidate storage
        root."""
        online_root = join_path(self.storage, "online")
        replay_csv = os.path.join(online_root, f"replay-{n}.csv")
        self._spill_replay(replay_csv)
        candidate = join_path(online_root, "candidate")
        remove_tree(candidate)
        os.makedirs(candidate, exist_ok=True)

        supervised = self.knobs["mode"] == "supervised"
        cand_config = dataclasses.replace(
            self.config,
            data_path=replay_csv,
            storage_path=candidate,
            warm_start=self.storage,
            max_epochs=int(self.knobs["retrain_epochs"]),
            resume=False,
            stream=False,
            online=None,
            verbose=False,
            faults=[],
            save_every=1 if supervised else 0,
            progress_path=None,
        )
        self._event(
            "online_retrain", window=idx, retrain=n,
            replay_rows=self._replay_rows(), mode=self.knobs["mode"],
        )
        t0 = time.monotonic()
        if supervised:
            from tpuflow.train.supervisor import supervise

            # The existing supervisor owns the child: restart backoff,
            # crash-loop classification, terminal NumericsDivergence —
            # the continuous loop gets batch training's whole failure
            # taxonomy for free.
            supervise(
                dataclasses.asdict(cand_config),
                max_restarts=int(self.knobs["max_restarts"]),
                verbose=False,
            )
        else:
            from tpuflow.api import train

            train(cand_config)
        self._event(
            "online_retrain_done", window=idx, retrain=n,
            seconds=round(time.monotonic() - t0, 3),
        )
        try:
            os.remove(replay_csv)
        except OSError:
            pass
        return candidate

    def _spill_replay(self, path: str) -> None:
        """The replay window as a headerless CSV in schema column order
        — exactly the on-disk shape ``train()``'s ingest reads."""
        names = [c.name for c in self.schema.columns]
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8", newline="") as f:
            writer = csv.writer(f)
            for chunk in self.replay:
                missing = [n for n in names if n not in chunk]
                if missing:
                    raise ValueError(
                        f"replay chunk is missing schema column(s) "
                        f"{missing} — cannot spill a retrain corpus"
                    )
                cols = [np.asarray(chunk[n]) for n in names]
                for row in zip(*cols):
                    writer.writerow([_cell(v) for v in row])


def _cell(value) -> str:
    """One CSV cell: floats in full precision, everything else str()."""
    if isinstance(value, (np.floating, float)):
        return repr(float(value))
    return str(value)


def _merge_chunks(chunks: list[dict]) -> dict:
    keys = chunks[0].keys()
    return {
        k: np.concatenate([np.asarray(c[k]) for c in chunks])
        for k in keys
    }


def run_online(
    config,
    *,
    max_windows: int | None = None,
    daemon_url: str | None = None,
    registry=None,
) -> dict:
    """One-call entry: build the trainer and run the loop. ``daemon_url``
    overrides the knob/env spelling (the CLI's ``--online-daemon``)."""
    if daemon_url:
        online = dict(config.online or {})
        online["daemon_url"] = daemon_url
        config = dataclasses.replace(config, online=online)
    trainer = OnlineTrainer(config, registry=registry)
    return trainer.run(max_windows=max_windows)
