"""CLI — the reference's job-submission contract, preserved and fixed.

Usage (reference cnn.py:2 contract, plus the data path its argv bug lost):

    python -m tpuflow.cli columnNames columnTypes targetColumn storagePath \
        [--data PATH] [--model NAME] [--epochs N] ...

Positional args are the reference's exact four: comma-separated column
names, comma-separated types (int|float|anything-else=categorical), the
target column, and the artifact storage path (reference cnn.py:41-44).
With no positional args, the synthetic well schema is used end-to-end.

Daemon mode: ``python -m tpuflow.cli serve [...]`` launches the async
serving control plane (``tpuflow/serve_async.py`` — admission control,
continuous batching, deadlines, ``--replicas`` for the multi-replica
data plane, ``--drift-admission`` for the drift gate, and
``--autoscale`` for the SLO-driven autoscaler
(``tpuflow/serve_autoscale.py``, knobs via
``TPUFLOW_SERVE_AUTOSCALE_*``); docs/serving.md) with the remaining
args; ``serve --threaded``
launches the legacy threaded front end (``tpuflow/serve.py``) instead.
The subcommand is intercepted before the training parser so the
reference's positional contract is untouched.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpuflow",
        description="TPU-native well-flow model training",
    )
    p.add_argument("columnNames", nargs="?", default="", help="comma-separated feature/target column names")
    p.add_argument("columnTypes", nargs="?", default="", help="comma-separated types: int|float|other=categorical")
    p.add_argument("targetColumn", nargs="?", default="flow", help="target column name")
    p.add_argument("storagePath", nargs="?", default=None, help="artifact root; best model saved under {storagePath}/models/")
    p.add_argument("--data", default=None, help="headerless CSV data path (omit for synthetic wells)")
    p.add_argument("--well-column", default=None, help="column grouping CSV rows into per-well logs (sequence models)")
    p.add_argument("--model", default="lstm", help="static_mlp|dynamic_mlp|cnn1d|lstm|stacked_lstm")
    p.add_argument("--model-kwargs", default=None, metavar="JSON",
                   help='JSON dict forwarded to the model family, e.g. '
                        '\'{"hidden": 128, "backend": "pallas", '
                        '"remat": true}\'')
    p.add_argument("--epochs", type=int, default=1000)
    p.add_argument("--batch-size", type=int, default=20)
    p.add_argument("--patience", type=int, default=10)
    p.add_argument("--window", type=int, default=24)
    p.add_argument("--loss", default="mae_clip")
    p.add_argument("--optimizer", default="keras_sgd")
    p.add_argument("--clip-norm", type=float, default=0.0,
                   help="global-norm gradient clipping (0 = off)")
    p.add_argument("--accumulate-steps", type=int, default=1,
                   help="micro-batch gradients averaged per optimizer update")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--devices", type=int, default=None, help="data-parallel device count (default: all)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel model-axis size of the (data, model) "
                        "mesh (MLP families; devices/tp do data parallelism)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel stage count over the model axis "
                        "(pipeline_mlp family; GPipe microbatch schedule; "
                        "devices/pp do data parallelism)")
    p.add_argument("--pp-microbatches", type=int, default=0,
                   help="microbatches per pipelined step (0 = auto, = pp)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel device count over the model axis "
                        "(moe_mlp family; devices/ep do data parallelism)")
    p.add_argument("--synthetic-wells", type=int, default=8)
    p.add_argument("--synthetic-steps", type=int, default=512)
    p.add_argument("--jit-epoch", action="store_true", default=None,
                   dest="jit_epoch",
                   help="compile each epoch into one XLA program; default "
                        "AUTO picks the measured-fastest program for this "
                        "device and batch size (tpuflow/train/autotune.py)")
    p.add_argument("--no-jit-epoch", action="store_false", dest="jit_epoch",
                   help="force per-batch stepping (disable the epoch scan)")
    p.add_argument("--stream", action="store_true",
                   help="out-of-core ingest: never materialize the CSV "
                        "(tabular models; bounded memory at any file size)")
    p.add_argument("--stream-chunk-rows", type=int, default=65536)
    p.add_argument("--stream-shuffle-buffer", type=int, default=8192)
    p.add_argument("--stream-sample-rows", type=int, default=100_000,
                   help="rows of the head sample the feature pipeline fits on")
    p.add_argument("--stream-eval-rows", type=int, default=100_000,
                   help="val/test materialization cap (rows per split)")
    p.add_argument("--save-every", type=int, default=0,
                   help="epochs between full-state run checkpoints (needs storagePath)")
    p.add_argument("--resume", action="store_true",
                   help="continue from the latest run checkpoint under storagePath")
    p.add_argument("--trace-dir", default=None,
                   help="capture a jax.profiler trace of the first epoch here")
    p.add_argument("--metrics", default=None, metavar="PATH",
                   help="append per-epoch JSONL metric records to PATH")
    p.add_argument("--trace-id", default=None, metavar="TOKEN",
                   help="bind this trace id (1-64 chars of "
                        "[A-Za-z0-9._-]) for the whole job instead of "
                        "minting one: exported as TPUFLOW_TRACE_ID so "
                        "supervised restart attempts, elastic workers, "
                        "and online retrains all share ONE trace on the "
                        "fleet timeline (python -m tpuflow.obs fleet)")
    p.add_argument("--health", default="warn",
                   choices=["warn", "abort", "halve_lr", "off"],
                   help="numerics-watchdog policy on NaN/Inf/spike "
                        "anomalies: warn and continue (default), abort "
                        "the run (typed NumericsDivergence), halve the "
                        "optimizer LR, or off")
    p.add_argument("--precision", default="f32",
                   choices=["f32", "bf16"],
                   help="mixed-precision policy "
                        "(tpuflow/train/precision.py): bf16 computes in "
                        "bfloat16 while master params, optimizer state, "
                        "checkpoints, and serving artifacts stay f32 — "
                        "roughly half the HBM bytes/sample on the "
                        "HBM-bound train path")
    p.add_argument("--autotune", action="store_true",
                   help="online occupancy autotuning "
                        "(tpuflow/train/autotune.py): a post-epoch "
                        "controller hill-climbs microbatch size, remat, "
                        "and the epoch program from live throughput/MFU "
                        "gauges under a recompile budget, freezing on "
                        "the best-seen config when the budget is spent; "
                        "the winner persists next to the artifact so "
                        "restarts resume tuned (knobs via "
                        "TPUFLOW_AUTOTUNE_*; docs/performance.md)")
    p.add_argument("--autotune-budget", type=int, default=None,
                   metavar="N",
                   help="with --autotune: recompile budget (default "
                        "8) — the tuner freezes after charging N "
                        "XLA recompiles against its moves")
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--preflight", action="store_true", default=True,
                   dest="preflight",
                   help="static-analyze the job before running it "
                        "(spec/plan/shape passes; the default)")
    p.add_argument("--no-preflight", action="store_false", dest="preflight",
                   help="skip preflight static analysis (a bad spec then "
                        "fails wherever the runtime first hits it)")
    p.add_argument("--elastic", type=int, default=0, metavar="N",
                   help="run N elastic data-parallel workers under a "
                        "coordinator (tpuflow/elastic): each worker "
                        "trains the job on a disjoint shard, params are "
                        "averaged every --elastic-sync-every epochs, and "
                        "dead workers are evicted/restarted/rejoined "
                        "(needs storagePath)")
    p.add_argument("--elastic-sync-every", type=int, default=1,
                   help="epochs between elastic averaging rounds")
    p.add_argument("--elastic-transport", choices=("file", "socket"),
                   default=None,
                   help="exchange transport: 'file' (shared gang dir — "
                        "the reference implementation; the default) or "
                        "'socket' (a coordinator-hosted TCP exchange "
                        "server; no shared filesystem needed for the "
                        "exchange; implied by --elastic-fanout)")
    p.add_argument("--elastic-fanout", type=int, default=None,
                   metavar="K",
                   help="tree aggregation: fold pushes through mid-tier "
                        "aggregators with this subtree fan-out (0 = "
                        "star hub; implies the socket transport; "
                        "default TPUFLOW_ELASTIC_FANOUT or 0)")
    p.add_argument("--elastic-tiers", type=int, default=None,
                   help="aggregator tier count for --elastic-fanout "
                        "(default TPUFLOW_ELASTIC_TIER or 1)")
    p.add_argument("--elastic-delta", action="store_true", default=None,
                   help="delta-encode pushes against the last adopted "
                        "average (socket transport)")
    p.add_argument("--elastic-wire-dtype", choices=("f32", "bf16"),
                   default=None,
                   help="push payload dtype on the wire (socket "
                        "transport; masters and folds stay f32)")
    p.add_argument("--elastic-opt-policy",
                   choices=("carry", "reset", "average"),
                   default="carry",
                   help="optimizer state across an elastic adoption: "
                        "keep local moments (carry), re-init them for "
                        "the adopted params (reset), or gang-average "
                        "floating moments alongside the params")
    p.add_argument("--elastic-async", action="store_true",
                   help="asynchronous gradient/param push (DeepSpark "
                        "style): workers push when ready and adopt the "
                        "freshest average — no round barrier, so one "
                        "straggler can't stall every round")
    p.add_argument("--elastic-max-staleness", type=int, default=2,
                   help="async only: pushes more than this many rounds "
                        "behind the gang's frontier are rejected from "
                        "the average (fresher-but-stale pushes are "
                        "down-weighted by 1/(1+staleness))")
    p.add_argument("--elastic-heartbeat-timeout", type=float, default=30.0,
                   help="stale-heartbeat eviction deadline, seconds")
    p.add_argument("--elastic-max-restarts", type=int, default=2,
                   help="per-worker supervisor restart budget")
    p.add_argument("--elastic-stall-timeout", type=float, default=None,
                   help="per-worker progress watchdog, seconds: a "
                        "worker wedged mid-epoch (not dead — the "
                        "heartbeat eviction can't end its process) is "
                        "killed and restarted; set above first-epoch "
                        "compile time")
    p.add_argument("--online", action="store_true",
                   help="run the continuous-training loop (tpuflow/online) "
                        "as a sidecar instead of one batch train: stream "
                        "--data, detect drift against the serving artifact "
                        "under storagePath, warm-start retrain on drift, "
                        "and hot-swap non-regressing candidates (knobs via "
                        "TPUFLOW_ONLINE_*; docs/online.md)")
    p.add_argument("--online-max-windows", type=int, default=None,
                   metavar="N",
                   help="with --online: stop after N streaming windows "
                        "(default: run the stream out)")
    p.add_argument("--online-daemon", default=None, metavar="URL",
                   help="with --online: serving daemon(s) to POST "
                        "/artifacts/reload after a swap (comma-separated)")
    p.add_argument("--predict", action="store_true",
                   help="serve: load the trained artifact from storagePath and predict --data")
    p.add_argument("--out", default=None, help="with --predict: write predictions CSV here")
    p.add_argument("--compare", default=None, metavar="M1,M2,...",
                   help="train several model families on the same data and rank by MAE")
    return p


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        # Daemon subcommand, intercepted ahead of argparse (the four
        # reference positionals would swallow "serve" as columnNames).
        rest = list(argv[1:])
        if "--threaded" in rest:
            rest.remove("--threaded")
            from tpuflow import serve as _serve

            return _serve.main(rest)
        from tpuflow import serve_async as _serve_async

        return _serve_async.main(rest)
    args = build_parser().parse_args(argv)
    if args.trace_id:
        from tpuflow.obs.tracing import TRACE_ENV, clean_trace_id

        if clean_trace_id(args.trace_id) != args.trace_id:
            print(
                f"--trace-id: {args.trace_id!r} is not a valid trace "
                "token (1-64 chars of [A-Za-z0-9._-])",
                file=sys.stderr,
            )
            return 2
        # The env spelling is THE propagation channel: train() binds it,
        # supervise() hands it to every child attempt, elastic workers
        # and online retrains inherit it (tpuflow/obs/tracing.py).
        import os

        os.environ[TRACE_ENV] = args.trace_id
    if args.predict:
        return _predict_main(args)
    # Registry-backed parse-time validation: an unknown family dies HERE
    # with the catalog in hand, not minutes later as a KeyError deep in
    # training (kept out of argparse choices= so --help stays import-free).
    from tpuflow.models import MODELS

    if args.model not in MODELS:
        print(
            f"--model: unknown model {args.model!r}; valid: "
            f"{', '.join(sorted(MODELS))}",
            file=sys.stderr,
        )
        return 2
    compare_names = ()
    if args.compare:
        compare_names = tuple(
            m.strip() for m in args.compare.split(",") if m.strip()
        )
        unknown = [m for m in compare_names if m not in MODELS]
        if unknown:
            # Name typos fail at submission with the catalog in hand —
            # the job-runner's documented contract for compare specs
            # (serve.py: "typos fail at submission, not as all-FAILED
            # rows"). Candidates with VALID names that fail deeper
            # preflight are different: those fall through to compare()'s
            # record-failures-and-continue handling below.
            print(
                f"--compare: unknown models {unknown}; valid: "
                f"{', '.join(sorted(MODELS))}",
                file=sys.stderr,
            )
            return 2
    from tpuflow.api import TrainJobConfig, train

    model_kwargs = {}
    if args.model_kwargs:
        import json

        try:
            model_kwargs = json.loads(args.model_kwargs)
        except json.JSONDecodeError as e:
            print(
                f"--model-kwargs: {args.model_kwargs!r} is not valid "
                f"JSON: {e}",
                file=sys.stderr,
            )
            return 2
        if not isinstance(model_kwargs, dict):
            print(
                f"--model-kwargs must be a JSON object, got "
                f"{args.model_kwargs!r}",
                file=sys.stderr,
            )
            return 2

    autotune_block = None
    autotune_on = args.autotune
    if not autotune_on and args.autotune_budget is not None:
        # The env spelling of the switch counts too: TPUFLOW_AUTOTUNE=1
        # plus --autotune-budget is a legitimate combination (train_api
        # enables the tuner from the flag either way).
        from tpuflow.utils.env import env_flag

        autotune_on = env_flag("TPUFLOW_AUTOTUNE", False)
        if not autotune_on:
            print(
                "--autotune-budget needs --autotune (or TPUFLOW_AUTOTUNE"
                "=1); the budget gates the online tuner's moves",
                file=sys.stderr,
            )
            return 2
    if autotune_on:
        autotune_block = {}
        if args.autotune_budget is not None:
            autotune_block["recompile_budget"] = args.autotune_budget

    config = TrainJobConfig(
        column_names=args.columnNames,
        column_types=args.columnTypes,
        target=args.targetColumn,
        storage_path=args.storagePath,
        data_path=args.data,
        well_column=args.well_column,
        model=args.model,
        model_kwargs=model_kwargs,
        max_epochs=args.epochs,
        batch_size=args.batch_size,
        patience=args.patience,
        window=args.window,
        loss=args.loss,
        optimizer=args.optimizer,
        clip_norm=args.clip_norm,
        accumulate_steps=args.accumulate_steps,
        seed=args.seed,
        n_devices=args.devices,
        tp=args.tp,
        pp=args.pp,
        pp_microbatches=args.pp_microbatches,
        ep=args.ep,
        synthetic_wells=args.synthetic_wells,
        synthetic_steps=args.synthetic_steps,
        verbose=not args.quiet,
        jit_epoch=args.jit_epoch,
        precision=args.precision,
        stream=args.stream,
        stream_chunk_rows=args.stream_chunk_rows,
        stream_shuffle_buffer=args.stream_shuffle_buffer,
        stream_sample_rows=args.stream_sample_rows,
        stream_eval_rows=args.stream_eval_rows,
        save_every=args.save_every,
        resume=args.resume,
        trace_dir=args.trace_dir,
        metrics_path=args.metrics,
        health=args.health,
        autotune=autotune_block,
    )
    if args.preflight:
        # Preflight-by-default: the whole job is statically analyzed —
        # spec cross-checks, mesh/plan arithmetic, and an eval_shape
        # dry-run — before ANY ingest or compile. --no-preflight escapes
        # (the runtime's own later guards still apply).
        import dataclasses

        import jax

        from tpuflow.analysis import preflight

        failed = 0
        candidates = (
            [dataclasses.replace(config, model=m) for m in compare_names]
            if compare_names else [config]
        )
        for cfg in candidates:
            report = preflight(
                cfg,
                device_count=jax.device_count(),
                local_device_count=jax.local_device_count(),
                process_count=jax.process_count(),
            )
            if not report.ok:
                print(report.render(), file=sys.stderr)
                failed += 1
        # A compare is all-candidates-or-nothing ONLY when every family
        # fails preflight: compare()'s contract is record-failures-and-
        # continue (the comparison is the deliverable), so a candidate
        # with a valid name but a failing spec/plan/shape is reported
        # here and then recorded as a FAILED row by compare's own
        # handling — the healthy families still train. (Unknown NAMES
        # were already rejected at parse time above, the serve.py
        # submission contract.)
        if failed == len(candidates):
            print(
                "preflight failed: the job was rejected before any data "
                "was read or program compiled (--no-preflight to bypass)",
                file=sys.stderr,
            )
            return 2
    if args.online:
        if not config.storage_path or not config.data_path:
            print(
                "--online needs storagePath (the serving artifact the "
                "loop warm-starts from and swaps into) and --data (the "
                "stream to score)",
                file=sys.stderr,
            )
            return 2
        import json as _json

        from tpuflow.online import run_online

        try:
            summary = run_online(
                config,
                max_windows=args.online_max_windows,
                daemon_url=args.online_daemon,
            )
        except (ValueError, FileNotFoundError) as e:
            # Submission-shaped: a missing artifact or bad online block
            # is a message, not a traceback.
            print(f"--online: {e}", file=sys.stderr)
            return 2
        print(_json.dumps(summary))
        return 0
    if args.compare:
        from tpuflow.api import compare

        report = compare(compare_names, config)
        print(report.table())
        return 0 if report.ranked else 1
    if args.elastic:
        if not config.storage_path:
            print(
                "--elastic needs storagePath (workers checkpoint under "
                "{storagePath}/workerN; restarts resume from there)",
                file=sys.stderr,
            )
            return 2
        import dataclasses
        import json as _json

        from tpuflow.elastic.runner import run_elastic

        try:
            result = run_elastic(
                dataclasses.asdict(config),
                args.elastic,
                sync_every=args.elastic_sync_every,
                transport=args.elastic_transport or (
                    "socket" if args.elastic_fanout else "file"
                ),
                async_push=args.elastic_async,
                max_staleness=args.elastic_max_staleness,
                fanout=args.elastic_fanout,
                tiers=args.elastic_tiers,
                delta=args.elastic_delta,
                wire_dtype=args.elastic_wire_dtype,
                opt_policy=args.elastic_opt_policy,
                heartbeat_timeout=args.elastic_heartbeat_timeout,
                max_restarts=args.elastic_max_restarts,
                stall_timeout=args.elastic_stall_timeout,
                verbose=not args.quiet,
            )
        except ValueError as e:
            # e.g. a stale gang dir from a previous --elastic run under
            # the same storagePath: a submission error, not a traceback.
            print(f"--elastic: {e}", file=sys.stderr)
            return 2
        print(_json.dumps(result.summary()))
        return 0 if result.ok else 1
    train(config)
    return 0


def _predict_main(args) -> int:
    """Serving path (SURVEY.md §3.2): artifact + new data -> predictions."""
    if not args.storagePath or not args.data:
        print("--predict needs storagePath and --data", file=sys.stderr)
        return 2
    from tpuflow.api import predict

    y = predict(args.storagePath, args.model, data_path=args.data)
    if args.out:
        import numpy as np

        np.savetxt(args.out, y.reshape(len(y), -1), delimiter=",", fmt="%.6f")
        print(f"wrote {len(y)} predictions to {args.out}")
    else:
        print(f"{len(y)} predictions; first 5: {y[:5].tolist()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
