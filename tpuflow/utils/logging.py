"""Structured metrics logging: JSONL records instead of lost prints.

The reference prints schema echoes, per-epoch Keras lines, and a final
elapsed/loss pair, recording none of it (SURVEY.md §5.5, reference
cnn.py:62,128,133-134). ``MetricsLogger`` appends one JSON object per
event to a file (and optionally echoes), so every run leaves an auditable
metric trail.
"""

from __future__ import annotations

import json
import time
from typing import IO

from tpuflow.utils.paths import open_file


class MetricsLogger:
    """Append-only JSONL metrics writer.

    Usage::

        with MetricsLogger("runs/exp1/metrics.jsonl") as log:
            log.write("train_step", step=1, loss=0.5)
    """

    def __init__(self, path: str | None = None, echo: bool = False):
        self.path = path
        self.echo = echo
        self._fh: IO | None = None
        if path:
            # URI-aware (gs://, memory://, ...) via fsspec; local paths get
            # parent dirs created as before.
            self._fh = open_file(path, "a", encoding="utf-8")

    def write(self, event: str, **fields) -> dict:
        rec = {"event": event, "time": time.time(), **fields}
        line = json.dumps(rec)
        if self._fh:
            self._fh.write(line + "\n")
            self._fh.flush()
        if self.echo:
            print(line)
        return rec

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
