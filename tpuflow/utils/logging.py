"""Structured metrics logging: JSONL records instead of lost prints.

The reference prints schema echoes, per-epoch Keras lines, and a final
elapsed/loss pair, recording none of it (SURVEY.md §5.5, reference
cnn.py:62,128,133-134). ``MetricsLogger`` appends one JSON object per
event to a file (and optionally echoes), so every run leaves an auditable
metric trail.
"""

from __future__ import annotations

import datetime
import json
import threading
import time
from typing import IO

from tpuflow.utils.paths import open_file

# seq continuity across loggers on one path within a process: the
# ingest-phase writer and the fit loop's writer append to the SAME
# metrics file, and a seq that restarted at 1 per logger would make
# "order by seq" ambiguous for exactly the trail it exists to order.
_SEQ_LOCK = threading.Lock()
_SEQ_BY_PATH: dict[str, int] = {}


class MetricsLogger:
    """Append-only JSONL metrics writer.

    Every record carries the epoch-seconds ``time`` (sortable,
    arithmetic-friendly), an ISO-8601 UTC ``ts`` (human- and
    log-aggregator-friendly), and a monotonic ``seq`` — shared across
    every logger writing the same path in this process, so a trail that
    interleaves writers, or crosses a wall-clock step, still has a
    total order.

    Usage::

        with MetricsLogger("runs/exp1/metrics.jsonl") as log:
            log.write("train_step", step=1, loss=0.5)
    """

    def __init__(self, path: str | None = None, echo: bool = False):
        self.path = path
        self.echo = echo
        self._fh: IO | None = None
        self._seq = 0
        self._warned_closed = False
        if path:
            # URI-aware (gs://, memory://, ...) via fsspec; local paths get
            # parent dirs created as before.
            self._fh = open_file(path, "a", encoding="utf-8")

    def write(self, event: str, **fields) -> dict:
        now = time.time()
        # One lock for BOTH branches, and the record takes the claimed
        # seq from a local: a pathless logger shared across threads
        # (the supervisor's echo logger) raced its `_seq += 1`, and
        # even the pathed branch read `self._seq` back OUTSIDE the
        # lock — a concurrent writer could overwrite it between claim
        # and record, stamping two records with one seq (TPF016).
        with _SEQ_LOCK:
            if self.path:
                seq = _SEQ_BY_PATH[self.path] = (
                    _SEQ_BY_PATH.get(self.path, 0) + 1
                )
            else:
                seq = self._seq + 1
            self._seq = seq
        rec = {
            "event": event,
            "time": now,
            "ts": datetime.datetime.fromtimestamp(
                now, datetime.timezone.utc
            ).isoformat(timespec="milliseconds"),
            "seq": seq,
            **fields,
        }
        if "trace_id" not in rec:
            # Stamp the bound trace (tpuflow/obs/tracing.py) so every
            # trail record — epoch lines, drift anomalies, daemon
            # reloads — is linkable on the merged fleet timeline, not
            # just the span events (which carry it explicitly).
            try:
                from tpuflow.obs.tracing import current_trace_id

                tid = current_trace_id()
                if tid is not None:
                    rec["trace_id"] = tid
            except Exception:
                pass
        line = json.dumps(rec)
        if self._fh:
            # A broken write drops THIS line (warn once) instead of
            # raising mid-train: losing metric records is strictly
            # better than killing an hours-in training run over its
            # log. A transient OSError (ENOSPC blip, NFS hiccup) keeps
            # the handle so later writes can succeed again; a closed
            # handle (ValueError) is gone for good and is released.
            try:
                self._fh.write(line + "\n")
                self._fh.flush()
            except (OSError, ValueError) as e:
                if not self._warned_closed:
                    self._warned_closed = True
                    import sys

                    print(
                        f"tpuflow.utils.logging: metrics write to "
                        f"{self.path!r} failed ({type(e).__name__}: {e}); "
                        "dropping records that fail to write",
                        file=sys.stderr,
                    )
                if isinstance(e, ValueError):
                    self._fh = None
        if self.echo:
            print(line)
        return rec

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
