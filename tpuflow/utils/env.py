"""Validated environment-variable reads — THE one copy.

Both knob families that read numbers from the environment
(``TPUFLOW_RETRY_*`` in resilience/retry.py, ``TPUFLOW_SERVE_*`` in
serve.py) share one contract: a typo'd, non-finite, or below-minimum
value raises a ValueError naming the variable and the expected form,
because the error surfaces deep inside whatever path read the knob —
far from the shell that exported it — and must say exactly what to
fix. Two hand-rolled copies of that contract had already drifted
subtly; this module is the single implementation they both call.
"""

from __future__ import annotations

import math
import os


def env_number(name: str, default, *, cast, minimum, form: str):
    """One validated numeric env read. Unset (or, for historical
    compatibility with the retry family, empty-string) values return
    ``default``; anything else must cast, be finite, and clear
    ``minimum`` — or the error names the variable and ``form``."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = cast(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"invalid {name}={raw!r}: expected {form}"
        ) from None
    if not math.isfinite(value):
        # 'nan' survives < comparisons and 'inf' would sleep/queue
        # forever — exactly the far-from-the-shell breakage this
        # validation exists to prevent.
        raise ValueError(f"invalid {name}={raw!r}: expected {form}")
    if value < minimum:
        raise ValueError(
            f"invalid {name}={raw!r}: expected {form}, got a value below "
            f"{minimum}"
        )
    return value
