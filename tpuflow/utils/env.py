"""Validated environment-variable reads — THE one copy.

Every knob family that reads configuration from the environment
(``TPUFLOW_RETRY_*`` in resilience/retry.py, ``TPUFLOW_SERVE_*`` in
serve.py / serve_async.py, ``TPUFLOW_ONLINE_*`` in tpuflow/online)
shares one contract: a typo'd, non-finite, or below-minimum value
raises a ValueError naming the variable and the expected form, because
the error surfaces deep inside whatever path read the knob — far from
the shell that exported it — and must say exactly what to fix.
Hand-rolled copies of that contract had already drifted subtly; this
module is the single implementation they all call: :func:`env_number`
(the raw numeric read), plus the three knob-shaped wrappers
:func:`env_num`, :func:`env_flag`, and :func:`env_choice` that
serve.py re-exports for compatibility.
"""

from __future__ import annotations

import math
import os


def env_number(name: str, default, *, cast, minimum, form: str):
    """One validated numeric env read. Unset (or, for historical
    compatibility with the retry family, empty-string) values return
    ``default``; anything else must cast, be finite, and clear
    ``minimum`` — or the error names the variable and ``form``."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = cast(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"invalid {name}={raw!r}: expected {form}"
        ) from None
    if not math.isfinite(value):
        # 'nan' survives < comparisons and 'inf' would sleep/queue
        # forever — exactly the far-from-the-shell breakage this
        # validation exists to prevent.
        raise ValueError(f"invalid {name}={raw!r}: expected {form}")
    if value < minimum:
        raise ValueError(
            f"invalid {name}={raw!r}: expected {form}, got a value below "
            f"{minimum}"
        )
    return value


_FLAG_TRUE = ("1", "true", "yes", "on")
_FLAG_FALSE = ("0", "false", "no", "off")


def env_flag(name: str, default: bool) -> bool:
    """One validated boolean env read. An unrecognized token raises a
    ValueError naming the variable and the accepted spellings: a typo'd
    ``TPUFLOW_SERVE_BATCH=ture`` silently enabling (or worse, silently
    NOT disabling) a fast path is exactly the far-from-the-shell
    breakage read-time validation exists to prevent."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    token = raw.strip().lower()
    if token in _FLAG_TRUE:
        return True
    if token in _FLAG_FALSE:
        return False
    raise ValueError(
        f"invalid {name}={raw!r}: expected one of "
        f"{'/'.join(_FLAG_TRUE)} or {'/'.join(_FLAG_FALSE)}"
    )


def env_num(name: str, default, cast, *, minimum=0, form: str | None = None):
    """One validated numeric knob read — :func:`env_number` with the
    knob families' default form text. A non-numeric, non-finite, or
    below-minimum value raises a ValueError naming the variable and the
    expected form."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    if form is None:
        form = (
            f"an integer >= {minimum}" if cast is int
            else f"a number >= {minimum:g}"
        )
    return env_number(name, default, cast=cast, minimum=minimum, form=form)


def env_trace_id(name: str = "TPUFLOW_TRACE_ID") -> str | None:
    """One validated trace-token env read (the cross-process trace
    propagation contract, tpuflow/obs/tracing.py): unset/blank returns
    None; a valid token returns it; anything else fails loudly naming
    the variable, because a silently-dropped malformed trace would
    quietly orphan every span a supervised child records from the
    parent's trail. THE token rule (1-64 chars of ``[A-Za-z0-9._-]``,
    the same clamp serving applies to a client's ``X-Trace-Id``) lives
    in ``clean_trace_id`` — one copy, lazily imported (tracing imports
    this module lazily too; no cycle)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return None
    from tpuflow.obs.tracing import clean_trace_id

    token = clean_trace_id(raw)
    if token is not None:
        return token
    raise ValueError(
        f"invalid {name}={raw!r}: expected a trace token of 1-64 "
        "characters from [A-Za-z0-9._-]"
    )


def env_choice(name: str, default: str, choices: tuple) -> str:
    """One validated enum env read (same fail-loud contract as
    :func:`env_num`)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    token = raw.strip().lower()
    if token not in choices:
        raise ValueError(
            f"invalid {name}={raw!r}: expected one of {', '.join(choices)}"
        )
    return token
