"""Observability: tracing, timing, NaN guards, structured metrics.

SURVEY.md §5.1/§5.2/§5.5: the reference's entire observability stack is a
wall-clock print pair around ``model.fit`` plus loose prints (reference
cnn.py:126-134). Kept as the CLI summary contract; extended here with real
device profiling, per-step timing, numeric guards, and recorded (not just
printed) metrics.
"""

from tpuflow.utils.profiling import StepTimer, trace
from tpuflow.utils.guards import check_finite, finite_or_raise
from tpuflow.utils.logging import MetricsLogger

__all__ = [
    "StepTimer",
    "trace",
    "check_finite",
    "finite_or_raise",
    "MetricsLogger",
]
