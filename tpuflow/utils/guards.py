"""Numeric guards: NaN/Inf detection for losses, grads, and params.

SURVEY.md §5.2: the reference has no sanitizers and no native code to
sanitize; the TPU-framework equivalent is numeric-health checking of the
training state (plus ``jax.config.update("jax_debug_nans", True)`` for
deep debugging, which these helpers don't require).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def check_finite(tree) -> jnp.ndarray:
    """Scalar bool: True iff every leaf of the pytree is fully finite.

    Jit-safe — usable inside a train step (e.g. to skip a bad update).
    """
    leaves = jax.tree_util.tree_leaves(tree)
    ok = jnp.asarray(True)
    for leaf in leaves:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


def finite_or_raise(tree, name: str = "tree") -> None:
    """Host-side check (blocks): raise FloatingPointError naming the first
    non-finite leaf path."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in flat:
        if not bool(jnp.all(jnp.isfinite(leaf))):
            raise FloatingPointError(
                f"non-finite values in {name}{jax.tree_util.keystr(path)}"
            )
