"""URI-aware storage paths: local POSIX and remote (gs://, s3://, ...).

The reference's whole deployment story is writing the artifact to
cluster-shared storage (``storagePath + "models/cnn.mdl"``, reference
cnn.py:122; Hadoop cluster per Readme.md:3). The TPU-native equivalent is
an object store: Orbax handles ``gs://`` natively *iff* the URI reaches it
intact. These helpers keep URI-schemed paths opaque — never ``abspath``-ed
(which would mangle ``gs://b/x`` into ``/cwd/gs:/b/x``) — while local
paths keep their absolute-path normalization. Sidecar file IO goes through
``fsspec`` for URIs, so any registered filesystem (gcs, s3, memory for
tests) works unchanged.
"""

from __future__ import annotations

import os
import posixpath
import re
from typing import IO

_URI_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.-]*://")


def is_uri(path: str) -> bool:
    """True for scheme-prefixed paths (gs://, s3://, memory://, ...)."""
    return bool(_URI_RE.match(path))


def join_path(base: str, *parts: str) -> str:
    """Join artifact-layout components under a storage root.

    Remote URIs are joined with ``/`` and returned verbatim otherwise;
    local paths are joined and normalized to absolute, as before.
    """
    if is_uri(base):
        return posixpath.join(base.rstrip("/"), *parts)
    return os.path.abspath(os.path.join(base, *parts))


def open_file(path: str, mode: str = "r", **kwargs) -> IO:
    """Open a local path or any fsspec-registered URI for reading/writing.

    Parent directories are created on write for both kinds (object stores
    that have no directories simply no-op).
    """
    if is_uri(path):
        import fsspec

        if "w" in mode or "a" in mode or "x" in mode:
            fs, fs_path = fsspec.core.url_to_fs(path)
            parent = posixpath.dirname(fs_path)
            if parent:
                try:
                    fs.makedirs(parent, exist_ok=True)
                except Exception:
                    pass  # bucket-style stores have no directories
            if "a" in mode:
                # Object stores have no real append: bucket backends either
                # refuse 'ab' or silently replace the object. Emulate append
                # by rewriting prior content into a fresh 'w' stream.
                prior = None
                if fs.exists(fs_path):
                    read_mode = "rb" if "b" in mode else "r"
                    with fsspec.open(path, read_mode, **kwargs).open() as rf:
                        prior = rf.read()
                f = fsspec.open(path, mode.replace("a", "w"), **kwargs).open()
                if prior:
                    f.write(prior)
                return f
        return fsspec.open(path, mode, **kwargs).open()
    if "w" in mode or "a" in mode or "x" in mode:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
    return open(path, mode, **kwargs)


def exists(path: str) -> bool:
    if is_uri(path):
        import fsspec

        fs, fs_path = fsspec.core.url_to_fs(path)
        return fs.exists(fs_path)
    return os.path.exists(path)


def atomic_write_json(path: str, obj) -> None:
    """Write ``obj`` as JSON via tmp + fsync + ``os.replace``: a
    concurrent reader sees the old file or the new one, never a torn
    write. The tmp name is unique per (process, thread), so concurrent
    writers of the SAME path (e.g. an elastic worker's heartbeat thread
    racing its main-thread beat) cannot yank each other's tmp
    mid-write. The fsync BEFORE the rename is load-bearing: rename
    alone orders the directory entry, not the data blocks, so a crash
    between write and rename could otherwise publish a zero-length
    "atomic" file under the final name. Local filesystem only — the one
    shared owner of the rename idiom the elastic gang files, progress
    records, and state mirrors all rely on. Raises OSError; callers own
    their best-effort policy."""
    import json
    import threading

    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
