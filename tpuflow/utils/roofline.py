"""Roofline accounting: is a measured throughput good, and what bounds it?

The reference records wall-clock only (reference cnn.py:126-134); a raw
samples/sec number can't say whether it leaves 5x on the table. This
module supplies the missing context: a FLOPs/bytes-per-sample model for
the LSTM config, per-chip peak specs, and the MFU / HBM-utilization /
bound-by verdict. Used by ``bench.py`` for the recorded north-star metric.
"""

from __future__ import annotations

# Per-chip peak bf16 matmul FLOP/s and HBM bytes/s, keyed by substrings of
# jax.Device.device_kind (public spec-sheet numbers). Order matters:
# longest/most-specific keys first ("v5p" before "v5").
CHIP_PEAKS = {
    "v6": (918e12, 1640e9),  # v6e / Trillium
    "v5p": (459e12, 2765e9),
    "v5": (197e12, 819e9),  # v5e reports as "TPU v5 lite"
    "v4": (275e12, 1228e9),
    "v3": (123e12, 900e9),
    "v2": (45e12, 700e9),
}

# HBM itemsize per compute-precision token — THE canonical map (the
# mixed-precision policy in tpuflow/train/precision.py re-exports it):
# activation bytes travel in the compute dtype, so the bytes-per-sample
# models below must be fed the itemsize of the dtype the job actually
# runs, not a hard-coded 4.
PRECISION_ITEMSIZE = {"f32": 4, "bf16": 2}


def precision_itemsize(compute_dtype: str) -> int:
    """Itemsize for a precision token ("f32" | "bf16"); raises naming
    the valid tokens on anything else — a typo here would silently
    corrupt every byte account downstream."""
    try:
        return PRECISION_ITEMSIZE[compute_dtype]
    except KeyError:
        raise ValueError(
            f"unknown compute precision {compute_dtype!r}; "
            f"valid: {', '.join(PRECISION_ITEMSIZE)}"
        ) from None


def chip_peaks(device_kind: str) -> tuple[float | None, float | None]:
    """(peak bf16 FLOP/s, peak HBM bytes/s) for a device_kind, or Nones."""
    kind = device_kind.lower()
    for key, peaks in CHIP_PEAKS.items():
        if key in kind:
            return peaks
    return None, None


def lstm_flops_per_sample_step(T: int, F: int, H: int) -> float:
    """Model FLOPs for ONE sample through one train step (fwd+bwd+update).

    Matmuls (2*m*n*k each, per timestep): input projection [F,4H],
    recurrent [H,4H], head [H,1]. Gate elementwise math ~25 flops per gate
    element (sigmoid/tanh ~10 each plus combines). Backward of a matmul
    costs 2x its forward (dX and dW products); elementwise bwd ~= fwd.
    """
    matmul_fwd = 2.0 * T * (F * 4 * H + H * 4 * H + H)
    gates_fwd = 25.0 * T * 4 * H
    return 3.0 * matmul_fwd + 2.0 * gates_fwd


def lstm_bytes_per_sample_step(T: int, F: int, H: int, itemsize: int) -> float:
    """Rough HBM bytes for one sample through one train step.

    Activation traffic dominates (weights are small and VMEM-resident
    across the scan): read x; write+read the hoisted projection xw [T,4H];
    write hs/cs and re-read them in backward; write dxw. Counts each
    logical tensor's HBM round trips; XLA fusion can only shrink this.
    """
    xw = 4 * H * T
    hs_cs = 2 * H * T
    return itemsize * (T * F + 3 * xw + 3 * hs_cs)


def attention_flops_per_sample_step(
    T: int, F: int, D: int, layers: int, mlp_ratio: int = 4
) -> float:
    """Model FLOPs for ONE sample through one attention train step.

    Per layer: qkv [D,3D] + out-proj [D,D] + MLP [D,rD]+[rD,D] projections
    (2*m*n*k each, per timestep), plus the causal attention products
    q@k^T and p@v — T*D each per query row, halved by causality. Embed
    [F,D] + head [D,1] once. Backward of a matmul costs 2x its forward.
    """
    proj = 2.0 * T * (3 * D * D + D * D + 2 * mlp_ratio * D * D)
    attn = 2.0 * 2.0 * (T * T // 2) * D  # s = q@k^T and p@v, causal half
    embed = 2.0 * T * (F * D + D)
    return 3.0 * (layers * (proj + attn) + embed)


def attention_bytes_per_sample_step(
    T: int,
    D: int,
    layers: int,
    itemsize: int,
    mlp_ratio: int = 4,
    score_heads: int = 0,
) -> float:
    """Rough HBM bytes for one sample through one attention train step.

    Per layer, the [T, D]-shaped activations (x, qkv, att out, MLP
    hidden) each make write+read round trips fwd and bwd. With
    ``score_heads=0`` (the flash/ring kernels) the [T, T] score matrix is
    NOT counted — those kernels never spill it; for the materializing
    "full" backend pass the head count, adding per-head [T, T] traffic
    (write fwd + re-read and re-write in backward), which dominates at
    long T and is exactly why the flash crossover exists.
    """
    act = T * D * (1 + 3 + 1 + mlp_ratio)
    scores = score_heads * T * T * 3
    return itemsize * layers * (4.0 * act + scores)


def model_cost_per_sample(
    model: str,
    *,
    window: int,
    features: int,
    model_kwargs: dict | None = None,
    itemsize: int = 4,
) -> tuple[float, float] | None:
    """(FLOPs, HBM bytes) per sample per train step for the model
    families with a cost model — the live-MFU feed (the fit loop
    publishes ``train_mfu``/``train_bound`` from this each epoch).

    Covers the sequence families whose arithmetic the module already
    models: the LSTM stack (per layer, the first layer consuming
    ``features`` and deeper layers ``hidden``) and the causal
    transformer. Returns None for families without a model (MLPs, the
    residual-MLP hybrids) — an absent MFU is honest, a guessed one is
    noise. Defaults mirror the model registry (hidden 64; stacked_lstm
    2 layers; attention dim 64 x 2 layers); ``itemsize`` defaults to 4
    (the models' float32 default — bench.py passes 2 for its bf16
    sweeps).
    """
    kw = model_kwargs or {}
    if model in ("lstm", "stacked_lstm", "lstm_residual"):
        hidden = int(kw.get("hidden", 64))
        layers = int(
            kw.get("num_layers", 2 if model == "stacked_lstm" else 1)
        )
        flops = bytes_ = 0.0
        for i in range(layers):
            f_in = features if i == 0 else hidden
            flops += lstm_flops_per_sample_step(window, f_in, hidden)
            bytes_ += lstm_bytes_per_sample_step(
                window, f_in, hidden, itemsize
            )
        return flops, bytes_
    if model == "attention":
        dim = int(kw.get("dim", 64))
        layers = int(kw.get("num_layers", 2))
        score_heads = (
            int(kw.get("heads", 4))
            if kw.get("backend", "full") == "full"
            else 0
        )
        return (
            attention_flops_per_sample_step(window, features, dim, layers),
            attention_bytes_per_sample_step(
                window, dim, layers, itemsize, score_heads=score_heads
            ),
        )
    return None


def roofline_report(
    samples_per_sec: float,
    flops_per_sample: float,
    bytes_per_sample: float,
    device_kind: str,
    compute_dtype: str | None = None,
) -> dict:
    """MFU, HBM utilization, and the bound-by verdict for a measurement.

    ``compute_dtype`` ("f32" | "bf16") makes the verdict honest under
    the mixed-precision policy: ``CHIP_PEAKS`` are bf16 matmul peaks,
    and an all-f32 run cannot reach them — the MXU runs f32 dots as
    multiple bf16 passes at roughly HALF the rate — so "f32" judges MFU
    (and the ridge) against half the FLOP peak instead of flattering an
    f32 run with an unreachable denominator. ``None`` (legacy callers)
    keeps the bf16 peak. The token is echoed in the report when given.

    Returns ``{"mfu": None, "bound": "unknown chip ..."}`` for chips
    without a peaks entry (e.g. cpu).
    """
    peak_flops, peak_bw = chip_peaks(device_kind)
    if not peak_flops:
        rep = {"mfu": None, "bound": f"unknown chip {device_kind!r}"}
        if compute_dtype is not None:
            rep["compute_dtype"] = compute_dtype
        return rep
    if compute_dtype == "f32":
        peak_flops = peak_flops / 2.0
    ai = flops_per_sample / bytes_per_sample  # arithmetic intensity
    ridge = peak_flops / peak_bw
    rep = {
        "mfu": round(samples_per_sec * flops_per_sample / peak_flops, 6),
        "hbm_util": round(samples_per_sec * bytes_per_sample / peak_bw, 6),
        "bound": "hbm" if ai < ridge else "mxu",
    }
    if compute_dtype is not None:
        rep["compute_dtype"] = compute_dtype
    return rep
