"""Device profiling + per-step timing.

``trace`` wraps ``jax.profiler`` (XLA/TPU traces viewable in
TensorBoard/Perfetto); ``StepTimer`` gives honest step timings by blocking
on device results — the recorded version of the reference's
``t0 = time.time(); model.fit(...)`` wall-clock pair (cnn.py:126-133).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace for the enclosed block.

    View with TensorBoard's profile plugin or ui.perfetto.dev. The block
    also records an ``xla.profile`` span carrying the logdir, so device
    captures are visible on the ``obs timeline``/``obs fleet`` xla lane
    next to the flight-recorder marks they usually accompany.
    """
    import jax

    from tpuflow.obs.tracing import span

    with span("xla.profile", logdir=logdir):
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()


@dataclass
class StepTimer:
    """Accumulates per-step wall-clock; blocks on a result each step so the
    measured time covers device execution, not just dispatch."""

    times: list = field(default_factory=list)
    _t0: float | None = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, block_on=None) -> float:
        import jax

        if self._t0 is None:
            # A stop() with no matching start() used to record ~0.0 —
            # a silently-wrong sample that drags the mean toward zero
            # and inflates samples_per_sec. Fail loudly instead.
            raise RuntimeError(
                "StepTimer.stop() called before start() — the ~0.0 it "
                "would record is not a measurement"
            )
        if block_on is not None:
            # device_get, not block_until_ready: on the axon relay backend
            # block_until_ready can return before the device work finishes
            # (measured round 5 — see benchmarks/common.py::drain); only a
            # real transfer of a data-dependent value is a sync point.
            jax.device_get(block_on)
        dt = time.perf_counter() - self._t0
        self._t0 = None  # a second stop() without a new start() also fails
        self.times.append(dt)
        return dt

    @contextlib.contextmanager
    def step(self):
        """Time one step: set ``out["block_on"]`` to the step's device
        result so the timing covers execution, not just dispatch."""
        self.start()
        out = {}
        try:
            yield out
        finally:
            self.stop(out.get("block_on"))

    @property
    def mean(self) -> float:
        return sum(self.times) / len(self.times) if self.times else 0.0

    @property
    def total(self) -> float:
        return sum(self.times)

    def samples_per_sec(self, batch_size: int) -> float:
        return batch_size / self.mean if self.mean else 0.0
