"""Shared baseline machinery for the repo-wide analyzer passes.

Both whole-repo passes — concurrency (TPF016–018) and storage
(TPF019–021) — accept triaged findings through the same committed-file
workflow: entries are fingerprinted ``(rule, file, scope, subject)``
with NO line numbers (they survive unrelated edits), every entry
carries a one-line justification, and an entry whose finding no longer
exists is itself reported (stale-entry hygiene). This module is the one
implementation of that contract; the passes bind their own rule tables
and baseline filenames.

Fingerprints are **package-relative** (the ``file`` field is the
/-normalized path under the analysis root), and regeneration
(``write_baseline``) preserves justifications across pure file moves: a
reason whose fingerprint matches a current finding exactly is carried
verbatim, and a reason orphaned by a rename is re-attached when exactly
one current finding shares its ``(rule, scope, subject)`` — the
function moved, the accepted hazard did not.
"""

from __future__ import annotations

import json


class BaselineError(ValueError):
    """A malformed baseline file. Loud by design (the utils/env.py
    posture): names the file and the offending entry/field."""


def baseline_key(entry: dict) -> tuple:
    """The line-free fingerprint of one accepted finding."""
    return (entry["rule"], entry["file"], entry["scope"], entry["subject"])


def load_baseline(path: str, known_rules) -> list[dict]:
    """Parse + validate a baseline; returns its entries. Raises
    :class:`BaselineError` naming the file and field on anything
    malformed — a baseline that silently half-loads would silently
    un-suppress (or worse, un-report) findings. ``known_rules`` is the
    calling pass's rule table; an entry naming any other code is
    malformed."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise BaselineError(f"baseline {path}: unreadable ({e})") from e
    except json.JSONDecodeError as e:
        raise BaselineError(
            f"baseline {path}: not valid JSON ({e})"
        ) from e
    if not isinstance(doc, dict):
        raise BaselineError(
            f"baseline {path}: top level must be an object, got "
            f"{type(doc).__name__}"
        )
    entries = doc.get("entries")
    if not isinstance(entries, list):
        raise BaselineError(
            f"baseline {path}: field 'entries' must be a list, got "
            f"{type(entries).__name__}"
        )
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict):
            raise BaselineError(
                f"baseline {path}: entries[{i}] must be an object, got "
                f"{type(entry).__name__}"
            )
        for key in ("rule", "file", "scope", "subject", "reason"):
            value = entry.get(key)
            if not isinstance(value, str) or not value.strip():
                raise BaselineError(
                    f"baseline {path}: entries[{i}] field {key!r} must "
                    "be a non-empty string (every accepted finding "
                    "carries a one-line justification)"
                )
        if entry["rule"] not in known_rules:
            raise BaselineError(
                f"baseline {path}: entries[{i}] names unknown rule code "
                f"{entry['rule']!r} (valid: "
                f"{', '.join(sorted(known_rules))})"
            )
    return entries


def write_baseline(path: str, findings, reasons: dict | None = None,
                   *, comment: str) -> int:
    """(Re)write a baseline accepting every current finding (objects
    with ``fingerprint``/``rule``/``rel``/``scope``/``subject``).

    Reasons from an existing baseline are preserved per fingerprint;
    a reason whose file component went stale (the function moved files)
    follows it when exactly one current finding shares its
    ``(rule, scope, subject)``. New entries get a placeholder the owner
    must edit into a real justification."""
    reasons = reasons or {}
    # Rename-robust fallback: reasons indexed by the file-free remainder
    # of the fingerprint. Only an UNAMBIGUOUS match may travel — two
    # same-shaped findings in different files keep their own triage.
    moved: dict[tuple, list[str]] = {}
    for key, reason in reasons.items():
        rule, _file, scope, subject = key
        moved.setdefault((rule, scope, subject), []).append(reason)
    seen = set()
    entries = []
    for f in findings:
        if f.fingerprint in seen:
            continue
        seen.add(f.fingerprint)
        reason = reasons.get(f.fingerprint)
        if reason is None:
            candidates = moved.get((f.rule, f.scope, f.subject), [])
            if len(candidates) == 1:
                reason = candidates[0]
        entries.append({
            "rule": f.rule,
            "file": f.rel,
            "scope": f.scope,
            "subject": f.subject,
            "reason": reason
            or "TODO: replace with a one-line justification",
        })
    doc = {"version": 1, "comment": comment, "entries": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    return len(entries)
