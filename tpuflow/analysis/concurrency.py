"""Pass 5 — repo-wide concurrency analyzer: static lock-discipline races.

Every other analysis pass is per-file and per-function; this one builds
ONE index over the whole package — every class, method, lock attribute,
``with <lock>`` region, and thread entry point — and reasons across
functions, classes, and files at once. tpuflow is a fleet of cooperating
threads (batcher lanes, coordinator rounds, supervisor watchdogs, metric
registries, daemon admission); a guarded attribute read without its lock
is invisible to pytest until a chaos soak turns it into a flaky SLO
violation. The cheapest place to catch it is here, statically, in
tier-1 — the DeepSpark/BigDL lesson (PAPERS.md) that async exchange and
thread-pooled serving make data races the dominant correctness hazard.

Lock-discipline inference (the index's core judgment):

- A **lock** is an attribute assigned ``threading.Lock()`` / ``RLock()``
  / ``Condition(...)`` (module-level lock names count too). A Condition
  wraps a mutex, so holding it IS holding a lock.
- An attribute **written under** ``with <lock>:`` in any method — in the
  class or anywhere in its inheritance family — is inferred *guarded*.
  ``__init__``/``__post_init__`` writes neither guard nor violate: the
  object is pre-publication, no other thread can hold a reference yet.
- A method named ``*_locked`` is callee-side convention for "my caller
  holds the lock": its accesses count as guarded, and its writes count
  as guarding evidence (the repo's ``_admit_locked``/``_drain_locked``
  idiom).
- A class is **thread-shared** once any of its methods is reachable —
  over the repo call graph — from a thread entry point: a
  ``Thread(target=...)``, an ``executor.submit``/``run_in_executor``
  argument, an HTTP-handler method, or any function/lambda registered
  as a callback (gauge ``fn=``, batcher ``on_done=``, reload hooks).
  Callback registration is deliberately over-approximated: a callable
  that escapes into a registry runs on whatever thread collects it.

Three rule families ride on the index:

- **TPF016** — guarded-attribute access outside its lock: a read or
  write of an inferred-guarded attribute, in a thread-shared class,
  without holding THE guarding mutex (not in ``__init__`` / a
  ``*_locked`` method). The guard is the intersection of every locked
  write's canonical tokens — ``Condition(self._lock)`` aliases to the
  lock it wraps — falling back to the majority mutex when writes
  disagree, so holding a DIFFERENT lock (the classic wrong-lock race)
  is flagged exactly like holding none. Module globals get the
  write-only variant: an unguarded WRITE to a global that is elsewhere
  written under a module lock (reads of module globals are pervasively
  safe constants; lost updates are not), with Python scoping honored —
  a local that shadows a guarded global is not a race.
- **TPF017** — blocking call while holding a lock: ``sleep``, socket
  ops, ``open(...)``, ``subprocess.*``, ``requests.*``, ``.result()``
  on a future, ``Event.wait``, ``Thread.join`` inside a ``with <lock>``
  region (or a ``*_locked`` method). Every other thread that needs the
  lock stalls behind I/O it cannot see. ``Condition.wait`` is exempt —
  it RELEASES the lock; that is its contract.
- **TPF018** — thread-lifecycle hygiene: ``Condition.wait`` outside a
  predicate loop (wakeups are allowed to be spurious; an un-looped wait
  is a missed-notify hang), and a non-daemon ``Thread(...)`` that is
  never ``join``ed or marked daemon (a leak that outlives — or hangs —
  interpreter shutdown).

Accepted findings live in a committed **baseline**
(``tpuflow/analysis/concurrency_baseline.json``): entries are
fingerprinted (rule, file, scope, subject) — line-number-free, so they
survive unrelated edits — and every entry carries a one-line
justification. A baseline entry whose finding no longer exists is
itself reported (stale-entry hygiene). ``# noqa: TPF016`` line
suppression works exactly as in the per-file linter.

Entry points: ``python -m tpuflow.analysis repo [--json|--baseline]``
and the tier-1 self-analysis gate (zero unbaselined findings over
``tpuflow/``) in tests/test_analysis.py.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from tpuflow.analysis.baseline import BaselineError  # noqa: F401 (re-export)
from tpuflow.analysis.baseline import baseline_key as _baseline_key
from tpuflow.analysis.baseline import load_baseline as _load_baseline
from tpuflow.analysis.baseline import write_baseline as _write_baseline
from tpuflow.analysis.diagnostics import Diagnostic
from tpuflow.analysis.linter import _noqa_lines

_PASS = "concurrency"

RULES = {
    "TPF016": "guarded-attribute access outside its lock: the attribute "
              "is written under a lock elsewhere in the class family, so "
              "every access in a thread-shared class must hold it — an "
              "unguarded read can observe a torn update, an unguarded "
              "write can lose one",
    "TPF017": "blocking call (sleep / socket / file I/O / .result() / "
              "subprocess / Event.wait / Thread.join) while holding a "
              "lock: every thread that needs the lock stalls behind I/O "
              "it cannot see — move the blocking work outside the "
              "critical section (Condition.wait is exempt: it releases "
              "the lock by contract)",
    "TPF018": "thread-lifecycle hygiene: Condition.wait outside a "
              "predicate loop (spurious wakeups and missed notifies are "
              "part of the contract — re-check the predicate in a while "
              "loop), or a non-daemon Thread that is never joined or "
              "marked daemon (leaks past — or hangs — interpreter "
              "shutdown)",
}

# The stale-baseline hygiene code: an accepted finding whose code no
# longer exists. Reported as an error so the gate forces the entry's
# removal — a baseline that only grows is a baseline nobody reads.
STALE_CODE = "concurrency.baseline.stale"

# The *_locked convention's pseudo-token: "my caller holds the lock" —
# which lock, the callee cannot know statically.
_CALLER_TOKEN = "<caller holds the lock>"

# threading constructors the index recognizes, by terminal call name.
_LOCK_CTORS = {"Lock", "RLock"}
_COND_CTORS = {"Condition"}
_EVENT_CTORS = {"Event"}
_THREAD_CTORS = {"Thread"}

# Methods that mutate their receiver: a call like ``self._pending.pop(0)``
# is a WRITE access to ``_pending`` for guarding/violation purposes.
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "remove", "clear", "add", "discard", "update", "setdefault",
    "popitem",
}

# TPF017 blocking shapes. ``sleep`` matches by bare call name (catches
# time.sleep, self.sleep, and injected sleeps — the TPF007 precedent)
# except under an ``asyncio`` base; the module roots catch everything
# dispatched through them.
_BLOCKING_ROOTS = {"socket", "subprocess", "requests"}
_BLOCKING_NAMES = {"open", "urlopen"}

# Init-phase methods: accesses here are pre-publication (no other
# thread can hold a reference to a half-constructed object), so they
# neither establish guarding nor violate it.
_INIT_METHODS = {"__init__", "__post_init__", "__del__", "__repr__"}

# Callback-registration keywords: a callable passed under one of these
# names escapes into a registry and runs on whatever thread collects
# it (metrics scrape threads, dispatcher lanes, reload hooks).
_CALLBACK_KWARGS = {
    "target", "fn", "on_done", "callback", "on_artifact_change",
}

# HTTP-handler entry heuristic: ThreadingHTTPServer/socketserver spawn
# one thread per request into these methods.
_HANDLER_PREFIXES = ("do_",)
_HANDLER_NAMES = {"handle", "handle_one_request", "process_request"}

# File-op shapes the shared walk records for the storage pass. Kept
# deliberately syntactic — the storage pass classifies, this walk only
# observes. ``.replace`` is NOT a path op unless rooted at ``os`` (str
# .replace is everywhere); ``.rename`` has no str/dict collision, so an
# attribute ``.rename`` is recorded (Path.rename is rename-as-publish).
_OS_RENAMES = {"replace", "rename", "renames"}
_PATH_WRITES = {"write_text", "write_bytes"}
_PATH_READS = {"read_text", "read_bytes"}
_PATH_FS = {"unlink", "glob", "rglob"}
_NP_IO = {"save", "load", "savez", "savez_compressed"}
_JSON_IO = {"dump", "load"}


# ---------------------------------------------------------------------
# the index
# ---------------------------------------------------------------------


@dataclass
class Access:
    """One ``self.<attr>`` (or module-global) touch inside a function."""

    attr: str
    write: bool
    line: int
    locks: frozenset  # lock tokens lexically held


@dataclass
class BlockingCall:
    """A blocking-shaped call and the locks held around it."""

    what: str  # rendered callable, e.g. "time.sleep"
    line: int
    locks: frozenset


@dataclass
class CondWait:
    """A ``<condition>.wait(...)`` call site."""

    expr: str  # rendered receiver, e.g. "self._cond"
    line: int
    in_loop: bool  # lexically inside a while/for of the same function


@dataclass
class ThreadSpawn:
    """A ``threading.Thread(...)`` construction site."""

    line: int
    daemon: bool | None  # True/False from the kwarg; None = not passed
    bound_to: str | None  # assignment target's terminal name, if any


@dataclass
class FileOp:
    """One filesystem touchpoint, recorded raw during the shared walk.

    The storage pass (tpuflow/analysis/storage.py, TPF019–021) owns the
    CLASSIFICATION; this index only records what it saw. ``kind``:

    - ``open``        — ``open(...)`` / ``<x>.open(...)``
    - ``rename``      — ``os.replace``/``os.rename``/``os.renames``,
                        ``shutil.move``, ``<path>.rename(...)``
    - ``path_write``  — ``<x>.write_text``/``write_bytes``
    - ``path_read``   — ``<x>.read_text``/``read_bytes``
    - ``path_fs``     — ``<x>.unlink``/``glob``/``rglob``
    - ``np``          — ``np.save``/``load``/``savez[_compressed]``
    - ``json``        — ``json.dump``/``json.load`` (handle-mediated:
                        read-modify-write evidence, never flagged alone)
    - ``shutil``      — any other ``shutil.*`` call
    """

    kind: str
    what: str  # rendered callable, e.g. "os.replace", "open"
    target: str  # rendered path expression ("" when not resolvable)
    mode: str  # open()'s literal mode string when constant, else ""
    line: int


@dataclass
class FuncInfo:
    qual: str  # "Class.method", "func", "Class.__init__.<lambda>"
    name: str
    cls: str | None
    lineno: int
    module: "ModuleInfo" = field(repr=False, default=None)
    callees: list = field(default_factory=list)  # (kind, name) pairs
    accesses: list = field(default_factory=list)  # self.<attr> Access
    global_accesses: list = field(default_factory=list)
    blocking: list = field(default_factory=list)  # BlockingCall
    cond_waits: list = field(default_factory=list)  # CondWait
    spawns: list = field(default_factory=list)  # ThreadSpawn
    file_ops: list = field(default_factory=list)  # FileOp (storage pass)
    is_entry: bool = False

    @property
    def locked_convention(self) -> bool:
        return self.name.endswith("_locked")


@dataclass
class ClassInfo:
    name: str
    module: "ModuleInfo" = field(repr=False, default=None)
    bases: list = field(default_factory=list)  # base names (strings)
    locks: dict = field(default_factory=dict)  # attr -> kind
    events: set = field(default_factory=set)  # Event attrs
    threads: set = field(default_factory=set)  # Thread attrs
    methods: dict = field(default_factory=dict)  # name -> FuncInfo


@dataclass
class ModuleInfo:
    path: str  # as walked (display)
    rel: str  # /-normalized path relative to the analysis root
    locks: dict = field(default_factory=dict)  # module lock name -> kind
    global_names: set = field(default_factory=set)  # top-level bindings
    classes: dict = field(default_factory=dict)
    functions: dict = field(default_factory=dict)  # qual -> FuncInfo
    noqa: dict = field(default_factory=dict)  # line -> {codes}
    joined_names: set = field(default_factory=set)  # x in `x.join(...)`
    daemon_set_names: set = field(default_factory=set)  # x.daemon = True
    entry_refs: set = field(default_factory=set)  # (kind, name) escaped


@dataclass
class RepoIndex:
    root: str
    modules: dict = field(default_factory=dict)  # rel -> ModuleInfo
    # repo-wide lookup tables (the cross-file reasoning surface)
    cond_attr_names: set = field(default_factory=set)
    event_attr_names: set = field(default_factory=set)
    thread_attr_names: set = field(default_factory=set)
    lock_attr_names: set = field(default_factory=set)
    lock_aliases: dict = field(default_factory=dict)  # cond -> wrapped lock
    methods_by_name: dict = field(default_factory=dict)  # name -> [FuncInfo]

    def all_functions(self):
        for mod in self.modules.values():
            yield from mod.functions.values()
            for cls in mod.classes.values():
                yield from cls.methods.values()


def _terminal_name(node) -> str | None:
    """The rightmost identifier of a Name/Attribute chain."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _root_name(node) -> str | None:
    """The leftmost identifier of a Name/Attribute chain."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _render(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover — unparse is total on our input
        return "<expr>"


def _ctor_kind(value) -> str | None:
    """'Lock'|'RLock'|'Condition'|'Event'|'Thread' for a threading
    constructor call, else None."""
    if not isinstance(value, ast.Call):
        return None
    name = _terminal_name(value.func)
    if name in _LOCK_CTORS | _COND_CTORS | _EVENT_CTORS | _THREAD_CTORS:
        return name
    return None


# ---------------------------------------------------------------------
# phase A — declarations: locks, conditions, events, threads, globals
# ---------------------------------------------------------------------


def _scan_declarations(index: RepoIndex, mod: ModuleInfo, tree) -> None:
    # Module-level bindings (the global-candidate set: the write-only
    # TPF016 variant must never mistake a local for a module global).
    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        for t in targets:
            if isinstance(t, ast.Name):
                mod.global_names.add(t.id)
        # Module LOCKS register from top-level statements ONLY: a
        # function-local `helper = threading.Lock()` must not enter
        # mod.locks (it would credit `with helper:` as held coverage
        # everywhere in the module and mask real races).
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            kind = _ctor_kind(stmt.value)
            if kind in _LOCK_CTORS | _COND_CTORS:
                for t in targets:
                    if isinstance(t, ast.Name):
                        mod.locks[t.id] = kind
                        index.lock_attr_names.add(t.id)
                        if kind in _COND_CTORS:
                            index.cond_attr_names.add(t.id)
                            _note_alias(index, t.id, stmt.value)
    for node in ast.walk(tree):
        if isinstance(node, ast.Global):
            mod.global_names.update(node.names)
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        kind = _ctor_kind(node.value)
        if kind is None:
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for target in targets:
            # Attribute targets only here: an attribute assignment is
            # object state regardless of where it happens; bare-Name
            # locks were handled above at module level (a local Lock
            # binding is the caller's business, not the index's).
            if isinstance(target, ast.Attribute):
                attr = target.attr
                if kind in _LOCK_CTORS | _COND_CTORS:
                    index.lock_attr_names.add(attr)
                    if kind in _COND_CTORS:
                        index.cond_attr_names.add(attr)
                        _note_alias(index, attr, node.value)
                elif kind in _EVENT_CTORS:
                    index.event_attr_names.add(attr)
                elif kind in _THREAD_CTORS:
                    index.thread_attr_names.add(attr)


def _note_alias(index: RepoIndex, cond_name: str, value) -> None:
    """``X = threading.Condition(<lock>)`` wraps THE SAME mutex as
    ``<lock>``: record the alias so holding either token satisfies a
    guard established under the other (microbatch's ``_cond``/``_lock``
    pair). Resolvable only when the wrapped lock is a Name or
    ``self.<attr>`` — a parameter stays unaliased (coarse, and safe:
    an unresolved alias means two distinct canonical tokens, which can
    only ADD findings, never hide one)."""
    if not (isinstance(value, ast.Call) and value.args):
        return
    wrapped = _terminal_name(value.args[0])
    if wrapped and wrapped != cond_name:
        index.lock_aliases[cond_name] = wrapped


def _class_declarations(index: RepoIndex, cls: ClassInfo, node) -> None:
    """Per-class lock/event/thread attribute tables (``self.X = ...``
    anywhere in the class body, plus annotated attrs)."""
    for sub in ast.walk(node):
        if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
            continue
        kind = _ctor_kind(sub.value)
        # `self._thread: threading.Thread | None = None` style: the
        # annotation names the kind even when the value is None.
        if kind is None and isinstance(sub, ast.AnnAssign):
            ann = _render(sub.annotation)
            for k in ("Thread", "Event", "Condition", "RLock", "Lock"):
                if k in ann:
                    kind = k
                    break
        if kind is None:
            continue
        targets = (
            sub.targets if isinstance(sub, ast.Assign) else [sub.target]
        )
        for target in targets:
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                attr = target.attr
                if kind in _LOCK_CTORS | _COND_CTORS:
                    cls.locks[attr] = kind
                    index.lock_attr_names.add(attr)
                    if kind in _COND_CTORS:
                        index.cond_attr_names.add(attr)
                        _note_alias(index, attr, sub.value)
                elif kind in _EVENT_CTORS:
                    cls.events.add(attr)
                    index.event_attr_names.add(attr)
                elif kind in _THREAD_CTORS:
                    cls.threads.add(attr)
                    index.thread_attr_names.add(attr)


# ---------------------------------------------------------------------
# phase B — per-function scan: accesses, held locks, blocking calls
# ---------------------------------------------------------------------


class _FunctionScanner:
    """One function's body, walked with a lexical held-locks set.

    Nested function/lambda bodies are NOT descended into — each nested
    def is scanned as its own FuncInfo (a nested body runs when CALLED,
    on whatever thread calls it, with whatever locks that thread then
    holds — inheriting the definition site's locks would be wrong in
    both directions)."""

    def __init__(self, index: RepoIndex, mod: ModuleInfo, info: FuncInfo):
        self.index = index
        self.mod = mod
        self.info = info
        self.entry_lambda_lines: set = set()

    def _is_lock_expr(self, node) -> bool:
        name = _terminal_name(node)
        if name is None:
            return False
        if isinstance(node, ast.Name):
            return name in self.mod.locks
        # An attribute chain against the repo-wide lock-attr table:
        # `lane.cond` and `self._lock` both resolve by terminal name —
        # the coarse-but-sound direction (more held coverage, not less).
        return name in self.index.lock_attr_names

    def scan(self, node) -> None:
        self._collect_bindings(node)
        base = (
            frozenset({_CALLER_TOKEN})
            if self.info.locked_convention else frozenset()
        )
        body = [node.body] if isinstance(node, ast.Lambda) else node.body
        for stmt in body:
            self._walk(stmt, base, 0)

    def _collect_bindings(self, node) -> None:
        """Python scoping for the global pass: a name ASSIGNED anywhere
        in the function (params included) is a LOCAL unless declared
        ``global`` — a local that happens to shadow a guarded module
        global must not read as a race. Nested defs are their own
        scope and are skipped (they get their own scan)."""
        self._global_decls: set = set()
        assigned: set = set()
        args = getattr(node, "args", None)
        if args is not None:
            for a in (
                list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs)
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            ):
                assigned.add(a.arg)
        stack = list(ast.iter_child_nodes(node))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (
                ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
            )):
                continue
            if isinstance(sub, ast.Global):
                self._global_decls.update(sub.names)
            elif isinstance(sub, ast.Name) and isinstance(
                sub.ctx, (ast.Store, ast.Del)
            ):
                assigned.add(sub.id)
            stack.extend(ast.iter_child_nodes(sub))
        self._local_names = assigned

    # -- the recursive walk --

    def _walk(self, node, held: frozenset, loops: int) -> None:
        if isinstance(node, ast.Lambda):
            # A lambda that ESCAPED as a callback (fn=/target=/on_done=,
            # recorded by _record_call before this child visit) runs on
            # another thread with NO lock — it is its own FuncInfo. A
            # non-escaping lambda (a sort key, a min() selector) runs
            # synchronously right here, holding whatever we hold:
            # inline its body.
            if node.lineno not in self.entry_lambda_lines:
                self._walk(node.body, held, loops)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # its own FuncInfo
        if isinstance(node, (ast.With, ast.AsyncWith)):
            taken = set()
            for item in node.items:
                expr = item.context_expr
                if self._is_lock_expr(expr):
                    taken.add(_render(expr))
                self._walk(expr, held, loops)
            inner = held | frozenset(taken)
            for stmt in node.body:
                self._walk(stmt, inner, loops)
            return
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            for child in ast.iter_child_nodes(node):
                self._walk(child, held, loops + 1)
            return
        if isinstance(node, ast.Call):
            self._record_call(node, held, loops)
        elif isinstance(node, ast.Attribute):
            self._record_attribute(node, held)
        elif isinstance(node, ast.Name):
            self._record_global(node, held)
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                             ast.Delete)):
            self._record_store_shapes(node, held)
        for child in ast.iter_child_nodes(node):
            self._walk(child, held, loops)
        # `t = threading.Thread(...)` / `self._t = Thread(...)`: bind
        # the spawn (recorded while walking the value) to its target
        # name, the TPF018b join-evidence key.
        if (
            isinstance(node, ast.Assign)
            and _ctor_kind(node.value) in _THREAD_CTORS
            and node.targets
        ):
            bound = _terminal_name(node.targets[0])
            for spawn in self.info.spawns:
                if spawn.line == node.value.lineno:
                    spawn.bound_to = bound

    # -- accesses --

    def _record_attribute(self, node: ast.Attribute, held) -> None:
        if not (
            isinstance(node.value, ast.Name) and node.value.id == "self"
        ):
            return
        self.info.accesses.append(Access(
            attr=node.attr,
            write=isinstance(node.ctx, (ast.Store, ast.Del)),
            line=node.lineno, locks=held,
        ))

    def _record_global(self, node: ast.Name, held) -> None:
        if node.id not in self.mod.global_names:
            return
        if (
            node.id in self._local_names
            and node.id not in self._global_decls
        ):
            return  # a local shadowing the module name, not the global
        self.info.global_accesses.append(Access(
            attr=node.id,
            write=isinstance(node.ctx, (ast.Store, ast.Del)),
            line=node.lineno, locks=held,
        ))

    def _record_store_shapes(self, node, held) -> None:
        """Writes the plain ctx walk misses: subscript stores
        (``self._x[k] = v`` / ``GLOBAL[k] = v``) and their delete
        forms. (Attribute/Name targets already carry Store ctx.)"""
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.Delete):
            targets = node.targets
        else:
            targets = [node.target]
        for t in targets:
            if not isinstance(t, ast.Subscript):
                continue
            base = t.value
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                self.info.accesses.append(Access(
                    attr=base.attr, write=True, line=t.lineno, locks=held,
                ))
            elif (
                isinstance(base, ast.Name)
                and base.id in self.mod.global_names
                and not (base.id in self._local_names
                         and base.id not in self._global_decls)
            ):
                self.info.global_accesses.append(Access(
                    attr=base.id, write=True, line=t.lineno, locks=held,
                ))

    # -- calls: callees, entries, blocking shapes, waits, spawns --

    def _record_call(self, node: ast.Call, held, loops: int) -> None:
        func = node.func
        name = _terminal_name(func)
        # call-graph edge
        if isinstance(func, ast.Name):
            self.info.callees.append(("name", func.id))
        elif isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                self.info.callees.append(("self", func.attr))
            else:
                self.info.callees.append(("attr", func.attr))

        # mutation call == write access to the receiver
        if name in _MUTATORS and isinstance(func, ast.Attribute):
            base = func.value
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                self.info.accesses.append(Access(
                    attr=base.attr, write=True, line=node.lineno,
                    locks=held,
                ))
            elif (
                isinstance(base, ast.Name)
                and base.id in self.mod.global_names
                and not (base.id in self._local_names
                         and base.id not in self._global_decls)
            ):
                self.info.global_accesses.append(Access(
                    attr=base.id, write=True, line=node.lineno, locks=held,
                ))

        # thread spawn (TPF018b)
        if name in _THREAD_CTORS:
            daemon = None
            for kw in node.keywords:
                if kw.arg == "daemon":
                    daemon = (
                        bool(kw.value.value)
                        if isinstance(kw.value, ast.Constant) else True
                    )
            self.info.spawns.append(ThreadSpawn(
                line=node.lineno, daemon=daemon, bound_to=None,
            ))

        # entry registration: Thread target / submit / run_in_executor /
        # callback kwargs — anything that lets a callable escape onto
        # another thread.
        entry_args = []
        if name in _THREAD_CTORS:
            entry_args += [
                kw.value for kw in node.keywords if kw.arg == "target"
            ]
        if name == "submit" and node.args:
            entry_args.append(node.args[0])
        if name == "run_in_executor" and len(node.args) >= 2:
            entry_args.append(node.args[1])
        if name in ("call_soon_threadsafe", "call_soon",
                    "add_done_callback") and node.args:
            entry_args.append(node.args[0])
        entry_args += [
            kw.value for kw in node.keywords
            if kw.arg in _CALLBACK_KWARGS
        ]
        for arg in entry_args:
            self._mark_entry(arg)

        # TPF017 blocking shapes
        self._record_blocking(node, func, name, held)

        # storage-pass raw material (TPF019–021): every filesystem
        # touchpoint, recorded during this same walk
        self._record_file_op(node, func, name)

        # TPF018a condition waits
        if name == "wait" and isinstance(func, ast.Attribute):
            recv = func.value
            if _terminal_name(recv) in self.index.cond_attr_names:
                self.info.cond_waits.append(CondWait(
                    expr=_render(recv), line=node.lineno,
                    in_loop=loops > 0,
                ))

    def _record_blocking(self, node, func, name, held) -> None:
        if not held:
            return
        root = _root_name(func) if isinstance(func, ast.Attribute) else None
        what = None
        if name == "sleep" and root != "asyncio":
            what = _render(func)
        elif isinstance(func, ast.Name) and name in _BLOCKING_NAMES:
            what = name
        elif root in _BLOCKING_ROOTS:
            what = _render(func)
        elif name == "result" and isinstance(func, ast.Attribute):
            what = _render(func)
        elif name == "wait" and isinstance(func, ast.Attribute):
            recv_name = _terminal_name(func.value)
            # Event.wait blocks WITHOUT releasing the lock; a
            # Condition.wait releases it — that is the exemption.
            if (
                recv_name in self.index.event_attr_names
                and recv_name not in self.index.cond_attr_names
            ):
                what = _render(func)
        elif name == "join" and isinstance(func, ast.Attribute):
            recv_name = _terminal_name(func.value)
            if recv_name in self.index.thread_attr_names or (
                recv_name and "thread" in recv_name.lower()
            ):
                what = _render(func)
        if what is not None:
            self.info.blocking.append(BlockingCall(
                what=what, line=node.lineno, locks=held,
            ))

    def _record_file_op(self, node, func, name) -> None:
        """Record one filesystem touchpoint (see :class:`FileOp`)."""
        root = _root_name(func) if isinstance(func, ast.Attribute) else None
        is_attr = isinstance(func, ast.Attribute)
        kind = None
        target = ""
        mode = ""
        if name == "open" and (isinstance(func, ast.Name) or is_attr):
            kind = "open"
            if node.args:
                target = _render(node.args[0]) if not is_attr else ""
            if is_attr:
                target = _render(func.value)
            for i, arg in enumerate(node.args):
                if i == (1 if not is_attr else 0) and isinstance(
                    arg, ast.Constant
                ) and isinstance(arg.value, str):
                    mode = arg.value
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(
                    kw.value, ast.Constant
                ) and isinstance(kw.value.value, str):
                    mode = kw.value.value
        elif root == "os" and name in _OS_RENAMES:
            kind = "rename"
            if node.args:
                target = _render(node.args[-1])  # the destination
        elif root == "shutil" and name == "move":
            kind = "rename"
            if len(node.args) >= 2:
                target = _render(node.args[1])
        elif is_attr and name == "rename" and root != "os":
            kind = "rename"
            target = _render(node.args[0]) if node.args else ""
        elif root == "shutil":
            kind = "shutil"
            if node.args:
                target = _render(node.args[0])
        elif root in ("np", "numpy") and name in _NP_IO:
            kind = "np"
            if node.args:
                target = _render(node.args[0])
        elif root == "json" and name in _JSON_IO:
            kind = "json"
            idx = 1 if name == "dump" else 0
            if len(node.args) > idx:
                target = _render(node.args[idx])
        elif is_attr and name in _PATH_WRITES:
            kind = "path_write"
            target = _render(func.value)
        elif is_attr and name in _PATH_READS:
            kind = "path_read"
            target = _render(func.value)
        elif is_attr and name in _PATH_FS:
            kind = "path_fs"
            target = _render(func.value)
        if kind is not None:
            self.info.file_ops.append(FileOp(
                kind=kind, what=_render(func), target=target, mode=mode,
                line=node.lineno,
            ))

    def _mark_entry(self, arg) -> None:
        """Resolve a callable reference escaping onto another thread."""
        if isinstance(arg, ast.Lambda):
            self.entry_lambda_lines.add(arg.lineno)
            return
        if isinstance(arg, ast.Call):
            # partial(self._loop, ...): the wrapped callable is the
            # first argument.
            if _terminal_name(arg.func) == "partial" and arg.args:
                self._mark_entry(arg.args[0])
            return
        if isinstance(arg, ast.Attribute):
            # `target=self._loop` and `target=worker.run` both resolve
            # by terminal method name repo-wide (the thread does not
            # care which class it entered through).
            self.mod.entry_refs.add(("attr", arg.attr))
        elif isinstance(arg, ast.Name):
            self.mod.entry_refs.add(("name", arg.id))


# ---------------------------------------------------------------------
# phase B' — module walk: build FuncInfos, wire entries
# ---------------------------------------------------------------------


class _ModuleBuilder(ast.NodeVisitor):
    def __init__(self, index: RepoIndex, mod: ModuleInfo, tree):
        self.index = index
        self.mod = mod
        self.tree = tree
        self._cls_stack: list[ClassInfo] = []
        self._fn_stack: list[str] = []

    def build(self) -> None:
        self.visit(self.tree)
        # join / daemon-flip evidence, module-wide (TPF018b)
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
            ):
                recv = _terminal_name(node.func.value)
                if recv:
                    self.mod.joined_names.add(recv)
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and node.targets[0].attr == "daemon"
            ):
                recv = _terminal_name(node.targets[0].value)
                if recv:
                    self.mod.daemon_set_names.add(recv)

    def visit_ClassDef(self, node) -> None:
        cls = ClassInfo(
            name=node.name, module=self.mod,
            bases=[_terminal_name(b) for b in node.bases
                   if _terminal_name(b)],
        )
        _class_declarations(self.index, cls, node)
        self.mod.classes[node.name] = cls
        self._cls_stack.append(cls)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _visit_function(self, node) -> None:
        cls = self._cls_stack[-1] if self._cls_stack else None
        name = getattr(node, "name", "<lambda>")
        qual_parts = ([cls.name] if cls else []) + self._fn_stack + [name]
        info = FuncInfo(
            qual=".".join(qual_parts), name=name,
            cls=cls.name if cls else None,
            lineno=node.lineno, module=self.mod,
        )
        scanner = _FunctionScanner(self.index, self.mod, info)
        scanner.scan(node)
        if isinstance(node, ast.Lambda):
            self.mod.functions.setdefault(
                f"{info.qual}@{node.lineno}", info
            )
        elif cls is not None and not self._fn_stack:
            cls.methods[name] = info
        else:
            self.mod.functions.setdefault(info.qual, info)
        self.index.methods_by_name.setdefault(name, []).append(info)
        self._fn_stack.append(name)
        for sub, owner in _direct_nested(node):
            if owner is not node:
                continue
            if isinstance(sub, ast.Lambda):
                if sub.lineno in scanner.entry_lambda_lines:
                    # The literal lambda escaped as a callback: its
                    # BODY is the thread entry.
                    self._visit_entry_lambda(sub)
                # else: inlined into this function's scan above
            else:
                self._visit_function(sub)
        self._fn_stack.pop()

    def _visit_entry_lambda(self, node) -> None:
        before = set(self.mod.functions)
        self._visit_function(node)
        for key in set(self.mod.functions) - before:
            self.mod.functions[key].is_entry = True

    def visit_FunctionDef(self, node) -> None:
        self._visit_function(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._visit_function(node)

    def visit_Lambda(self, node) -> None:
        self._visit_function(node)


def _direct_nested(root):
    """(nested def/lambda, owning function) pairs for defs directly
    inside ``root`` — not inside a deeper def (those belong to their own
    parent's visit)."""
    out = []
    stack = [(child, root) for child in ast.iter_child_nodes(root)]
    while stack:
        node, owner = stack.pop()
        if isinstance(node, (
            ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
        )):
            out.append((node, owner))
            continue  # its own visit walks deeper
        stack.extend(
            (child, owner) for child in ast.iter_child_nodes(node)
        )
    return [(n, o) for n, o in out if o is root]


# ---------------------------------------------------------------------
# index construction
# ---------------------------------------------------------------------


def build_index(root: str) -> RepoIndex:
    """Walk every ``.py`` under ``root`` into one cross-file index."""
    index = RepoIndex(root=os.path.abspath(root))
    parsed: list[tuple[ModuleInfo, ast.AST]] = []
    for dirpath, dirnames, filenames in os.walk(index.root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, index.root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                source = f.read()
            mod = ModuleInfo(path=path, rel=rel)
            mod.noqa = _noqa_lines(source)
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError:
                continue  # the linter owns TPF000 syntax reporting
            _scan_declarations(index, mod, tree)
            index.modules[rel] = mod
            parsed.append((mod, tree))
    # Phase B needs the COMPLETE lock tables (a `lane.cond` in module A
    # resolves against a Condition declared in any module) — hence the
    # two passes.
    for mod, tree in parsed:
        _ModuleBuilder(index, mod, tree).build()
    # entry wiring: escaped callable references -> FuncInfos
    for mod in index.modules.values():
        for kind, name in mod.entry_refs:
            for target in _resolve(index, mod, None, kind, name):
                target.is_entry = True
    # handler-method heuristic
    for fn in index.all_functions():
        if fn.cls and (
            fn.name in _HANDLER_NAMES
            or fn.name.startswith(_HANDLER_PREFIXES)
        ):
            fn.is_entry = True
    return index


def _resolve(index: RepoIndex, mod: ModuleInfo, cls_family,
             kind: str, name: str):
    """Call/reference targets for one (kind, name) edge."""
    if kind == "name":
        fn = mod.functions.get(name)
        if fn is not None:
            return [fn]
        # nested defs are keyed by qual; fall back to same-module
        # by-name lookup (`call_soon_threadsafe(_stop)` inside a method)
        return [
            f for f in index.methods_by_name.get(name, ())
            if f.module is mod
        ]
    if kind == "self" and cls_family is not None:
        out = [
            cls.methods[name] for cls in cls_family
            if name in cls.methods
        ]
        if out:
            return out
    # attr (or an unresolved self): every repo method with the name —
    # class-hierarchy-insensitive, deliberately over-approximate in the
    # "more reachable" direction.
    return [
        fn for fn in index.methods_by_name.get(name, ()) if fn.cls
    ]


# ---------------------------------------------------------------------
# class families (inheritance closure within the repo)
# ---------------------------------------------------------------------


def _class_families(index: RepoIndex) -> list[list[ClassInfo]]:
    """Union-find over repo-internal inheritance edges: a family shares
    one attribute namespace (``self`` is one object), so guarding
    evidence in a base method covers accesses in a derived one."""
    by_name: dict[str, list[ClassInfo]] = {}
    for mod in index.modules.values():
        for cls in mod.classes.values():
            by_name.setdefault(cls.name, []).append(cls)
    classes = [c for group in by_name.values() for c in group]
    ids = {id(c): i for i, c in enumerate(classes)}
    parent = list(range(len(classes)))

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    for cls in classes:
        for base in cls.bases:
            for target in by_name.get(base, ()):
                ra, rb = find(ids[id(cls)]), find(ids[id(target)])
                if ra != rb:
                    parent[ra] = rb
    families: dict[int, list[ClassInfo]] = {}
    for cls in classes:
        families.setdefault(find(ids[id(cls)]), []).append(cls)
    return list(families.values())


# ---------------------------------------------------------------------
# reachability
# ---------------------------------------------------------------------


def _reachable_functions(index: RepoIndex, families) -> set:
    family_of: dict[str, list] = {}
    for fam in families:
        for cls in fam:
            family_of[f"{cls.module.rel}::{cls.name}"] = fam
    work = [fn for fn in index.all_functions() if fn.is_entry]
    reached = {id(fn) for fn in work}
    while work:
        fn = work.pop()
        fam = (
            family_of.get(f"{fn.module.rel}::{fn.cls}")
            if fn.cls else None
        )
        for kind, name in fn.callees:
            for target in _resolve(index, fn.module, fam, kind, name):
                if id(target) not in reached:
                    reached.add(id(target))
                    work.append(target)
    return reached


# ---------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One concurrency finding + its line-free baseline fingerprint."""

    rule: str
    message: str
    path: str  # display path
    rel: str  # /-normalized, root-relative (the fingerprint's file)
    line: int
    scope: str  # nearest named enclosing scope, e.g. "Class.method"
    subject: str  # the attr / call / resource the finding is about

    @property
    def fingerprint(self) -> tuple:
        return (self.rule, self.rel, self.scope, self.subject)

    def diagnostic(self) -> Diagnostic:
        return Diagnostic(
            pass_name=_PASS, code=self.rule,
            message=f"{self.message} — {RULES[self.rule]}",
            where=f"{self.path}:{self.line}",
        )


def _named_scope(fn: FuncInfo) -> str:
    """The fingerprint scope: the qualname with lambda segments dropped
    (lambdas move lines; their nearest named parent does not)."""
    parts = [p for p in fn.qual.split(".") if not p.startswith("<lambda")]
    return ".".join(parts) or fn.qual


def _canon_token(index: RepoIndex, token: str) -> str:
    """One canonical name per mutex: the token's terminal segment
    (``self._lock`` → ``_lock``, ``lane.cond`` → ``cond``), chased
    through the Condition-alias map (``_cond`` → ``_lock`` when
    ``Condition(self._lock)`` was seen anywhere in the repo)."""
    if token == _CALLER_TOKEN:
        return token
    name = token.rsplit(".", 1)[-1]
    seen = set()
    while name in index.lock_aliases and name not in seen:
        seen.add(name)
        name = index.lock_aliases[name]
    return name


def _infer_guard(write_sets: list) -> set:
    """THE mutex an attribute is disciplined under, from the canonical
    token sets of its locked writes. Normally the intersection (every
    write holds it); when writes disagree (already a wrong-lock bug at
    one of the sites), fall back to the majority mutex so the minority
    sites — not the whole attribute — read as the violations. Empty
    when every locked write is *_locked-convention only (the callee
    cannot name its caller's lock)."""
    real = [s - {_CALLER_TOKEN} for s in write_sets]
    real = [s for s in real if s]
    if not real:
        return set()
    inter = set.intersection(*real)
    if inter:
        return inter
    counts: dict[str, int] = {}
    for s in real:
        for t in s:
            counts[t] = counts.get(t, 0) + 1
    top = max(counts.items(), key=lambda kv: (kv[1], kv[0]))[0]
    return {top}


def _guard_violation(index, held: frozenset, guard: set) -> str | None:
    """None when ``held`` satisfies ``guard``; else the violation
    flavor. The SAME mutex must be held — a disjoint lock is the
    classic wrong-lock race, just as torn as no lock at all. An empty
    guard (convention-only) accepts any held lock."""
    held_canon = {_canon_token(index, t) for t in held}
    if _CALLER_TOKEN in held_canon:
        return None  # the *_locked convention: caller vouches
    if not held_canon:
        return "without a lock"
    if not guard or held_canon & guard:
        return None
    return (
        f"under {', '.join(sorted(held_canon))} — a DIFFERENT lock"
    )


def analyze_index(index: RepoIndex) -> list[Finding]:
    findings: list[Finding] = []
    families = _class_families(index)
    reached = _reachable_functions(index, families)

    def shared(funcs) -> bool:
        return any(id(fn) in reached or fn.is_entry for fn in funcs)

    # --- TPF016 over class families ---
    for fam in families:
        non_data: set = set()
        for cls in fam:
            # locks/conditions/events/threads are synchronization
            # OBJECTS, not data: touching them is how you synchronize.
            non_data |= cls.events | cls.threads | set(cls.locks)
        everyone = [
            m for cls in fam for m in cls.methods.values()
        ] + _nested_of(fam)
        if not shared(everyone):
            continue
        # A class with no lock attrs of its own still participates: an
        # attribute written under a MODULE lock (MetricsLogger._seq
        # under _SEQ_LOCK) is guarded all the same.
        write_sets: dict[str, list] = {}
        first_scope: dict[str, str] = {}
        for fn in everyone:
            if fn.name in _INIT_METHODS:
                continue
            for acc in fn.accesses:
                if acc.write and acc.locks and acc.attr not in non_data:
                    write_sets.setdefault(acc.attr, []).append(
                        {_canon_token(index, t) for t in acc.locks}
                    )
                    first_scope.setdefault(acc.attr, _named_scope(fn))
        if not write_sets:
            continue
        guards = {
            attr: _infer_guard(sets)
            for attr, sets in write_sets.items()
        }
        for fn in everyone:
            if fn.name in _INIT_METHODS or fn.locked_convention:
                continue
            for acc in fn.accesses:
                if acc.attr not in guards:
                    continue
                how = _guard_violation(
                    index, acc.locks, guards[acc.attr]
                )
                if how is None:
                    continue
                verb = "written" if acc.write else "read"
                named = sorted(guards[acc.attr]) or [
                    "its lock (held via *_locked callers)"
                ]
                findings.append(Finding(
                    rule="TPF016",
                    message=(
                        f"self.{acc.attr} {verb} {how}; it is "
                        f"written under {', '.join(named)} in "
                        f"{first_scope[acc.attr]} (inferred guarded)"
                    ),
                    path=fn.module.path, rel=fn.module.rel,
                    line=acc.line, scope=_named_scope(fn),
                    subject=acc.attr,
                ))

    # --- TPF016 over module-global lock discipline (writes only:
    # global reads are pervasively constants; lost updates are the
    # class that corrupts) ---
    for mod in index.modules.values():
        if not mod.locks:
            continue
        funcs = list(mod.functions.values()) + [
            m for cls in mod.classes.values()
            for m in cls.methods.values()
        ]
        if not shared(funcs):
            continue
        write_sets = {}
        first_scope = {}
        for fn in funcs:
            for acc in fn.global_accesses:
                if acc.write and acc.locks and acc.attr not in mod.locks:
                    write_sets.setdefault(acc.attr, []).append(
                        {_canon_token(index, t) for t in acc.locks}
                    )
                    first_scope.setdefault(acc.attr, _named_scope(fn))
        guards = {
            attr: _infer_guard(sets)
            for attr, sets in write_sets.items()
        }
        for fn in funcs:
            if fn.locked_convention:
                continue
            for acc in fn.global_accesses:
                if acc.attr not in guards or not acc.write:
                    continue
                how = _guard_violation(
                    index, acc.locks, guards[acc.attr]
                )
                if how is None:
                    continue
                named = sorted(guards[acc.attr]) or ["its lock"]
                findings.append(Finding(
                    rule="TPF016",
                    message=(
                        f"module global {acc.attr} written {how}; it "
                        f"is written under {', '.join(named)} in "
                        f"{first_scope[acc.attr]} (inferred guarded)"
                    ),
                    path=mod.path, rel=mod.rel, line=acc.line,
                    scope=_named_scope(fn), subject=acc.attr,
                ))

    # --- TPF017 ---
    for fn in index.all_functions():
        for call in fn.blocking:
            findings.append(Finding(
                rule="TPF017",
                message=(
                    f"{call.what}(...) while holding "
                    f"{', '.join(sorted(call.locks))}"
                ),
                path=fn.module.path, rel=fn.module.rel, line=call.line,
                scope=_named_scope(fn),
                subject=call.what.split(".")[-1],
            ))

    # --- TPF018a: un-looped Condition.wait ---
    for fn in index.all_functions():
        for wait in fn.cond_waits:
            if wait.in_loop:
                continue
            findings.append(Finding(
                rule="TPF018",
                message=(
                    f"{wait.expr}.wait() outside a predicate loop "
                    "(spurious wakeup / missed notify hazard)"
                ),
                path=fn.module.path, rel=fn.module.rel, line=wait.line,
                scope=_named_scope(fn), subject="wait",
            ))

    # --- TPF018b: non-daemon threads nobody joins ---
    for mod in index.modules.values():
        funcs = list(mod.functions.values()) + [
            m for cls in mod.classes.values()
            for m in cls.methods.values()
        ]
        for fn in funcs:
            for spawn in fn.spawns:
                if spawn.daemon is not None:
                    continue
                if spawn.bound_to and (
                    spawn.bound_to in mod.joined_names
                    or spawn.bound_to in mod.daemon_set_names
                ):
                    continue
                if spawn.bound_to is None and mod.joined_names:
                    # Unbound spawn in a module that joins SOMETHING:
                    # the `threads.append(Thread(...))` + `for t in
                    # threads: t.join()` shape — the binding is a list
                    # element, invisible statically.
                    continue
                findings.append(Finding(
                    rule="TPF018",
                    message=(
                        "non-daemon Thread with no reachable join() or "
                        "daemon flag in this module"
                    ),
                    path=mod.path, rel=mod.rel, line=spawn.line,
                    scope=_named_scope(fn), subject="thread",
                ))

    # noqa parity with the per-file linter
    findings = [
        f for f in findings
        if f.rule not in index.modules[f.rel].noqa.get(f.line, ())
    ]
    findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    return findings


def _nested_of(fam) -> list:
    """FuncInfos nested under a family method (gauge-callback lambdas,
    nested defs defined inside methods — their ``self`` is the
    enclosing method's)."""
    out = []
    for cls in fam:
        prefix = f"{cls.name}."
        for key, fn in cls.module.functions.items():
            if fn.cls == cls.name and key.startswith(prefix):
                out.append(fn)
    return out


# ---------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------


# The one baseline implementation lives in tpuflow/analysis/baseline.py
# (shared with the storage pass); these bindings keep this module's
# public surface — tests and the CLI import from here.

_BASELINE_COMMENT = (
    "Triaged-accepted concurrency findings "
    "(python -m tpuflow.analysis repo --baseline). Entries are "
    "fingerprinted (rule, file, scope, subject) — no line "
    "numbers, so they survive unrelated edits. Every entry "
    "carries a one-line justification; stale entries (finding "
    "gone) are reported and must be pruned."
)


def load_baseline(path: str) -> list[dict]:
    """Parse + validate the concurrency baseline (see
    :mod:`tpuflow.analysis.baseline`); raises :class:`BaselineError`
    naming the file and field on anything malformed."""
    return _load_baseline(path, RULES)


def write_baseline(path: str, findings: list[Finding],
                   reasons: dict | None = None) -> int:
    """(Re)write the baseline accepting every current finding; reasons
    survive regeneration (and pure file moves — see
    :func:`tpuflow.analysis.baseline.write_baseline`)."""
    return _write_baseline(
        path, findings, reasons, comment=_BASELINE_COMMENT
    )


# ---------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------


def default_root() -> str:
    import tpuflow

    return os.path.dirname(os.path.abspath(tpuflow.__file__))


def default_baseline_path(root: str) -> str:
    """``<root>/analysis/concurrency_baseline.json`` when the root has
    an analysis/ package (the tpuflow layout), else flat in the root
    (fixture dirs)."""
    nested = os.path.join(root, "analysis")
    if os.path.isdir(nested):
        return os.path.join(nested, "concurrency_baseline.json")
    return os.path.join(root, "concurrency_baseline.json")


def analyze_repo(
    root: str | None = None,
    baseline_path: str | None = "auto",
    index: RepoIndex | None = None,
) -> list[Diagnostic]:
    """The gate-shaped entry: analyze ``root`` (default: the installed
    tpuflow package), subtract the baseline, and report the remainder
    PLUS any stale baseline entries as :class:`Diagnostic` records.

    ``baseline_path="auto"`` resolves next to the root (and is simply
    skipped when absent); ``None`` disables baselining. A malformed
    baseline raises :class:`BaselineError` — loud, naming file+field.
    Pass ``index`` to reuse an already-built walk (the CLI builds ONE
    index for both repo-wide passes).
    """
    root = root or default_root()
    if baseline_path == "auto":
        candidate = default_baseline_path(root)
        baseline_path = candidate if os.path.exists(candidate) else None
    findings = analyze_index(index if index is not None
                             else build_index(root))
    entries = load_baseline(baseline_path) if baseline_path else []
    by_key: dict[tuple, dict] = {}
    for e in entries:
        by_key.setdefault(_baseline_key(e), e)
    used: set = set()
    out: list[Diagnostic] = []
    for f in findings:
        if f.fingerprint in by_key:
            used.add(f.fingerprint)
            continue
        out.append(f.diagnostic())
    for e in entries:
        if _baseline_key(e) not in used:
            out.append(Diagnostic(
                pass_name=_PASS, code=STALE_CODE,
                message=(
                    f"stale baseline entry {e['rule']} "
                    f"{e['file']}::{e['scope']}::{e['subject']} — the "
                    "finding it accepts no longer exists; prune it "
                    f"from {baseline_path}"
                ),
                where=baseline_path,
            ))
    return out
