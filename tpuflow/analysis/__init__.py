"""Preflight static analysis: validate a whole job WITHOUT running it.

Four passes over a :class:`~tpuflow.api.config.TrainJobConfig` (and one
over the framework itself), each collecting :class:`Diagnostic` records
instead of raising, so one preflight reports every problem in a spec:

1. **spec** (:mod:`tpuflow.analysis.spec`) — cross-field config checks:
   registry keys (model/loss/optimizer), schema, windowing vs synthetic
   length, stream knobs, fault-spec grammar (incl. ``TPUFLOW_FAULTS``).
2. **shape** (:mod:`tpuflow.analysis.shapes`) — ``jax.eval_shape``
   abstract interpretation through schema → windowing → model
   init/apply → loss: shape/dtype bugs in milliseconds, no compile.
3. **plan** (:mod:`tpuflow.analysis.plan`) — mesh/divisibility checks
   for dp/tp/pp/ep (shared with the training path's own validation).
4. **lint** (:mod:`tpuflow.analysis.linter`) — AST rules over the
   ``tpuflow`` package itself (host syncs in jit, untraced randomness,
   mutable defaults, unknown fault sites); tier-1 runs it as a gate.
5. **concurrency** (:mod:`tpuflow.analysis.concurrency`) — the
   REPO-WIDE pass: one AST index over every class, lock attribute,
   ``with <lock>`` region, and thread entry point, feeding
   lock-discipline race detection (TPF016 guarded-attribute access
   outside its lock, TPF017 blocking call under a lock, TPF018
   thread-lifecycle hygiene) with a committed baseline for
   triaged-accepted sites; tier-1 runs it as a gate too.

Entry points: ``python -m tpuflow.analysis spec.json`` for CI,
``python -m tpuflow.analysis repo`` for the concurrency pass,
``tpuflow.cli --preflight`` (on by default; ``--no-preflight`` escapes),
and ``train()``/``supervise()``/``serve`` fail-fast on submission.
"""

from __future__ import annotations

from tpuflow.analysis.diagnostics import (  # noqa: F401
    Diagnostic,
    PreflightError,
    PreflightReport,
)

DEFAULT_PASSES = ("spec", "plan", "shape")


def preflight(
    config,
    *,
    passes: tuple = DEFAULT_PASSES,
    device_count: int | None = None,
    local_device_count: int | None = None,
    process_count: int = 1,
) -> PreflightReport:
    """Run the requested analysis passes over one job config.

    Never raises on a bad job — returns the aggregated report (use
    :func:`ensure_preflight` for the raising flavor). Pass order is
    fixed spec → plan → shape so the cheap pure-Python passes report
    before the abstract interpreter runs.
    """
    report = PreflightReport(passes_run=tuple(passes))

    def _run(pass_name, fn):
        # Per-pass safety net: a config broken enough to crash one
        # pass's arithmetic (a string where an int belongs) must become
        # a finding, not a traceback that hides every other finding.
        try:
            report.extend(fn())
        except Exception as e:  # noqa: BLE001 — the net IS the contract
            report.extend([Diagnostic(
                pass_name=pass_name, code=f"{pass_name}.unusable_config",
                message=f"{pass_name} pass could not run on this config "
                f"({type(e).__name__}: {e}) — a field has an unusable "
                "type or value",
            )])

    if "spec" in passes:
        from tpuflow.analysis.spec import validate_spec

        _run("spec", lambda: validate_spec(config))
    if "plan" in passes:
        from tpuflow.analysis.plan import check_plan

        _run("plan", lambda: check_plan(
            config,
            device_count=device_count,
            local_device_count=local_device_count,
            process_count=process_count,
        ))
    if "shape" in passes:
        from tpuflow.analysis.shapes import shape_dryrun

        _run("shape", lambda: shape_dryrun(config))
    if "lint" in passes:
        from tpuflow.analysis.linter import lint_package

        _run("lint", lambda: lint_package())
    if "concurrency" in passes:
        from tpuflow.analysis.concurrency import analyze_repo

        _run("concurrency", lambda: analyze_repo())
    return report


def ensure_preflight(config, **kwargs) -> PreflightReport:
    """Run :func:`preflight` and raise :class:`PreflightError` (a
    ``ValueError``) when any pass found errors — the fail-fast flavor
    every submission seam (train/supervise/serve) calls."""
    report = preflight(config, **kwargs)
    if not report.ok:
        raise PreflightError(report)
    return report


__all__ = [
    "DEFAULT_PASSES",
    "Diagnostic",
    "PreflightError",
    "PreflightReport",
    "ensure_preflight",
    "preflight",
]
