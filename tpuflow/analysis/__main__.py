"""``python -m tpuflow.analysis`` — the CI entry point for preflight.

Usage::

    python -m tpuflow.analysis spec.json [spec2.json ...] [--devices N]
    python -m tpuflow.analysis --lint [PATH]
    python -m tpuflow.analysis spec.json --lint     # both
    python -m tpuflow.analysis repo [ROOT] [--json|--baseline]

Each positional argument is a JSON job spec in the job-runner contract
(``tpuflow.serve.spec_to_config`` — camelCase or snake_case fields); the
spec, plan, and shape passes run over each and EVERY finding is printed
(one run reports all the errors, not the first). ``--devices`` supplies
the target device count for plan checking without touching a backend —
nothing here compiles, allocates, or initializes accelerator state.
``--lint`` runs the framework linter over ``tpuflow`` (or PATH).

``repo`` runs the repo-wide passes — concurrency (TPF016–TPF018,
``tpuflow/analysis/concurrency.py``) and storage (TPF019–TPF021,
``tpuflow/analysis/storage.py``) — over ONE shared AST walk:
findings minus each pass's committed baseline,
``--passes concurrency,storage`` to select, ``--json`` for machine
output, ``--baseline`` to accept the current findings into each
selected pass's baseline file (existing justifications are preserved
per fingerprint, and survive pure file moves).

Exit status: 0 when no pass reported an error, 1 otherwise, 2 for
unusable inputs (missing/unparseable spec file, malformed baseline,
missing analysis root).
"""

from __future__ import annotations

import argparse
import json
import sys


def _repo_main(argv: list[str]) -> int:
    """The ``repo`` subcommand: repo-wide static analysis passes."""
    import os

    from tpuflow.analysis import concurrency, storage
    from tpuflow.analysis.baseline import BaselineError

    # Pass registry, in gate order. Both passes ride ONE AST walk
    # (concurrency.build_index); storage only classifies the FileOps
    # that walk already recorded.
    passes = {"concurrency": concurrency, "storage": storage}

    ap = argparse.ArgumentParser(
        prog="python -m tpuflow.analysis repo",
        description="repo-wide static analysis: concurrency "
                    "(TPF016-TPF018 lock discipline) and storage "
                    "(TPF019-TPF021 storage contract) over the package",
    )
    ap.add_argument("root", nargs="?", default=None, metavar="ROOT",
                    help="directory to analyze (default: the installed "
                         "tpuflow package)")
    ap.add_argument("--passes", default="concurrency,storage",
                    metavar="NAMES",
                    help="comma-separated pass list: concurrency, "
                         "storage (default: both)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--baseline", action="store_true",
                    help="accept the current findings into each selected "
                         "pass's baseline file (existing entries keep "
                         "their reasons; new ones get a TODO placeholder "
                         "to edit)")
    ap.add_argument("--baseline-file", default=None, metavar="PATH",
                    help="baseline path override (default: each pass's "
                         "<ROOT>/analysis/<pass>_baseline.json when ROOT "
                         "has an analysis/ dir, else flat). Pair with a "
                         "single --passes value: one file holds one "
                         "pass's rules.")
    args = ap.parse_args(argv)

    selected = []
    for name in args.passes.split(","):
        name = name.strip()
        if not name:
            continue
        if name not in passes:
            print(
                f"repo: unknown pass {name!r} "
                f"(expected: {', '.join(passes)})",
                file=sys.stderr,
            )
            return 2
        if name not in selected:
            selected.append(name)
    if not selected:
        print("repo: --passes selected nothing", file=sys.stderr)
        return 2

    root = args.root or concurrency.default_root()
    if not os.path.isdir(root):
        print(f"repo: {root}: not a directory", file=sys.stderr)
        return 2
    explicit_baseline = args.baseline_file is not None
    index = concurrency.build_index(root)
    try:
        if args.baseline:
            for name in selected:
                mod = passes[name]
                baseline_file = (
                    args.baseline_file
                    or mod.default_baseline_path(root)
                )
                findings = mod.analyze_index(index)
                reasons = {}
                if os.path.exists(baseline_file):
                    reasons = {
                        mod._baseline_key(e): e["reason"]
                        for e in mod.load_baseline(baseline_file)
                    }
                n = mod.write_baseline(baseline_file, findings, reasons)
                print(
                    f"repo: accepted {n} {name} finding(s) into "
                    f"{baseline_file} (edit each TODO reason into a "
                    "real justification)"
                )
            return 0
        # An EXPLICIT --baseline-file is a contract: if it cannot be
        # loaded, fail loudly (load_baseline raises "unreadable") —
        # silently analyzing without the user's baseline would report
        # "<pass>-clean" while skipping stale-entry checking. Only
        # the implicit default path may be legitimately absent.
        diags = []
        for name in selected:
            mod = passes[name]
            baseline_file = (
                args.baseline_file or mod.default_baseline_path(root)
            )
            pass_diags = mod.analyze_repo(
                root,
                baseline_path=(
                    baseline_file
                    if explicit_baseline or os.path.exists(baseline_file)
                    else None
                ),
                index=index,
            )
            if not args.json:
                if pass_diags:
                    print(
                        f"repo: {len(pass_diags)} {name} finding(s) "
                        f"in {root}"
                    )
                    for d in pass_diags:
                        print(f"  {d.render()}")
                else:
                    print(f"repo OK: {root} is {name}-clean")
            diags.extend(pass_diags)
    except BaselineError as e:
        print(f"repo: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({
            "root": root,
            "findings": [
                {
                    "code": d.code,
                    "message": d.message,
                    "where": d.where,
                }
                for d in diags
            ],
        }, indent=2))
    return 1 if diags else 0


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "repo":
        return _repo_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m tpuflow.analysis",
        description="preflight static analysis for tpuflow job specs",
    )
    ap.add_argument("specs", nargs="*", metavar="SPEC.json",
                    help="job-spec files (tpuflow.serve contract)")
    ap.add_argument("--devices", type=int, default=None,
                    help="target device count for plan checking "
                         "(default: the config's n_devices, else skipped)")
    ap.add_argument("--no-shape", action="store_true",
                    help="skip the eval_shape dry-run pass")
    ap.add_argument("--lint", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="run the framework linter over PATH "
                         "(default: the tpuflow package)")
    args = ap.parse_args(argv)
    if not args.specs and args.lint is None:
        ap.print_usage(sys.stderr)
        print(
            "error: pass at least one spec file and/or --lint",
            file=sys.stderr,
        )
        return 2

    failed = False
    unreadable = False
    for path in args.specs:
        try:
            with open(path, encoding="utf-8") as f:
                spec = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            # Keep going: one missing/typo'd file must not hide the
            # findings of every later spec (and the lint pass) — the
            # submit-fix-resubmit loop this tool exists to kill.
            print(f"{path}: unreadable spec: {e}", file=sys.stderr)
            unreadable = True
            continue
        from tpuflow.analysis import preflight
        from tpuflow.serve import spec_to_config

        try:
            config = spec_to_config(spec)
        except (ValueError, TypeError) as e:
            # An unknown/duplicate field never reaches the passes; it is
            # itself the (whole) finding for this spec.
            print(f"{path}: {e}")
            failed = True
            continue
        passes = ("spec", "plan") if args.no_shape else (
            "spec", "plan", "shape"
        )
        report = preflight(
            config, passes=passes, device_count=args.devices
        )
        print(f"{path}: {report.render()}")
        failed = failed or not report.ok

    if args.lint is not None:
        from tpuflow.analysis.linter import lint_package

        findings = lint_package(args.lint or None)
        errors = [d for d in findings if d.severity == "error"]
        target = args.lint or "tpuflow"
        if findings:
            print(f"lint: {len(findings)} finding(s) in {target}")
            for d in findings:
                print(f"  {d.render()}")
        else:
            print(f"lint OK: {target} is clean")
        failed = failed or bool(errors)
    if unreadable:
        return 2
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
