"""``python -m tpuflow.analysis`` — the CI entry point for preflight.

Usage::

    python -m tpuflow.analysis spec.json [spec2.json ...] [--devices N]
    python -m tpuflow.analysis --lint [PATH]
    python -m tpuflow.analysis spec.json --lint     # both

Each positional argument is a JSON job spec in the job-runner contract
(``tpuflow.serve.spec_to_config`` — camelCase or snake_case fields); the
spec, plan, and shape passes run over each and EVERY finding is printed
(one run reports all the errors, not the first). ``--devices`` supplies
the target device count for plan checking without touching a backend —
nothing here compiles, allocates, or initializes accelerator state.
``--lint`` runs the framework linter over ``tpuflow`` (or PATH).

Exit status: 0 when no pass reported an error, 1 otherwise, 2 for
unusable inputs (missing/unparseable spec file).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpuflow.analysis",
        description="preflight static analysis for tpuflow job specs",
    )
    ap.add_argument("specs", nargs="*", metavar="SPEC.json",
                    help="job-spec files (tpuflow.serve contract)")
    ap.add_argument("--devices", type=int, default=None,
                    help="target device count for plan checking "
                         "(default: the config's n_devices, else skipped)")
    ap.add_argument("--no-shape", action="store_true",
                    help="skip the eval_shape dry-run pass")
    ap.add_argument("--lint", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="run the framework linter over PATH "
                         "(default: the tpuflow package)")
    args = ap.parse_args(argv)
    if not args.specs and args.lint is None:
        ap.print_usage(sys.stderr)
        print(
            "error: pass at least one spec file and/or --lint",
            file=sys.stderr,
        )
        return 2

    failed = False
    unreadable = False
    for path in args.specs:
        try:
            with open(path, encoding="utf-8") as f:
                spec = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            # Keep going: one missing/typo'd file must not hide the
            # findings of every later spec (and the lint pass) — the
            # submit-fix-resubmit loop this tool exists to kill.
            print(f"{path}: unreadable spec: {e}", file=sys.stderr)
            unreadable = True
            continue
        from tpuflow.analysis import preflight
        from tpuflow.serve import spec_to_config

        try:
            config = spec_to_config(spec)
        except (ValueError, TypeError) as e:
            # An unknown/duplicate field never reaches the passes; it is
            # itself the (whole) finding for this spec.
            print(f"{path}: {e}")
            failed = True
            continue
        passes = ("spec", "plan") if args.no_shape else (
            "spec", "plan", "shape"
        )
        report = preflight(
            config, passes=passes, device_count=args.devices
        )
        print(f"{path}: {report.render()}")
        failed = failed or not report.ok

    if args.lint is not None:
        from tpuflow.analysis.linter import lint_package

        findings = lint_package(args.lint or None)
        errors = [d for d in findings if d.severity == "error"]
        target = args.lint or "tpuflow"
        if findings:
            print(f"lint: {len(findings)} finding(s) in {target}")
            for d in findings:
                print(f"  {d.render()}")
        else:
            print(f"lint OK: {target} is clean")
        failed = failed or bool(errors)
    if unreadable:
        return 2
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
