"""Pass 2 — shape dry-run: abstract interpretation of the whole job.

``jax.eval_shape`` traces data schema → windowing → model init/apply →
loss with :class:`jax.ShapeDtypeStruct` stand-ins: every shape/dtype
mismatch a real run would hit minutes in (after ingest and an XLA
compile) surfaces in milliseconds, with ZERO compilation and zero
device memory — eval_shape never touches a backend, so this runs on a
login node that has no accelerator at all.

The dry-run mirrors the training path's data contract
(``tpuflow.api.train_api._prepare_data``):

- sequence families see ``x [B, window, F]`` where ``F`` is the schema's
  continuous feature channels (minus the well column); teacher-forced
  families train against ``y [B, window]``, the rest against ``y [B]``;
- tabular families see ``x [B, F]`` with ``F`` = continuous features +
  one-hot blocks (categorical vocabularies are unknown before ingest, so
  each contributes a placeholder width of 2 — models are width-agnostic
  past the first Dense, which is what makes the placeholder sound);
- the residual families get the extra Gilbert channel and dummy target
  stats injected exactly like the training path injects the real ones.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpuflow.analysis.diagnostics import Diagnostic

_PASS = "shape"

# Placeholder one-hot width per categorical column: the real width is the
# training split's vocabulary size, unknowable before ingest. Any value
# >= 1 exercises the same dtype/rank contract.
_PLACEHOLDER_VOCAB = 2


def _diag(code, message, where=None, severity="error"):
    return Diagnostic(
        pass_name=_PASS, code=code, message=message, where=where,
        severity=severity,
    )


def _schema(config):
    from tpuflow.data.schema import Schema
    from tpuflow.data.synthetic import (
        SYNTHETIC_COLUMN_NAMES,
        SYNTHETIC_COLUMN_TYPES,
        SYNTHETIC_TARGET,
    )

    return Schema.from_cli(
        config.column_names or SYNTHETIC_COLUMN_NAMES,
        config.column_types or SYNTHETIC_COLUMN_TYPES,
        config.target or SYNTHETIC_TARGET,
    )


def _feature_dim(config, schema) -> int:
    if config.is_sequence_model:
        from tpuflow.data.pipeline import sequence_feature_names

        dim = len(sequence_feature_names(schema, config.well_column))
    else:
        dim = len(schema.continuous_features)
        dim += _PLACEHOLDER_VOCAB * len(schema.categorical_features)
    if config.model in ("gilbert_residual", "lstm_residual"):
        dim += 1  # the appended raw Gilbert prediction channel
    return dim


def abstract_batch(config, schema=None):
    """The (x, y) ShapeDtypeStructs one training batch would carry."""
    schema = schema if schema is not None else _schema(config)
    feat = _feature_dim(config, schema)
    b = config.batch_size
    if config.is_sequence_model:
        x = jax.ShapeDtypeStruct((b, config.window, feat), jnp.float32)
        y_shape = (b, config.window) if config.teacher_forcing else (b,)
    else:
        x = jax.ShapeDtypeStruct((b, feat), jnp.float32)
        y_shape = (b,)
    return x, jax.ShapeDtypeStruct(y_shape, jnp.float32)


def shape_dryrun(config) -> list[Diagnostic]:
    """Abstractly run schema → batch → init → apply → loss; collect every
    mismatch. Skips (with a warning) when the model/loss name itself is
    unknown — that is the spec pass's finding, not a shape finding."""
    from tpuflow.core.losses import LOSSES, mae_clip
    from tpuflow.models import MODELS, build_model

    if config.model not in MODELS:
        return [_diag(
            "shape.skipped",
            f"shape dry-run skipped: unknown model {config.model!r} "
            "(see the spec pass finding)",
            where="model", severity="warning",
        )]
    try:
        schema = _schema(config)
        x, y = abstract_batch(config, schema)
    except ValueError as e:
        return [_diag(
            "shape.skipped",
            f"shape dry-run skipped: no abstract batch ({e})",
            where="column_names", severity="warning",
        )]

    # An ill-typed model_kwargs is the spec pass's finding; dry-run the
    # family at its defaults so the REST of the job still gets checked.
    model_kwargs = (
        dict(config.model_kwargs)
        if isinstance(config.model_kwargs, dict) else {}
    )
    if config.model in ("gilbert_residual", "lstm_residual"):
        # The training path injects the train split's target stats; any
        # finite placeholder exercises the same shape contract.
        model_kwargs.setdefault("target_mean", 0.0)
        model_kwargs.setdefault("target_std", 1.0)
    # train()'s mixed-precision injection, via the SHARED rule: the
    # dry-run must trace the graph the job will actually run (a model
    # whose kwargs break under the bf16 cast fails HERE, before any
    # compile). An invalid precision token is the spec pass's finding;
    # inject_model_dtype ignores it and the dry-run proceeds at f32.
    from tpuflow.train.precision import inject_model_dtype

    model_kwargs = inject_model_dtype(
        config.model, model_kwargs, getattr(config, "precision", "f32")
    )
    try:
        model = build_model(config.model, **model_kwargs)
    except Exception as e:  # noqa: BLE001 — any constructor failure IS the finding
        return [_diag(
            "shape.model_kwargs",
            f"model {config.model!r} rejected model_kwargs "
            f"{model_kwargs!r}: {type(e).__name__}: {e}",
            where="model_kwargs",
        )]

    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)  # PRNGKey stand-in
    try:
        variables = jax.eval_shape(model.init, rng, x)
    except Exception as e:  # noqa: BLE001
        return [_diag(
            "shape.init",
            f"model.init failed on abstract batch x{tuple(x.shape)}: "
            f"{type(e).__name__}: {e}",
            where="model_kwargs",
        )]
    try:
        pred = jax.eval_shape(
            lambda v, xx: model.apply(v, xx, deterministic=True),
            variables, x,
        )
    except Exception as e:  # noqa: BLE001
        return [_diag(
            "shape.apply",
            f"model.apply failed on abstract batch x{tuple(x.shape)}: "
            f"{type(e).__name__}: {e}",
            where="model",
        )]

    out = []
    if tuple(pred.shape) != tuple(y.shape):
        out.append(_diag(
            "shape.target_mismatch",
            f"model output {tuple(pred.shape)} != target {tuple(y.shape)} "
            f"(teacher_forcing={config.teacher_forcing}); the loss would "
            "silently broadcast or crash mid-epoch",
            where="model",
        ))
    # mae_clip_pallas lowers a kernel; shape semantics match mae_clip.
    loss_fn = LOSSES.get(config.loss, mae_clip)
    if config.loss == "mae_clip_pallas":
        loss_fn = mae_clip
    try:
        loss = jax.eval_shape(loss_fn, y, pred)
        if loss.shape != ():
            out.append(_diag(
                "shape.loss_rank",
                f"loss {config.loss!r} returned shape {tuple(loss.shape)}, "
                "expected a scalar",
                where="loss",
            ))
    except Exception as e:  # noqa: BLE001
        out.append(_diag(
            "shape.loss",
            f"loss {config.loss!r} failed on (y{tuple(y.shape)}, "
            f"pred{tuple(pred.shape)}): {type(e).__name__}: {e}",
            where="loss",
        ))
    return out
