"""Pass 4 — framework linter: AST rules for JAX-correctness over tpuflow.

The other three passes check a *job*; this one checks the *framework
itself*. Each rule encodes a bug class that type checkers and pytest
both miss because the code "works" while silently being wrong (a host
sync per step, a drill that can never fire):

- **TPF001** — host sync inside a jitted function: ``float(...)``,
  ``bool(...)``, ``.item()``, ``np.asarray``/``np.array`` on a traced
  value force a device→host transfer per call (or crash under jit).
- **TPF002** — Python ``random`` / ``np.random`` inside a jitted
  function: untraced host randomness is frozen at trace time, so every
  execution replays the SAME "random" numbers (use ``jax.random``).
- **TPF003** — mutable default argument (list/dict/set literal) on a
  function or a dataclass field: shared across calls/instances (for
  dataclasses, use ``field(default_factory=...)``).
- **TPF004** — fault-site string literal not in the resilience catalog:
  a ``fault_point``/``parse_fault_spec`` literal that names an unknown
  site is a drill that can never fire (the catalog is
  ``tpuflow.resilience.faults.SITES``).
- **TPF005** — metrics/trace recording inside a jitted function:
  ``.inc(...)``/``.observe(...)`` (the obs registry's recording calls)
  or ``record_event``/``record_span`` under jit either freezes at trace
  time (recording once, at compile) or forces a host sync per step —
  record OUTSIDE the jitted program, on already-transferred host values
  (the ``tpuflow.obs`` contract).
- **TPF006** — host-side float conversion of per-step train aux inside
  the epoch batch loop: ``float(...)``/``.item()``/``np.asarray`` on a
  name bound from a ``*train_step(...)`` call, in the same ``for`` body,
  syncs the device once per step and serializes async dispatch. Collect
  device references, convert once post-epoch — which is exactly where
  the numerics watchdog reads them (``tpuflow/obs/health.py``).
  ``epoch_step`` results are exempt: converting the scanned epoch's one
  result IS the post-epoch read.
- **TPF007** — unbounded ``while True:`` poll loop: a loop that sleeps
  each iteration but mentions no deadline/timeout/stop identifier waits
  on its peer FOREVER when the peer dies — exactly the wedge the
  elastic coordinator's eviction deadline and the worker's pull timeout
  exist to prevent, so those loops must pass this rule by construction.
  The bound check is identifier-based (``deadline``/``timeout``/
  ``stop``/``until``/``budget``/...): the rule catches loops with no
  exit discipline at all, not arithmetic mistakes in ones that have it.

"Jitted function" means a function decorated with ``jit``/``jax.jit``/
``partial(jax.jit, ...)`` or passed to a ``jax.jit(...)`` call reachable
in the same module (this repo's dominant idiom: ``return jax.jit(step)``).
Nested functions inherit jitted-ness — a closure's body is traced with
its parent.

Suppression: a ``# noqa: TPF00x`` comment on the offending line, for the
rare construct that is trace-time-constant and provably fine.

A tier-1 test runs this linter over the whole ``tpuflow`` package (the
self-lint gate), so new framework code violating a rule fails the suite.
"""

from __future__ import annotations

import ast
import os
import re

from tpuflow.analysis.diagnostics import Diagnostic

_PASS = "lint"

RULES = {
    "TPF001": "host sync (float()/bool()/.item()/np.asarray()) inside a "
              "jitted function",
    "TPF002": "Python random/np.random inside a jitted function "
              "(untraced host randomness; use jax.random)",
    "TPF003": "mutable default argument (list/dict/set literal); use "
              "field(default_factory=...) / None",
    "TPF004": "fault-site string literal not in the resilience SITES "
              "catalog (a drill against it can never fire)",
    "TPF005": "metrics/trace recording inside a jitted function (frozen "
              "at trace time or a host sync per step; record outside jit "
              "— the tpuflow.obs contract)",
    "TPF006": "host-side float conversion of per-step train aux inside "
              "the epoch batch loop (float()/.item()/np.asarray() on the "
              "step's result syncs the device once per step and "
              "serializes async dispatch; collect device references and "
              "convert ONCE post-epoch — the numerics-watchdog contract)",
    "TPF007": "unbounded while-True poll loop: sleeps every iteration "
              "but checks no deadline/timeout/stop condition, so a dead "
              "peer (an evicted worker, an absent coordinator, a wedged "
              "backend) parks it forever — bound the wait against a "
              "deadline, a stop event, or a give-up budget",
    "TPF008": "direct jax.make_mesh / jax.shard_map / jax.set_mesh use "
              "or a raw shard_map import outside "
              "tpuflow/parallel/compat.py — these APIs move across jax "
              "releases (the 74-failure make_mesh TypeError family); go "
              "through the compat layer's version-probed wrappers",
    "TPF009": "blocking call (time.sleep / requests.* / urlopen / open / "
              "socket.socket) inside an async def: it parks the WHOLE "
              "event loop — every connection the serving control plane "
              "owns stalls behind it. Run blocking work on an executor "
              "(loop.run_in_executor) or use the async equivalent "
              "(asyncio.sleep); the tpuflow/serve_async.py contract",
    "TPF010": "jax/jnp call inside a streaming-window consumer loop in "
              "tpuflow/online/: drift scoring must stay host-side numpy "
              "— a device call (and its sync) per window stalls ingest "
              "behind the accelerator. Score with numpy at loop level; "
              "device work (the retrain) belongs in a helper the loop "
              "calls, where it runs once per retrain, not once per "
              "window",
    "TPF011": "explicit f32 promotion (.astype(jnp.float32) / "
              "jnp.float32(...)) on activations inside a jitted "
              "*train_step body: it silently defeats the mixed-precision "
              "policy — one promoted activation drags every downstream "
              "op back to f32 and the HBM bytes the bf16 path saved come "
              "back (tpuflow/train/precision.py). Loss/grad-reduction "
              "sites are exempt (identifiers mentioning "
              "loss/grad/norm/metric, or any code under a *loss* "
              "function — reduction MUST promote), as is "
              "preferred_element_type=jnp.float32 (an accumulator "
              "request, not a promotion)",
    "TPF012": "raw socket / http.client import outside "
              "tpuflow/elastic/transport.py and the serve modules — "
              "the wire belongs to the transport seam (the TPF008 "
              "compat-seam precedent): ad-hoc sockets dodge the framed "
              "checksummed protocol, the retry policy, and the "
              "elastic.transport.* fault sites, so their failures are "
              "undrillable; speak the exchange backend interface "
              "instead",
    "TPF013": "direct jax.devices() / jax.device_put / "
              "jax.local_devices use outside tpuflow/parallel/ — "
              "device discovery and value placement belong to the "
              "placement seam (tpuflow/parallel/placement.py): serving "
              "replica placement, forced host device counts, and any "
              "future multi-host policy change ONE module, not every "
              "scattered call site; use local_devices()/place()/"
              "device_put() from the seam",
    "TPF014": "direct jax.jit / pjit call inside a loop body outside "
              "the autotune/steps seam: every call builds a FRESH "
              "jitted callable whose compile cache dies with it — the "
              "loop re-jits (and re-compiles) every iteration, churn "
              "the RecompileDetector cannot attribute because the new "
              "callable was never wrapped. Build steps ONCE through "
              "the factories in tpuflow/train/steps.py (or the "
              "autotuner's memoized step cache in train/loop.py) and "
              "call the built function in the loop",
    "TPF015": "duration computed from a time.time() delta outside "
              "tpuflow/obs/: wall-clock subtraction makes every span "
              "duration, throughput number, and SLO window a casualty "
              "of the next NTP step or leap smear — and the fleet "
              "timeline (obs/fleet.py) draws those durations. Measure "
              "durations with time.monotonic()/time.perf_counter(); "
              "time.time() is for TIMESTAMPS (trail records, "
              "heartbeats), never for deltas",
    "TPF022": "bare time.sleep inside a control/sampler loop in "
              "tpuflow/obs/ or tpuflow/serve_autoscale.py: a sleeping "
              "loop ignores its stop event for a whole period (shutdown "
              "drills hang on the join) and its cadence cannot be "
              "driven by a test's fake clock — pace the loop with "
              "stop_event.wait(interval) (interruptible, injectable) "
              "like the history sampler and the autoscaler do",
    "TPF023": "threading.Thread(...) constructed without an explicit "
              "name=: the sampling profiler (tpuflow/obs/profiler.py) "
              "attributes wall-clock to components BY thread-name "
              "prefix, so an anonymous Thread-N lands every sample in "
              "'other' and the flight recorder's stack dumps lose "
              "their subsystem labels. Name the thread with its "
              "tpuflow-<subsystem> prefix",
}

_HOST_SYNC_NAMES = {"float", "bool"}
_HOST_SYNC_NP_ATTRS = {"asarray", "array"}
_RANDOM_BASES = {"random"}  # bare `random.` — jax.random is Attribute-based
_NP_NAMES = {"np", "numpy"}
# The obs registry's recording surface: method names on Counter/Gauge/
# Histogram plus the module-level event/span recorders. ``.set`` is
# deliberately absent (far too generic a method name to flag).
_METRIC_RECORD_ATTRS = {"inc", "observe"}
_METRIC_RECORD_NAMES = {"record_event", "record_span"}
# TPF007: an identifier in the loop containing any of these substrings
# counts as evidence the wait is bounded (a deadline compare, a stop
# event, a timeout knob, a give-up budget). Deliberately generous — the
# rule exists to catch loops with NO exit discipline at all, not to
# audit the arithmetic of ones that have it.
_POLL_BOUND_WORDS = (
    "deadline", "timeout", "stop", "until", "budget", "give_up",
    "remaining", "expires",
)
# TPF009: blocking-call shapes inside ``async def``. Name-call forms
# (``open(...)``, ``urlopen(...)``), attribute chains matched on their
# LAST TWO segments (``time.sleep``, ``socket.socket``,
# ``request.urlopen`` — which also catches the full
# ``urllib.request.urlopen`` spelling), and any call rooted at a
# blocking base module (``requests.<anything>``). ``asyncio.sleep``
# never matches; a blocking call inside a NESTED sync def or lambda is
# not flagged — that function's callers own its context (the
# run_in_executor pattern), mirroring TPF007's nested-def rationale.
_ASYNC_BLOCKING_NAMES = {"open", "urlopen"}
_ASYNC_BLOCKING_ATTRS = {
    ("time", "sleep"),
    ("socket", "socket"),
    ("request", "urlopen"),
}
_ASYNC_BLOCKING_BASES = {"requests"}
# TPF011: scope and exemptions. The rule fires inside jitted functions
# whose enclosing-def chain includes a ``*train_step`` name (the step
# factories: make_train_step, make_dp_train_step, ...). An f32
# promotion is EXEMPT when any identifier in the call mentions one of
# these words (loss/grad reductions and the watchdog aux are REQUIRED
# to promote) or when it sits under a function whose name mentions
# "loss" (the loss_of closures — the loss site promotes the
# prediction by design).
_F32_EXEMPT_WORDS = ("loss", "grad", "norm", "metric")


def _noqa_lines(source: str) -> dict[int, set[str]]:
    """line -> suppressed rule codes (``# noqa: TPF001[,TPF002]``)."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = re.search(r"#\s*noqa:\s*([A-Z0-9, ]+)", line)
        if m:
            out[i] = {c.strip() for c in m.group(1).split(",") if c.strip()}
    return out


def _is_jit_expr(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` / ``nn.jit`` / ``partial(jax.jit, ...)``."""
    if isinstance(node, ast.Name):
        return node.id == "jit"
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    if isinstance(node, ast.Call):  # partial(jax.jit, ...) decorator form
        if (
            isinstance(node.func, (ast.Name, ast.Attribute))
            and (getattr(node.func, "id", None) == "partial"
                 or getattr(node.func, "attr", None) == "partial")
        ):
            return any(_is_jit_expr(a) for a in node.args)
    return False


def _collect_jitted_names(tree: ast.AST) -> set[str]:
    """Function NAMES passed to a jit call anywhere in the module —
    catches ``return jax.jit(step)`` and ``f = jax.jit(g)``."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node.func):
            for arg in node.args[:1]:  # jit's fun is the first positional
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
    return names


# TPF008: the jax attribute names the compat layer owns. ``jax.<attr>``
# references to these (and raw shard_map imports) are version-portability
# hazards everywhere EXCEPT the compat module itself.
_COMPAT_OWNED_JAX_ATTRS = {"make_mesh", "shard_map", "set_mesh"}
_COMPAT_MODULE_SUFFIX = "parallel/compat.py"

# TPF012: the modules allowed to speak the raw wire — the elastic
# transport seam and the HTTP serve stack. Everything else goes through
# the exchange backend interface. Import-level detection is deliberate:
# a module cannot use the socket API without importing it, and flagging
# imports (not attribute chains) keeps the rule free of false positives
# on local names that happen to be called ``socket``.
_SOCKET_ALLOWED_SUFFIXES = (
    "elastic/transport.py",
    "/serve.py",
    "/serve_async.py",
)
_SOCKET_MODULES = ("socket", "socketserver", "http.client")

# TPF013: the jax attribute names the placement seam owns — device
# discovery and value placement. Everything under tpuflow/parallel/ is
# exempt (the seam and the mesh/strategy modules it serves are one
# layer); everywhere else these references are placement decisions that
# belong in tpuflow/parallel/placement.py.
_PLACEMENT_OWNED_JAX_ATTRS = {"devices", "device_put", "local_devices"}
_PLACEMENT_DIR_FRAGMENT = "tpuflow/parallel/"

# TPF014: the modules allowed to create jitted callables inside a loop
# — the step-factory seam (train/steps.py holds THE jit call sites for
# training; train/autotune.py + train/loop.py own the tuner's memoized
# variant cache, whose whole point is that a revisited config reuses
# the SAME callable). Everywhere else, a jit/pjit call lexically inside
# a for/while body is re-jit churn: each iteration's fresh callable
# compiles from scratch and the RecompileDetector (which wraps named
# step fns once) cannot attribute the cost. Nested function defs are
# exempt — their callers own the calling context (TPF007 rationale).
_JIT_SEAM_SUFFIXES = (
    "train/steps.py",
    "train/autotune.py",
    "train/loop.py",
)
_JIT_CALL_NAMES = {"jit", "pjit"}

# TPF015: the one directory exempt from the wall-clock-delta rule —
# tpuflow/obs/ owns the trail format, whose records carry wall-clock
# `time` stamps by design (cross-process merge needs one shared clock);
# its own span timing already uses perf_counter.
_OBS_DIR_FRAGMENT = "tpuflow/obs/"

# TPF010: scope and trigger. The rule fires only in the online package
# (the one place a per-window device sync stalls a live ingest loop);
# a "streaming-window consumer loop" is a for-loop whose ITERABLE
# mentions one of these words (the stream/window/chunk sources the
# package consumes). jax/jnp attribute roots inside such a loop's body
# — without descending into nested defs, whose callers own their
# context — are findings.
_ONLINE_PATH_FRAGMENT = "tpuflow/online/"
_STREAM_ITER_WORDS = ("window", "stream", "chunk", "batch", "source")
_DEVICE_ROOTS = {"jax", "jnp"}

# TPF022 scope: the modules whose loops ARE control/sampler loops by
# construction — the history sampler, the alert engine, anything under
# tpuflow/obs/, and the serving autoscaler. Their pacing contract is
# stop_event.wait(interval): interruptible at shutdown, injectable in
# tests. Elsewhere a loop's sleep is judged by TPF007/TPF009/TPF017.
_CONTROL_LOOP_SUFFIX = "tpuflow/serve_autoscale.py"


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source: str, sites: dict):
        self.path = path
        self.sites = sites
        self.noqa = _noqa_lines(source)
        self.tree = ast.parse(source, filename=path)
        self.jitted_names = _collect_jitted_names(self.tree)
        self.findings: list[Diagnostic] = []
        self._jit_depth = 0
        self._async_depth = 0
        self._def_stack: list[str] = []
        norm = path.replace(os.sep, "/")
        self._is_compat = norm.endswith(_COMPAT_MODULE_SUFFIX)
        self._is_placement_layer = _PLACEMENT_DIR_FRAGMENT in norm
        self._is_online = _ONLINE_PATH_FRAGMENT in norm
        self._is_obs = _OBS_DIR_FRAGMENT in norm
        self._socket_allowed = norm.endswith(_SOCKET_ALLOWED_SUFFIXES)
        self._jit_seam = norm.endswith(_JIT_SEAM_SUFFIXES)
        self._is_control_loop_module = (
            self._is_obs or norm.endswith(_CONTROL_LOOP_SUFFIX)
        )

    def run(self) -> list[Diagnostic]:
        self.visit(self.tree)
        return self.findings

    def _emit(self, code: str, node: ast.AST, detail: str) -> None:
        if code in self.noqa.get(node.lineno, ()):
            return
        self.findings.append(Diagnostic(
            pass_name=_PASS, code=code,
            message=f"{detail} — {RULES[code]}",
            where=f"{self.path}:{node.lineno}",
        ))

    # --- jitted-scope tracking ---

    def _is_jitted_def(self, node) -> bool:
        if any(_is_jit_expr(d) for d in node.decorator_list):
            return True
        return node.name in self.jitted_names

    def _visit_function(self, node) -> None:
        self._check_defaults(node)
        entered = self._jit_depth > 0 or self._is_jitted_def(node)
        self._jit_depth += 1 if entered else 0
        self._def_stack.append(node.name)
        # TPF009 scope: an ``async def`` body runs on the event loop; a
        # nested SYNC def does not (its callers choose the thread — the
        # run_in_executor pattern), so it resets the flag for its body.
        prev_async = self._async_depth
        if isinstance(node, ast.AsyncFunctionDef):
            self._async_depth += 1
        else:
            self._async_depth = 0
        self.generic_visit(node)
        self._async_depth = prev_async
        self._def_stack.pop()
        self._jit_depth -= 1 if entered else 0

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node) -> None:
        self._check_defaults(node)
        # A lambda's body is deferred like a nested def's: its caller
        # owns the execution context (TPF009 scope reset).
        prev_async, self._async_depth = self._async_depth, 0
        self.generic_visit(node)
        self._async_depth = prev_async

    # --- TPF003: mutable defaults ---

    def _check_defaults(self, node) -> None:
        args = node.args
        for default in list(args.defaults) + [
            d for d in args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self._emit(
                    "TPF003", default,
                    f"mutable default in {getattr(node, 'name', '<lambda>')}()",
                )

    def visit_ClassDef(self, node) -> None:
        # Dataclass-style configs: a bare mutable literal as a class-level
        # field default is shared across instances (and for @dataclass,
        # a runtime error only once the class is actually instantiated).
        for stmt in node.body:
            value = None
            if isinstance(stmt, ast.AnnAssign):
                value = stmt.value
            elif isinstance(stmt, ast.Assign):
                value = stmt.value
            if isinstance(value, (ast.List, ast.Dict, ast.Set)):
                self._emit(
                    "TPF003", value,
                    f"mutable class-level default in {node.name}",
                )
        self.generic_visit(node)

    # --- TPF015: wall-clock deltas as durations ---

    @staticmethod
    def _is_wall_clock_call(node) -> bool:
        """Literal ``time.time()`` — the module spelling only: an
        injectable ``clock()`` variable is the drills' fake-clock
        pattern and never flagged, and ``self.clock()`` defaults are a
        deliberate seam."""
        return (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "time"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "time"
        )

    def visit_BinOp(self, node) -> None:
        if (
            not self._is_obs
            and isinstance(node.op, ast.Sub)
            and (self._is_wall_clock_call(node.left)
                 or self._is_wall_clock_call(node.right))
        ):
            self._emit(
                "TPF015", node,
                f"{ast.unparse(node)} computes a duration from a "
                "time.time() delta",
            )
        self.generic_visit(node)

    # --- TPF006: per-step host sync in the epoch batch loop ---

    def visit_For(self, node) -> None:
        self._check_step_aux_loop(node)
        self._check_online_consumer_loop(node)
        self._check_loop_jit(node)
        self._check_control_loop_sleep(node)
        self.generic_visit(node)

    # --- TPF014: jit/pjit calls inside loop bodies ---

    @staticmethod
    def _walk_loop_level(node):
        """One loop's per-iteration code: the body (and orelse), plus
        the test for ``while`` loops (re-evaluated every pass) — but
        NOT a ``for`` loop's iterable, which evaluates exactly once
        when the iterator is built (a jit call there is the factory
        pattern, not churn). Nested loops are skipped (they get their
        own visit — descending would double-report), as are nested
        function defs (a def's body runs when CALLED; a loop-defined
        jitted factory is owned by its callers)."""
        stack = list(node.body) + list(node.orelse)
        if isinstance(node, ast.While):
            stack.append(node.test)
        while stack:
            sub = stack.pop()
            if isinstance(sub, (
                ast.For, ast.AsyncFor, ast.While, ast.FunctionDef,
                ast.AsyncFunctionDef, ast.Lambda,
            )):
                continue
            yield sub
            stack.extend(ast.iter_child_nodes(sub))

    def _check_loop_jit(self, node) -> None:
        if self._jit_seam:
            return
        for sub in self._walk_loop_level(node):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            name = (
                func.id if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute)
                else None
            )
            if name in _JIT_CALL_NAMES:
                self._emit(
                    "TPF014", sub,
                    f"{ast.unparse(func)}(...) inside a loop body",
                )

    # --- TPF010: device calls in online streaming consumer loops ---

    @staticmethod
    def _mentions_stream_word(expr: ast.AST) -> bool:
        for sub in ast.walk(expr):
            name = (
                sub.id if isinstance(sub, ast.Name)
                else sub.attr if isinstance(sub, ast.Attribute)
                else None
            )
            if name and any(
                w in name.lower() for w in _STREAM_ITER_WORDS
            ):
                return True
        return False

    def _walk_one_consumer_loop(self, node: ast.AST):
        """Subtree without nested function defs (their callers own the
        context) and without nested loops that are THEMSELVES consumer
        loops — those get their own visit_For, and descending into them
        here would report each finding once per enclosing loop. Nested
        non-consumer loops (``for _ in range(k)``) stay in scope: their
        bodies still run once per streamed window."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (
                ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
            )):
                continue
            if isinstance(sub, (ast.For, ast.AsyncFor)) \
                    and self._mentions_stream_word(sub.iter):
                continue
            yield sub
            stack.extend(ast.iter_child_nodes(sub))

    def _check_online_consumer_loop(self, node: ast.For) -> None:
        if not self._is_online or not self._mentions_stream_word(node.iter):
            return
        for sub in self._walk_one_consumer_loop(node):
            if (
                isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id in _DEVICE_ROOTS
            ):
                self._emit(
                    "TPF010", sub,
                    f"{sub.value.id}.{sub.attr} in a streaming-window "
                    "consumer loop",
                )

    # --- TPF007: unbounded while-True poll loops ---

    @staticmethod
    def _walk_no_funcs(node: ast.AST):
        """``node``'s subtree without descending into nested function
        definitions (a nested def's sleep belongs to that function's own
        callers, not to this loop's iteration)."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (
                ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
            )):
                continue
            yield sub
            stack.extend(ast.iter_child_nodes(sub))

    def visit_While(self, node) -> None:
        self._check_unbounded_poll(node)
        self._check_loop_jit(node)
        self._check_control_loop_sleep(node)
        self.generic_visit(node)

    def visit_AsyncFor(self, node) -> None:
        # The async serving paths are where per-message re-jit churn is
        # most likely — TPF014 covers them like any other loop.
        self._check_loop_jit(node)
        self.generic_visit(node)

    def _check_unbounded_poll(self, node: ast.While) -> None:
        test = node.test
        if not (isinstance(test, ast.Constant) and test.value is True):
            return  # a real condition IS the loop's exit discipline
        sleeps = bounded = False
        for sub in self._walk_no_funcs(node):
            if (
                isinstance(sub, ast.Call)
                and self._call_name(sub.func) == "sleep"
            ):
                sleeps = True
            name = (
                sub.id if isinstance(sub, ast.Name)
                else sub.attr if isinstance(sub, ast.Attribute)
                else sub.arg if isinstance(sub, ast.keyword)
                else None
            )
            if name and any(w in name.lower() for w in _POLL_BOUND_WORDS):
                bounded = True
        if sleeps and not bounded:
            self._emit(
                "TPF007", node,
                "while True: loop sleeps but never checks a bound",
            )

    # --- TPF022: bare sleep pacing a control/sampler loop ---

    def _check_control_loop_sleep(self, node) -> None:
        """In the control-loop modules (tpuflow/obs/, the autoscaler),
        a loop paced by ``time.sleep`` (or a bare imported ``sleep``)
        cannot be interrupted by its stop event mid-period and cannot
        be driven by a fake clock — the pacing contract there is
        ``stop_event.wait(interval)``. One loop level per visit (the
        ``_walk_loop_level`` discipline), so nested loops are judged
        by their own visits; nested defs belong to their callers."""
        if not self._is_control_loop_module:
            return
        for sub in self._walk_loop_level(node):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            flagged = (
                isinstance(func, ast.Attribute)
                and func.attr == "sleep"
                and isinstance(func.value, ast.Name)
                and func.value.id == "time"
            ) or (isinstance(func, ast.Name) and func.id == "sleep")
            if flagged:
                self._emit(
                    "TPF022", sub,
                    f"{ast.unparse(sub)} paces this loop",
                )

    @staticmethod
    def _call_name(func) -> str | None:
        if isinstance(func, ast.Name):
            return func.id
        if isinstance(func, ast.Attribute):
            return func.attr
        return None

    @staticmethod
    def _walk_same_loop(node: ast.For):
        """``node``'s subtree WITHOUT descending into nested loops or
        function definitions: each ``visit_For`` analyzes exactly one
        loop level, so an epoch loop wrapping a batch loop neither
        double-reports the inner loop's findings nor flags the blessed
        post-batch-loop conversion (which sits in the OUTER body while
        the step assignment sits in the inner — different levels)."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            sub = stack.pop()
            yield sub
            if isinstance(sub, (
                ast.For, ast.AsyncFor, ast.FunctionDef,
                ast.AsyncFunctionDef, ast.Lambda,
            )):
                continue
            stack.extend(ast.iter_child_nodes(sub))

    def _check_step_aux_loop(self, node: ast.For) -> None:
        """Names bound from a ``*train_step(...)`` call at THIS loop
        level must not be host-converted at the same level — the
        per-batch sync that makes the watchdog contract explicit: aux is
        collected as device references, converted once post-epoch.
        (``epoch_step`` results are exempt: one conversion per scanned
        epoch IS the post-epoch read.)"""
        aux_names: set[str] = set()
        for sub in self._walk_same_loop(node):
            if isinstance(sub, ast.Assign) and isinstance(
                sub.value, ast.Call
            ):
                fname = self._call_name(sub.value.func)
                if fname and fname.endswith("train_step"):
                    for target in sub.targets:
                        elts = (
                            target.elts
                            if isinstance(target, ast.Tuple)
                            else [target]
                        )
                        aux_names |= {
                            e.id for e in elts if isinstance(e, ast.Name)
                        }
        if not aux_names:
            return

        def mentions_aux(expr: ast.AST) -> bool:
            return any(
                isinstance(n, ast.Name) and n.id in aux_names
                for n in ast.walk(expr)
            )

        for sub in self._walk_same_loop(node):
            if not isinstance(sub, ast.Call) or not sub.args:
                continue
            func = sub.func
            converted = (
                (isinstance(func, ast.Name)
                 and func.id in _HOST_SYNC_NAMES)
                or (isinstance(func, ast.Attribute)
                    and func.attr in _HOST_SYNC_NP_ATTRS
                    and isinstance(func.value, ast.Name)
                    and func.value.id in _NP_NAMES)
            )
            if converted and any(mentions_aux(a) for a in sub.args):
                self._emit(
                    "TPF006", sub,
                    f"{ast.unparse(func)}(...) on per-step aux",
                )
        for sub in self._walk_same_loop(node):
            # .item() is argument-less, so it needs its own scan over
            # the attribute's BASE expression.
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "item"
                and mentions_aux(sub.func.value)
            ):
                self._emit("TPF006", sub, ".item() on per-step aux")

    # --- TPF008: jax portability APIs outside the compat layer ---

    def visit_Attribute(self, node) -> None:
        if (
            not self._is_compat
            and node.attr in _COMPAT_OWNED_JAX_ATTRS
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax"
        ):
            self._emit("TPF008", node, f"jax.{node.attr} reference")
        if (
            not self._is_placement_layer
            and node.attr in _PLACEMENT_OWNED_JAX_ATTRS
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax"
        ):
            self._emit("TPF013", node, f"jax.{node.attr} reference")
        self.generic_visit(node)

    # --- TPF012: raw wire imports outside the transport seam ---

    @staticmethod
    def _is_socket_module(name: str) -> bool:
        return any(
            name == m or name.startswith(m + ".")
            for m in _SOCKET_MODULES
        )

    def _check_socket_import_from(self, node) -> None:
        if self._socket_allowed or not node.module:
            return
        if self._is_socket_module(node.module):
            names = ", ".join(sorted(a.name for a in node.names))
            self._emit(
                "TPF012", node, f"from {node.module} import {names}"
            )
        elif node.module == "http" and any(
            a.name == "client" for a in node.names
        ):
            self._emit("TPF012", node, "from http import client")

    def visit_ImportFrom(self, node) -> None:
        self._check_socket_import_from(node)
        if not self._is_compat and node.module:
            names = {a.name for a in node.names}
            raw_shard_map_import = (
                node.module.startswith("jax.experimental.shard_map")
                or (node.module == "jax.experimental"
                    and "shard_map" in names)
                or (node.module == "jax"
                    and names & _COMPAT_OWNED_JAX_ATTRS)
            )
            if raw_shard_map_import:
                # Name only the offending imports: `from jax import jit,
                # make_mesh` is a make_mesh finding, not a jit one.
                offending = (
                    names & _COMPAT_OWNED_JAX_ATTRS
                    if node.module == "jax"
                    else names
                )
                self._emit(
                    "TPF008", node,
                    f"from {node.module} import "
                    f"{', '.join(sorted(offending))}",
                )
        if not self._is_placement_layer and node.module == "jax":
            placed = {
                a.name for a in node.names
            } & _PLACEMENT_OWNED_JAX_ATTRS
            if placed:
                self._emit(
                    "TPF013", node,
                    f"from jax import {', '.join(sorted(placed))}",
                )
        self.generic_visit(node)

    def visit_Import(self, node) -> None:
        # The bypass spelling: ``import jax.experimental.shard_map as m``
        # then ``m.shard_map(...)`` — neither a from-import nor a
        # ``jax.<attr>`` attribute chain, so it needs its own check.
        if not self._is_compat:
            for alias in node.names:
                if alias.name.startswith("jax.experimental.shard_map"):
                    self._emit("TPF008", node, f"import {alias.name}")
        if not self._socket_allowed:
            for alias in node.names:
                if self._is_socket_module(alias.name):
                    self._emit("TPF012", node, f"import {alias.name}")
        self.generic_visit(node)

    # --- TPF001 / TPF002 / TPF004: calls ---

    # --- TPF011: f32 promotions inside jitted *train_step bodies ---

    def _in_train_step_scope(self) -> bool:
        return self._jit_depth > 0 and any(
            name.endswith("train_step") for name in self._def_stack
        )

    @staticmethod
    def _is_f32_expr(expr: ast.AST) -> bool:
        """``jnp.float32`` / ``np.float32`` / the "float32" string."""
        if isinstance(expr, ast.Attribute) and expr.attr == "float32":
            return True
        return isinstance(expr, ast.Constant) and expr.value == "float32"

    def _f32_exempt(self, node: ast.Call) -> bool:
        # A reduction site: the call mentions a loss/grad/norm/metric
        # identifier, or sits under a *loss* function (loss_of) — those
        # promotions ARE the policy ("loss/grad reduction in f32").
        for name in self._def_stack:
            if "loss" in name.lower():
                return True
        for sub in ast.walk(node):
            ident = (
                sub.id if isinstance(sub, ast.Name)
                else sub.attr if isinstance(sub, ast.Attribute)
                else None
            )
            if ident and any(
                w in ident.lower() for w in _F32_EXEMPT_WORDS
            ):
                return True
        return False

    def _check_f32_promotion(self, node: ast.Call, func) -> None:
        promotion = None
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "astype"
            and node.args
            and self._is_f32_expr(node.args[0])
        ):
            promotion = ".astype(jnp.float32)"
        elif (
            isinstance(func, ast.Attribute)
            and func.attr == "float32"
            and isinstance(func.value, ast.Name)
            and func.value.id in (_NP_NAMES | {"jnp"})
            and node.args
        ):
            promotion = f"{func.value.id}.float32(...)"
        if promotion and not self._f32_exempt(node):
            self._emit(
                "TPF011", node,
                f"{promotion} on an activation in a train step",
            )

    def visit_Call(self, node) -> None:
        func = node.func
        if self._async_depth > 0:
            self._check_async_blocking(node, func)
        if self._in_train_step_scope():
            self._check_f32_promotion(node, func)
        if self._jit_depth > 0:
            if (
                isinstance(func, ast.Name)
                and func.id in _HOST_SYNC_NAMES
            ):
                self._emit("TPF001", node, f"{func.id}(...) call")
            if (
                isinstance(func, ast.Name)
                and func.id in _METRIC_RECORD_NAMES
            ):
                self._emit("TPF005", node, f"{func.id}(...) call")
            if isinstance(func, ast.Attribute):
                if func.attr == "item":
                    self._emit("TPF001", node, ".item() call")
                if func.attr in _METRIC_RECORD_ATTRS:
                    self._emit("TPF005", node, f".{func.attr}(...) call")
                if (
                    func.attr in _HOST_SYNC_NP_ATTRS
                    and isinstance(func.value, ast.Name)
                    and func.value.id in _NP_NAMES
                ):
                    self._emit(
                        "TPF001", node,
                        f"{func.value.id}.{func.attr}(...) call",
                    )
                base = func.value
                if (
                    isinstance(base, ast.Name)
                    and base.id in _RANDOM_BASES
                ) or (
                    isinstance(base, ast.Attribute)
                    and base.attr == "random"
                    and isinstance(base.value, ast.Name)
                    and base.value.id in _NP_NAMES
                ):
                    self._emit(
                        "TPF002", node,
                        f"{ast.unparse(func)}(...) call",
                    )
        self._check_fault_site(node)
        self._check_nameless_thread(node, func)
        self.generic_visit(node)

    def _check_nameless_thread(self, node: ast.Call, func) -> None:
        """TPF023: ``Thread(...)`` / ``threading.Thread(...)`` without an
        explicit ``name=``. A ``**kwargs`` splat may carry the name, so
        splatted constructions are not judged."""
        is_thread = (
            isinstance(func, ast.Name) and func.id == "Thread"
        ) or (
            isinstance(func, ast.Attribute)
            and func.attr == "Thread"
            and isinstance(func.value, ast.Name)
            and func.value.id == "threading"
        )
        if not is_thread:
            return
        if len(node.args) >= 3:  # Thread(group, target, name, ...)
            return
        for kw in node.keywords:
            if kw.arg == "name" or kw.arg is None:  # name= or **splat
                return
        self._emit("TPF023", node, "Thread(...) constructed without name=")

    def _check_async_blocking(self, node: ast.Call, func) -> None:
        """TPF009: blocking-call shapes under an ``async def``."""
        if isinstance(func, ast.Name) and func.id in _ASYNC_BLOCKING_NAMES:
            self._emit("TPF009", node, f"{func.id}(...) in async def")
            return
        if isinstance(func, ast.Attribute):
            # Walk the whole attribute chain so the common dotted
            # spelling (``urllib.request.urlopen``) matches, not just
            # two-segment forms.
            parts: list[str] = []
            head = func
            while isinstance(head, ast.Attribute):
                parts.append(head.attr)
                head = head.value
            if not isinstance(head, ast.Name):
                return
            parts.append(head.id)
            parts.reverse()
            dotted = ".".join(parts)
            if parts[0] in _ASYNC_BLOCKING_BASES or (
                len(parts) >= 2
                and tuple(parts[-2:]) in _ASYNC_BLOCKING_ATTRS
            ):
                self._emit(
                    "TPF009", node, f"{dotted}(...) in async def"
                )

    def _check_fault_site(self, node: ast.Call) -> None:
        func = node.func
        name = (
            func.id if isinstance(func, ast.Name)
            else func.attr if isinstance(func, ast.Attribute)
            else None
        )
        if name == "fault_point" and node.args:
            arg = node.args[0]
            if (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and arg.value not in self.sites
            ):
                self._emit(
                    "TPF004", node,
                    f"fault_point({arg.value!r})",
                )
        if name == "parse_fault_spec" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                site = arg.value.split(",", 1)[0].strip()
                if site and site not in self.sites:
                    self._emit(
                        "TPF004", node,
                        f"parse_fault_spec({arg.value!r}) site {site!r}",
                    )


def lint_file(path: str, sites: dict | None = None) -> list[Diagnostic]:
    """Lint one Python file; returns findings (syntax errors included)."""
    if sites is None:
        from tpuflow.resilience.faults import SITES as sites
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        return _Linter(path, source, sites).run()
    except SyntaxError as e:
        return [Diagnostic(
            pass_name=_PASS, code="TPF000",
            message=f"syntax error: {e.msg}",
            where=f"{path}:{e.lineno}",
        )]


def lint_package(root: str | None = None) -> list[Diagnostic]:
    """Lint every ``.py`` file under ``root`` (default: the installed
    ``tpuflow`` package directory) — the self-lint gate's entry point."""
    if root is None:
        import tpuflow

        root = os.path.dirname(os.path.abspath(tpuflow.__file__))
    from tpuflow.resilience.faults import SITES

    findings: list[Diagnostic] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__"
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                findings += lint_file(os.path.join(dirpath, fn), SITES)
    return findings
