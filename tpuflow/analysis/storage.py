"""Pass 6 — repo-wide storage-contract analyzer: path I/O discipline.

Rides the SAME index as the concurrency pass (one walk of the package —
``tpuflow.analysis.concurrency.build_index`` records every filesystem
touchpoint into ``FuncInfo.file_ops`` while it scans for locks), and
enforces the object-store seam (``tpuflow/storage/``, docs/storage.md):
durable bytes move through ``ObjectStore`` / the audited local helpers,
not through scattered ``open``/``os.replace``/``shutil`` calls. The
contract exists because the next backend (``gs://``) has **no rename
and no partial write** — code that quietly assumes POSIX rename today
is code that cannot be pointed at a bucket tomorrow.

Three rules:

- **TPF019** — direct path I/O outside the seam: ``open(...)``,
  ``Path.write_*``/``read_*``/``unlink``/``glob``, ``np.save``/
  ``np.load``, ``shutil.*`` anywhere except the seam itself and a short
  allow-list of leaf modules whose business IS local files (ingestion,
  log sinks, the analyzers reading source). ``json.dump``/``load`` are
  recorded but never flagged alone — they ride a handle some ``open``
  already produced (that open is the finding).
- **TPF020** — rename-assumed-atomic publish outside the seam:
  ``os.replace``/``os.rename``, ``shutil.move``, ``Path.rename``. A
  rename is the one primitive object stores don't have; every
  rename-as-publish must live behind the seam (``fsync_write``,
  ``replace_file``, ``move_tree``) where the storage analyzer — and the
  gs:// port — can find them all in one place. A TPF020 site is NOT
  also TPF019 (one defect, one finding).
- **TPF021** — read-modify-write of a shared file without tmp+rename
  discipline or a seam transaction: the same function reads path
  expression ``X`` and writes ``X`` directly (no tmp + ``os.replace``,
  no ``atomic_write_json``/``write_json``/``put_atomic`` publish). A
  crash between the read and the in-place write tears the file; a
  concurrent reader sees the torn middle.

Accepted findings live in ``tpuflow/analysis/storage_baseline.json`` —
the same fingerprinted, justification-required workflow as the
concurrency baseline (shared machinery:
:mod:`tpuflow.analysis.baseline`), including stale-entry hygiene and
``# noqa: TPF019`` line suppression.

Entry points: ``python -m tpuflow.analysis repo --passes storage`` and
the tier-1 self-gate in tests/test_analysis.py.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from tpuflow.analysis.baseline import BaselineError  # noqa: F401
from tpuflow.analysis.baseline import baseline_key as _baseline_key
from tpuflow.analysis.baseline import load_baseline as _load_baseline
from tpuflow.analysis.baseline import write_baseline as _write_baseline
from tpuflow.analysis.concurrency import (
    FuncInfo,
    RepoIndex,
    _named_scope,
    build_index,
    default_root,
)
from tpuflow.analysis.diagnostics import Diagnostic

_PASS = "storage"

RULES = {
    "TPF019": "direct path I/O outside the storage seam: durable bytes "
              "must move through tpuflow.storage (ObjectStore / the "
              "audited local helpers) or an allow-listed leaf module — "
              "scattered open/Path/np/shutil calls are exactly the "
              "sites an object-store backend (no rename, no partial "
              "write) cannot honor",
    "TPF020": "rename-assumed-atomic publish outside the seam: "
              "os.replace/os.rename/shutil.move/Path.rename is the one "
              "primitive object stores don't have — route it through "
              "the seam (fsync_write / replace_file / move_tree) or "
              "publish by pointer promotion",
    "TPF021": "read-modify-write of a shared file without tmp+rename "
              "discipline or a seam transaction: a crash between the "
              "read and the in-place write tears the file, a "
              "concurrent reader sees the torn middle — write a tmp "
              "and os.replace it, or publish through "
              "atomic_write_json/write_json/put_atomic",
}

# Stale-baseline hygiene code (mirrors the concurrency pass).
STALE_CODE = "storage.baseline.stale"

# Where direct path I/O is the module's BUSINESS, not a seam violation
# (matched by /-normalized path prefix under the analysis root):
#
# - storage/        the seam itself — every primitive lands here
# - utils/paths.py  atomic_write_json + the fsspec shim the seam wraps
# - analysis/       the analyzers read source files and write baselines
# - data/           CSV/stream ingestion: leaf reads of user datasets
# - obs/            log/trace/forensics sinks (append-only local files)
# - utils/logging.py      the metrics JSONL sink
# - elastic/exchange.py   the FILE transport: its business is the gang
#                         directory (npz payloads, atomic publishes) —
#                         the store transport is its seam twin
# - elastic/membership.py the file transport's heartbeat half
#
# Everything else goes through the seam or carries a baseline entry.
ALLOWED_PREFIXES = (
    "storage/",
    "utils/paths.py",
    "analysis/",
    "data/",
    "obs/",
    "utils/logging.py",
    "elastic/exchange.py",
    "elastic/membership.py",
)

# Callee names that mark a function as publishing through the seam —
# TPF021's "seam transaction" escape hatch. A function that hands its
# bytes to one of these is preparing input for an atomic publish, not
# tearing a shared file in place.
_SEAM_WRITERS = {
    "atomic_write_json", "write_json", "put_atomic", "fsync_write",
    "put", "promote", "replace_file", "write_leaves",
}

# open() modes that WRITE (r+ included: in-place update is the sharpest
# TPF021 shape). Default mode is read.
def _mode_writes(mode: str) -> bool:
    return any(c in mode for c in "wax+")


@dataclass(frozen=True)
class Finding:
    """One storage finding + its line-free baseline fingerprint."""

    rule: str
    message: str
    path: str  # display path
    rel: str  # /-normalized, root-relative (the fingerprint's file)
    line: int
    scope: str  # nearest named enclosing scope
    subject: str  # the op / path expression the finding is about

    @property
    def fingerprint(self) -> tuple:
        return (self.rule, self.rel, self.scope, self.subject)

    def diagnostic(self) -> Diagnostic:
        return Diagnostic(
            pass_name=_PASS, code=self.rule,
            message=f"{self.message} — {RULES[self.rule]}",
            where=f"{self.path}:{self.line}",
        )


def _allowed(rel: str) -> bool:
    return any(rel.startswith(p) for p in ALLOWED_PREFIXES)


def _uses_seam(fn: FuncInfo) -> bool:
    return any(name in _SEAM_WRITERS for _kind, name in fn.callees)


def analyze_index(index: RepoIndex) -> list[Finding]:
    """Classify every recorded file op (see the module docstring)."""
    findings: list[Finding] = []
    for fn in index.all_functions():
        mod = fn.module
        if not fn.file_ops:
            continue
        allowed = _allowed(mod.rel)
        # --- TPF021 evidence tables (built even in allowed modules:
        # read-modify-write is torn no matter whose business the file
        # is — only the seam itself is exempt, its helpers ARE the
        # discipline). Reads carry their earliest line: RMW means the
        # read came FIRST — a write-then-read-back (log capture) is a
        # different, harmless shape. ---
        reads: dict[str, int] = {}
        rename_dsts: set[str] = set()
        for op in fn.file_ops:
            if op.kind == "rename":
                rename_dsts.add(op.target)
            elif op.kind == "path_read" or (
                op.kind == "open" and not _mode_writes(op.mode)
            ):
                if op.target and op.line < reads.get(
                    op.target, op.line + 1
                ):
                    reads[op.target] = op.line
        seam_fn = _uses_seam(fn)
        for op in fn.file_ops:
            # TPF020: rename-as-publish — one defect, one finding
            if op.kind == "rename":
                if not allowed:
                    findings.append(Finding(
                        rule="TPF020",
                        message=(
                            f"{op.what}(...) publishes by rename "
                            "outside the storage seam"
                        ),
                        path=mod.path, rel=mod.rel, line=op.line,
                        scope=_named_scope(fn), subject=op.what,
                    ))
                continue
            # TPF021: in-place rewrite of something this function reads
            writes_here = (
                op.kind in ("path_write",)
                or (op.kind == "open" and _mode_writes(op.mode))
            )
            if (
                writes_here
                and op.target
                and reads.get(op.target, op.line + 1) < op.line
                and op.target not in rename_dsts
                and not seam_fn
                and mod.rel.split("/")[0] != "storage"
            ):
                findings.append(Finding(
                    rule="TPF021",
                    message=(
                        f"{op.target} is read and rewritten in place "
                        "in the same function (no tmp+rename, no seam "
                        "transaction)"
                    ),
                    path=mod.path, rel=mod.rel, line=op.line,
                    scope=_named_scope(fn), subject=op.target,
                ))
                continue  # the sharper finding; don't also TPF019 it
            # TPF019: any other direct path I/O outside the allow-list.
            # json ops are handle-mediated — the open that produced the
            # handle is the finding.
            if op.kind == "json" or allowed:
                continue
            findings.append(Finding(
                rule="TPF019",
                message=(
                    f"{op.what}(...) touches the filesystem directly "
                    "outside the storage seam"
                ),
                path=mod.path, rel=mod.rel, line=op.line,
                scope=_named_scope(fn), subject=op.what,
            ))
    # noqa parity with the per-file linter and the concurrency pass
    findings = [
        f for f in findings
        if f.rule not in index.modules[f.rel].noqa.get(f.line, ())
    ]
    findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    return findings


# ---------------------------------------------------------------------
# baseline + gate entry points (shared machinery, storage bindings)
# ---------------------------------------------------------------------

_BASELINE_COMMENT = (
    "Triaged-accepted storage findings "
    "(python -m tpuflow.analysis repo --passes storage --baseline). "
    "Entries are fingerprinted (rule, file, scope, subject) — no line "
    "numbers, so they survive unrelated edits. Every entry carries a "
    "one-line justification; stale entries (finding gone) are reported "
    "and must be pruned."
)


def load_baseline(path: str) -> list[dict]:
    """Parse + validate the storage baseline; raises
    :class:`BaselineError` naming the file and field on anything
    malformed."""
    return _load_baseline(path, RULES)


def write_baseline(path: str, findings: list[Finding],
                   reasons: dict | None = None) -> int:
    """(Re)write the baseline accepting every current finding; reasons
    survive regeneration and pure file moves."""
    return _write_baseline(
        path, findings, reasons, comment=_BASELINE_COMMENT
    )


def default_baseline_path(root: str) -> str:
    """``<root>/analysis/storage_baseline.json`` when the root has an
    analysis/ package (the tpuflow layout), else flat in the root
    (fixture dirs)."""
    nested = os.path.join(root, "analysis")
    if os.path.isdir(nested):
        return os.path.join(nested, "storage_baseline.json")
    return os.path.join(root, "storage_baseline.json")


def analyze_repo(
    root: str | None = None,
    baseline_path: str | None = "auto",
    index: RepoIndex | None = None,
) -> list[Diagnostic]:
    """The gate-shaped entry: analyze ``root`` (default: the installed
    tpuflow package), subtract the baseline, and report the remainder
    PLUS any stale baseline entries. Pass ``index`` to reuse an
    already-built walk (the CLI builds ONE index for both repo-wide
    passes)."""
    root = root or default_root()
    if baseline_path == "auto":
        candidate = default_baseline_path(root)
        baseline_path = candidate if os.path.exists(candidate) else None
    findings = analyze_index(index if index is not None
                             else build_index(root))
    entries = load_baseline(baseline_path) if baseline_path else []
    by_key: dict[tuple, dict] = {}
    for e in entries:
        by_key.setdefault(_baseline_key(e), e)
    used: set = set()
    out: list[Diagnostic] = []
    for f in findings:
        if f.fingerprint in by_key:
            used.add(f.fingerprint)
            continue
        out.append(f.diagnostic())
    for e in entries:
        if _baseline_key(e) not in used:
            out.append(Diagnostic(
                pass_name=_PASS, code=STALE_CODE,
                message=(
                    f"stale baseline entry {e['rule']} "
                    f"{e['file']}::{e['scope']}::{e['subject']} — the "
                    "finding it accepts no longer exists; prune it "
                    f"from {baseline_path}"
                ),
                where=baseline_path,
            ))
    return out
