"""Pass 1 — job-spec validation: cross-field checks on ``TrainJobConfig``.

Every check here is a pure function of the config (plus the process
environment for ``TPUFLOW_FAULTS``): no data is read, no model is built,
no device is touched. Each finding names the offending field and, for
enum-like fields, the valid choices — the reference system's
submit-and-wait-for-the-cluster-traceback loop (PAPERS.md: SparkNet,
BigDL) replaced by a millisecond rejection at the door.

Error texts for conditions the training path also guards keep the
training path's exact phrasing (``needs data_path``, ``bounded-memory
stream``, ``JSON-serializable``, ...) so a caller that matched on the
late error keeps matching on the early one.
"""

from __future__ import annotations

import json
import os

from tpuflow.analysis.diagnostics import Diagnostic

_PASS = "spec"

# The fields whose values are registry keys, and where the registry lives.
_RESIDUAL_MODELS = ("gilbert_residual", "lstm_residual")


def _diag(code, message, where=None, choices=(), severity="error"):
    return Diagnostic(
        pass_name=_PASS, code=code, message=message, where=where,
        choices=tuple(choices), severity=severity,
    )


def _check_registries(config) -> list[Diagnostic]:
    from tpuflow.core.losses import LOSSES
    from tpuflow.models import MODELS
    from tpuflow.train.optim import OPTIMIZERS

    out = []
    if config.model not in MODELS:
        out.append(_diag(
            "spec.model.unknown",
            f"unknown model {config.model!r}",
            where="model", choices=sorted(MODELS),
        ))
    if config.loss not in LOSSES:
        out.append(_diag(
            "spec.loss.unknown",
            f"unknown loss {config.loss!r}",
            where="loss", choices=sorted(LOSSES),
        ))
    if config.optimizer not in OPTIMIZERS:
        out.append(_diag(
            "spec.optimizer.unknown",
            f"unknown optimizer {config.optimizer!r}",
            where="optimizer", choices=sorted(OPTIMIZERS),
        ))
    return out


def _check_schema(config) -> list[Diagnostic]:
    from tpuflow.data.schema import Schema
    from tpuflow.data.synthetic import (
        SYNTHETIC_COLUMN_NAMES,
        SYNTHETIC_COLUMN_TYPES,
        SYNTHETIC_TARGET,
    )

    names = config.column_names or SYNTHETIC_COLUMN_NAMES
    types = config.column_types or SYNTHETIC_COLUMN_TYPES
    target = config.target or SYNTHETIC_TARGET
    out = []
    try:
        schema = Schema.from_cli(names, types, target)
    except ValueError as e:
        return [_diag(
            "spec.schema.invalid", str(e),
            where="column_names/column_types/target",
        )]
    if not schema.feature_columns:
        out.append(_diag(
            "spec.schema.no_features",
            "schema has no feature columns (every column is the target)",
            where="column_names",
        ))
    if config.well_column and config.well_column not in schema.names:
        out.append(_diag(
            "spec.well_column.unknown",
            f"well_column {config.well_column!r} is not a schema column",
            where="well_column", choices=schema.names,
        ))
    if config.model in _RESIDUAL_MODELS:
        missing = {"pressure", "choke", "glr"} - set(schema.names)
        if missing:
            out.append(_diag(
                "spec.schema.physics_columns",
                f"{config.model} needs pressure/choke/glr columns; "
                f"schema is missing {sorted(missing)}",
                where="column_names",
            ))
    return out


def _check_scalars(config) -> list[Diagnostic]:
    out = []
    positive = (
        ("batch_size", config.batch_size),
        ("max_epochs", config.max_epochs),
        ("window", config.window),
        ("stride", config.stride),
        ("accumulate_steps", config.accumulate_steps),
        ("synthetic_wells", config.synthetic_wells),
        ("synthetic_steps", config.synthetic_steps),
        ("stream_chunk_rows", config.stream_chunk_rows),
        ("stream_sample_rows", config.stream_sample_rows),
        ("stream_eval_rows", config.stream_eval_rows),
    )
    for name, value in positive:
        if value < 1:
            out.append(_diag(
                f"spec.{name}.range",
                f"{name} must be >= 1, got {value}", where=name,
            ))
    non_negative = (
        ("patience", config.patience),
        ("clip_norm", config.clip_norm),
        ("save_every", config.save_every),
        ("stream_shuffle_buffer", config.stream_shuffle_buffer),
        ("pp_microbatches", config.pp_microbatches),
    )
    for name, value in non_negative:
        if value < 0:
            out.append(_diag(
                f"spec.{name}.range",
                f"{name} must be >= 0, got {value}", where=name,
            ))
    return out


def _check_windowing(config) -> list[Diagnostic]:
    from tpuflow.models import MODELS

    if config.model not in MODELS or not config.is_sequence_model:
        return []
    if config.data_path is None and config.window > config.synthetic_steps:
        return [_diag(
            "spec.window.empty",
            f"window {config.window} > synthetic_steps "
            f"{config.synthetic_steps}: every synthetic well yields ZERO "
            "windows (no training data)",
            where="window",
        )]
    return []


def _check_stream(config) -> list[Diagnostic]:
    out = []
    if not config.stream:
        return out
    if config.data_path is None:
        out.append(_diag(
            "spec.stream.data_path",
            "stream=True needs data_path (nothing to stream)",
            where="data_path",
        ))
    if config.is_sequence_model and config.well_column is None:
        out.append(_diag(
            "spec.stream.well_column",
            "streaming sequence ingest splits train/val/test by WELL "
            "(windows must not straddle splits); pass well_column",
            where="well_column",
        ))
    if config.model in _RESIDUAL_MODELS:
        out.append(_diag(
            "spec.stream.residual",
            f"stream=True does not support {config.model} (the Gilbert "
            "channel is appended by the materialized pipeline)",
            where="model",
        ))
    if config.jit_epoch is True:
        out.append(_diag(
            "spec.stream.jit_epoch",
            "jit_epoch stacks the whole epoch into device arrays and "
            "would defeat the bounded-memory stream; use per-batch "
            "stepping for streaming runs",
            where="jit_epoch",
        ))
    return out


def _check_storage(config) -> list[Diagnostic]:
    out = []
    if config.save_every and not config.storage_path:
        out.append(_diag(
            "spec.save_every.storage", severity="warning",
            message=f"save_every={config.save_every} without storage_path: "
            "no run checkpoints will be written",
            where="save_every",
        ))
    if config.resume and not config.storage_path:
        out.append(_diag(
            "spec.resume.storage", severity="warning",
            message="resume=True without storage_path: there is no "
            "checkpoint tree to resume from",
            where="resume",
        ))
    if not isinstance(config.model_kwargs, dict):
        out.append(_diag(
            "spec.model_kwargs.type",
            f"model_kwargs must be a dict, got "
            f"{type(config.model_kwargs).__name__}",
            where="model_kwargs",
        ))
    elif config.storage_path:
        from tpuflow.api.train_api import _sidecar_kwargs

        try:
            json.dumps(_sidecar_kwargs(config.model_kwargs))
        except (TypeError, ValueError) as e:
            out.append(_diag(
                "spec.model_kwargs.json",
                f"model_kwargs must be JSON-serializable when storage_path "
                f"is set (the serving sidecar records them): {e}",
                where="model_kwargs",
            ))
    if not isinstance(config.optimizer_kwargs, dict):
        out.append(_diag(
            "spec.optimizer_kwargs.type",
            f"optimizer_kwargs must be a dict, got "
            f"{type(config.optimizer_kwargs).__name__}",
            where="optimizer_kwargs",
        ))
    return out


def _check_precision(config) -> list[Diagnostic]:
    from tpuflow.train.precision import PRECISIONS

    precision = getattr(config, "precision", "f32")
    if precision in PRECISIONS:
        return []
    return [_diag(
        "spec.precision.unknown",
        f"unknown precision {precision!r}",
        where="precision",
        choices=list(PRECISIONS),
    )]


def _check_health(config) -> list[Diagnostic]:
    from tpuflow.obs.health import HEALTH_OFF, HEALTH_POLICIES

    policy = config.health
    if policy in HEALTH_OFF or policy in HEALTH_POLICIES:
        return []
    return [_diag(
        "spec.health.unknown",
        f"unknown health policy {policy!r}",
        where="health",
        choices=sorted(HEALTH_POLICIES) + ["off"],
    )]


def _check_faults(config) -> list[Diagnostic]:
    from tpuflow.resilience.faults import SITES, parse_fault_spec

    out = []
    for i, entry in enumerate(config.faults or ()):
        if not isinstance(entry, str):
            out.append(_diag(
                "spec.faults.type",
                f"faults[{i}] must be a 'site[,key=value...]' string, "
                f"got {type(entry).__name__}: {entry!r}",
                where=f"faults[{i}]",
            ))
            continue
        try:
            parse_fault_spec(entry)
        except (ValueError, TypeError) as e:
            out.append(_diag(
                "spec.faults.invalid",
                f"faults[{i}] {entry!r}: {e}",
                where=f"faults[{i}]", choices=sorted(SITES),
            ))
    from tpuflow.resilience.faults import (
        FAULTS_ENV_GRAMMAR,
        parse_fault_entries,
    )

    # The SAME parse loop the runtime arms with: a value that preflights
    # clean here is by construction a value fault_point will accept.
    env_specs, errors = parse_fault_entries(
        os.environ.get("TPUFLOW_FAULTS", "")
    )
    for entry, msg in errors:
        out.append(_diag(
            "spec.faults.env",
            f"TPUFLOW_FAULTS entry {entry!r}: {msg} "
            f"(expected {FAULTS_ENV_GRAMMAR})",
            where="TPUFLOW_FAULTS", choices=sorted(SITES),
        ))
    # A site armed by BOTH this job's faults list and the ambient
    # TPUFLOW_FAULTS is legal but easy to misread — surface the
    # documented precedence (resilience/faults.py: config specs are
    # evaluated first at every hit, and when one fires the env spec's
    # counters do not advance on that call) as a warning naming the
    # colliding site, so a drill author learns which spec will win
    # BEFORE the run instead of from a confusing firing log.
    config_sites = set()
    for entry in config.faults or ():
        if isinstance(entry, str):
            try:
                config_sites.add(parse_fault_spec(entry).site)
            except (ValueError, TypeError):
                pass  # already reported above
    env_sites = {spec.site for spec in env_specs}
    for site in sorted(config_sites & env_sites):
        out.append(_diag(
            "spec.faults.precedence",
            f"fault site {site!r} is armed by BOTH this job's faults "
            "list and TPUFLOW_FAULTS — the job's spec is evaluated "
            "first at every hit, and when it fires the env spec's "
            "counters do not advance on that call (documented "
            "precedence, tpuflow/resilience/faults.py)",
            where="faults",
            severity="warning",
        ))
    return out


def _check_elastic(config) -> list[Diagnostic]:
    from tpuflow.elastic import validate_elastic_block

    block = config.elastic
    if block is None:
        return []
    out = [
        _diag("spec.elastic.invalid", msg, where="elastic")
        for msg in validate_elastic_block(block)
    ]
    if config.stream:
        out.append(_diag(
            "spec.elastic.stream",
            "elastic workers shard the materialized training rows; "
            "stream=True has no arrays to shard",
            where="elastic",
        ))
    for axis in ("tp", "pp", "ep"):
        if getattr(config, axis, 1) > 1:
            out.append(_diag(
                "spec.elastic.model_axis",
                f"elastic is process-level data parallelism; {axis}="
                f"{getattr(config, axis)} (an in-worker model axis) is "
                "not supported inside an elastic worker",
                where=axis,
            ))
    if config.n_devices is None:
        # n_devices > 1 is the fleet-of-meshes shape (each worker is
        # itself data-parallel across its local devices, through
        # parallel/compat.py + make_mesh); only UNSET is flagged —
        # every co-located worker defaulting to ALL visible devices
        # would oversubscribe the host's mesh N times over.
        out.append(_diag(
            "spec.elastic.n_devices", severity="warning",
            message="elastic with n_devices unset defaults to ALL "
            "visible devices inside every worker; set it explicitly — "
            "1 for process-level DP only (runner-built specs default "
            "to that), >1 for an in-worker data-parallel mesh",
            where="n_devices",
        ))
    return out


def _check_autotune(config) -> list[Diagnostic]:
    block = getattr(config, "autotune", None)
    if block is None:
        return []
    from tpuflow.train.autotune import validate_autotune_block

    out = [
        _diag("spec.autotune.invalid", msg, where="autotune")
        for msg in validate_autotune_block(block)
    ]
    # The online tuner drives the DEFAULT single-chip step programs:
    # combinations that inject their own steps (or bake the microbatch
    # into an iterator) are rejected at submission with the same
    # reasons train() raises at runtime.
    if config.stream:
        out.append(_diag(
            "spec.autotune.stream",
            "autotune resizes the microbatch between epochs; "
            "stream=True bakes it into the per-epoch iterators",
            where="stream",
        ))
    for axis in ("tp", "pp", "ep"):
        if getattr(config, axis, 1) > 1:
            out.append(_diag(
                "spec.autotune.model_axis",
                f"autotune drives the default single-chip steps; "
                f"{axis}={getattr(config, axis)} injects its own step "
                "programs",
                where=axis,
            ))
    if config.elastic is not None:
        out.append(_diag(
            "spec.autotune.elastic",
            "autotune is per-run; elastic gang workers must keep one "
            "shard shape for averaging",
            where="elastic",
        ))
    if config.n_devices is not None and config.n_devices > 1:
        out.append(_diag(
            "spec.autotune.n_devices",
            f"autotune drives the single-chip default steps; "
            f"n_devices={config.n_devices} (set n_devices=1)",
            where="n_devices",
        ))
    elif config.n_devices is None:
        out.append(_diag(
            "spec.autotune.n_devices", severity="warning",
            message="autotune with n_devices unset defaults to ALL "
            "visible devices and will be rejected at runtime on a "
            "multi-device host; set n_devices=1",
            where="n_devices",
        ))
    return out


def _check_online(config) -> list[Diagnostic]:
    out = []
    ws = getattr(config, "warm_start", None)
    if ws is not None and not isinstance(ws, str):
        out.append(_diag(
            "spec.warm_start.type",
            f"warm_start must be an artifact storage_path string or "
            f"null, got {type(ws).__name__}",
            where="warm_start",
        ))
    block = getattr(config, "online", None)
    if block is None:
        return out
    from tpuflow.online import validate_online_block

    out += [
        _diag("spec.online.invalid", msg, where="online")
        for msg in validate_online_block(block)
    ]
    if not config.storage_path:
        out.append(_diag(
            "spec.online.storage",
            "online training needs storage_path (the serving artifact "
            "is the loop's anchor — warm starts resume from it, swaps "
            "promote into it)",
            where="storage_path",
        ))
    if config.data_path is None:
        out.append(_diag(
            "spec.online.data_path",
            "online training needs data_path (the stream to score and "
            "retrain on)",
            where="data_path",
        ))
    return out


def validate_spec(config) -> list[Diagnostic]:
    """Cross-field validation of a ``TrainJobConfig``; returns ALL
    findings, never raises on a bad spec.

    Each sub-check runs behind a safety net: a config field with an
    unusable TYPE (a JSON spec can put a string where an int belongs)
    must surface as a finding against that check, not abort the whole
    preflight with a traceback and hide every other finding.
    """
    out = []
    for check in (
        _check_registries, _check_schema, _check_scalars,
        _check_windowing, _check_stream, _check_storage, _check_health,
        _check_precision, _check_faults, _check_elastic,
        _check_autotune, _check_online,
    ):
        try:
            out += check(config)
        except Exception as e:  # noqa: BLE001 — the net IS the contract
            out.append(_diag(
                "spec.unusable_config",
                f"{check.__name__.lstrip('_')} could not run on this "
                f"config ({type(e).__name__}: {e}) — a field has an "
                "unusable type or value",
            ))
    return out
