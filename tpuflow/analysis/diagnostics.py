"""Diagnostics: the one result type every static-analysis pass emits.

A pass never raises on a bad job — it *collects* :class:`Diagnostic`
records, so one preflight run reports EVERY problem in a spec instead of
the first one (the submit-fix-resubmit loop a fail-fast validator forces
is exactly the cluster-time waste preflight exists to kill). Raising is
the *caller's* policy: entry points that must fail fast wrap the
collected errors in :class:`PreflightError`.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class Diagnostic:
    """One finding from one analysis pass.

    ``where`` names the config field for spec/plan/shape findings and
    ``file:line`` for lint findings; ``choices`` carries the valid
    alternatives when the finding is a bad enum-like value (the error a
    user can act on without opening the source).
    """

    pass_name: str  # "spec" | "shape" | "plan" | "lint"
    code: str  # stable machine key, e.g. "spec.model.unknown", "TPF001"
    message: str
    where: str | None = None
    choices: tuple = ()
    severity: str = "error"  # "error" | "warning"

    def render(self) -> str:
        loc = f" [{self.where}]" if self.where else ""
        text = f"{self.pass_name}:{loc} {self.code}: {self.message}"
        if self.choices:
            text += f" (valid: {', '.join(str(c) for c in self.choices)})"
        return text


@dataclass
class PreflightReport:
    """Aggregated diagnostics from every pass that ran."""

    diagnostics: list[Diagnostic] = field(default_factory=list)
    passes_run: tuple = ()

    @property
    def errors(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warning"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def extend(self, diags) -> None:
        self.diagnostics.extend(diags)

    def render(self) -> str:
        if not self.diagnostics:
            return (
                f"preflight OK ({', '.join(self.passes_run)}): "
                "no findings"
            )
        lines = [
            f"preflight: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s) "
            f"({', '.join(self.passes_run)})"
        ]
        lines += [f"  {d.render()}" for d in self.diagnostics]
        return "\n".join(lines)


class PreflightError(ValueError):
    """A preflight found errors and the caller asked to fail fast.

    Subclasses ``ValueError`` so every existing submission seam that
    already maps ``ValueError`` to "bad request / exit 2" keeps working
    unchanged. ``report`` carries the full structured findings.
    """

    def __init__(self, report: PreflightReport):
        self.report = report
        super().__init__(report.render())
