"""Artifact/config compatibility: static checks for the serving sidecar.

``Predictor.load`` reads a JSON sidecar (``save_artifact_meta``) and
rebuilds the model it describes. A sidecar hand-edited, written by an
older build, or pointed at the wrong model family used to die deep in
Orbax restore with a pytree mismatch; these checks reject it at load
with the field that is wrong. Same discipline as job preflight: collect
every finding, let the caller decide to raise.
"""

from __future__ import annotations

from tpuflow.analysis.diagnostics import Diagnostic

_PASS = "artifact"

_REQUIRED_KEYS = ("model", "model_kwargs", "kind", "preprocessor",
                  "sample_shape")
_KINDS = ("tabular", "windowed")


def _expected_kind(model: str) -> str:
    """The sidecar kind a model family serves from: sequence families
    (``TrainJobConfig.is_sequence_model`` — the one source of that
    classification) train through the windowed pipeline and serve from a
    "windowed" sidecar; everything else is tabular."""
    from tpuflow.api.config import TrainJobConfig

    seq = TrainJobConfig(model=model).is_sequence_model
    return "windowed" if seq else "tabular"


def _diag(code, message, where=None, choices=()):
    return Diagnostic(
        pass_name=_PASS, code=code, message=message, where=where,
        choices=tuple(choices),
    )


def check_artifact_meta(meta: dict) -> list[Diagnostic]:
    """Validate a serving sidecar dict; returns ALL findings."""
    from tpuflow.models import MODELS

    if not isinstance(meta, dict):
        # A sidecar file holding valid-but-non-object JSON ('null', a
        # number) must be a finding, not a TypeError that escapes the
        # never-raises contract (and the callers' ValueError mapping).
        return [_diag(
            "artifact.meta.type",
            f"sidecar must be a JSON object, got "
            f"{type(meta).__name__}: {meta!r}",
            where="meta",
        )]
    out = []
    missing = [k for k in _REQUIRED_KEYS if k not in meta]
    if missing:
        return [_diag(
            "artifact.keys.missing",
            f"sidecar is missing required keys {missing}",
            where="meta", choices=_REQUIRED_KEYS,
        )]
    model = meta["model"]
    if model not in MODELS:
        out.append(_diag(
            "artifact.model.unknown",
            f"sidecar names unknown model {model!r}",
            where="model", choices=sorted(MODELS),
        ))
    if meta["kind"] not in _KINDS:
        out.append(_diag(
            "artifact.kind.unknown",
            f"sidecar kind {meta['kind']!r} is not a serving kind",
            where="kind", choices=_KINDS,
        ))
    elif model in MODELS:
        expect = _expected_kind(model)
        if meta["kind"] != expect:
            out.append(_diag(
                "artifact.kind.mismatch",
                f"model {model!r} serves from a {expect!r} sidecar, got "
                f"kind {meta['kind']!r} (sidecar and checkpoint describe "
                "different artifacts)",
                where="kind",
            ))
    if not isinstance(meta["model_kwargs"], dict):
        out.append(_diag(
            "artifact.model_kwargs.type",
            f"sidecar model_kwargs must be a dict, got "
            f"{type(meta['model_kwargs']).__name__}",
            where="model_kwargs",
        ))
    shape = meta["sample_shape"]
    if (
        not isinstance(shape, (list, tuple))
        or not shape
        or not all(isinstance(d, int) and d > 0 for d in shape)
    ):
        out.append(_diag(
            "artifact.sample_shape.invalid",
            f"sidecar sample_shape must be a non-empty list of positive "
            f"ints, got {shape!r}",
            where="sample_shape",
        ))
    if out:
        return out

    # Abstract end-to-end: the recorded kwargs must actually build the
    # recorded model and init at the recorded sample shape — eval_shape,
    # so no weights are materialized and nothing compiles.
    import jax
    import jax.numpy as jnp

    from tpuflow.models import build_model

    try:
        model_obj = build_model(model, **meta["model_kwargs"])
        rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
        x = jax.ShapeDtypeStruct((2, *shape[1:]), jnp.float32)
        jax.eval_shape(model_obj.init, rng, x)
    except Exception as e:  # noqa: BLE001 — any init failure IS the finding
        out.append(_diag(
            "artifact.init",
            f"sidecar model {model!r} with kwargs {meta['model_kwargs']!r} "
            f"does not init at sample_shape {list(shape)}: "
            f"{type(e).__name__}: {e}",
            where="model_kwargs",
        ))
    return out


def ensure_artifact_meta(meta: dict, where: str = "artifact") -> None:
    """Raise ``ValueError`` naming every sidecar problem (the raising
    flavor ``Predictor.load`` calls before touching the checkpoint)."""
    findings = check_artifact_meta(meta)
    if findings:
        raise ValueError(
            f"{where}: incompatible serving sidecar — "
            + "; ".join(d.render() for d in findings)
        )
