"""Pass 3 — plan checking: mesh/divisibility validation for dp/tp/pp/ep.

The static half of parallel-plan validation, shared by preflight and the
training path: ``tpuflow.api.train_api`` delegates its pre-ingest
model-axis validation here (one rule set, two callers), so a plan bug
rejected at submission and a plan bug rejected before ingest are the
same rule with the same message. Axis semantics follow
``tpuflow/parallel/mesh.py``: a ``(data, model)`` mesh where tp/pp/ep
size the model axis and the remaining devices do data parallelism.

All checks are arithmetic over the config plus the device topology
numbers the caller passes in (``device_count``, ``local_device_count``,
``process_count``) — nothing here queries a backend, so preflight can
check an 8-chip plan from a loginless CI node by passing
``device_count=8``.
"""

from __future__ import annotations

from tpuflow.analysis.diagnostics import Diagnostic

_PASS = "plan"

# Families whose params form the Dense stack mlp_tp_shardings can shard
# megatron-style (tpuflow/parallel/tp_train.py's structural check, made
# name-static so a bad plan fails at submission, not after ingest).
TP_FAMILIES = ("static_mlp", "dynamic_mlp", "gilbert_residual")


def _default_hidden(model_name: str):
    """The family's default hidden widths, read off the registry's own
    module instance — not a parallel table that could go stale and turn
    this pre-ingest gate into the wrong authority."""
    from tpuflow.models import build_model

    return build_model(model_name).hidden


def _diag(code, message, where=None, severity="error"):
    return Diagnostic(
        pass_name=_PASS, code=code, message=message, where=where,
        severity=severity,
    )


def _kwargs(config) -> dict:
    """model_kwargs if it IS a dict, else {} — an ill-typed value is the
    spec pass's finding; this pass must keep collecting, not crash."""
    kw = config.model_kwargs
    return kw if isinstance(kw, dict) else {}


def _check_tp_family(config) -> list[Diagnostic]:
    from tpuflow.models import MODELS

    if config.model not in MODELS:
        return []  # the spec pass owns unknown-model findings
    if config.model not in TP_FAMILIES:
        return [_diag(
            "plan.tp.family",
            f"tp training supports Dense-stack MLP families "
            f"{list(TP_FAMILIES)}; got model {config.model!r}",
            where="tp",
        )]
    out = []
    hidden = _kwargs(config).get("hidden")
    if hidden is None:
        hidden = _default_hidden(config.model)
    if not isinstance(hidden, int) and not (
        isinstance(hidden, (list, tuple))
        and all(isinstance(h, int) for h in hidden)
    ):
        return out  # ill-typed hidden: the shape pass owns that finding
    hidden = (hidden,) if isinstance(hidden, int) else tuple(hidden)
    # Megatron alternation (mlp_tp_shardings): even-indexed hidden
    # layers are column-parallel and the following row-parallel layer
    # splits the same width, so the even-indexed widths must divide.
    for i in range(0, len(hidden), 2):
        if hidden[i] % config.tp:
            out.append(_diag(
                "plan.tp.hidden",
                f"hidden dim {hidden[i]} (layer {i}) not divisible by "
                f"tp={config.tp}",
                where="model_kwargs.hidden",
            ))
    return out


def check_plan(
    config,
    *,
    device_count: int | None = None,
    local_device_count: int | None = None,
    process_count: int = 1,
    jit_epoch: bool | None = None,
) -> list[Diagnostic]:
    """Validate the parallel plan; returns ALL findings, never raises.

    ``device_count`` is the visible device total (``jax.device_count()``
    on a live runtime, or the target topology when checking offline);
    ``jit_epoch=None`` means "not yet resolved" and only an explicit
    ``config.jit_epoch=True`` is held against the model axes.
    """
    out = []
    if jit_epoch is None:
        jit_epoch = config.jit_epoch is True
    for name in ("tp", "pp", "ep"):
        if getattr(config, name) < 1:
            out.append(_diag(
                f"plan.{name}.range",
                f"{name} must be >= 1, got {getattr(config, name)}",
                where=name,
            ))
            return out  # the divisibility arithmetic below needs >= 1
    if sum(n > 1 for n in (config.tp, config.pp, config.ep)) > 1:
        out.append(_diag(
            "plan.axis.combined",
            "tp, pp, and ep cannot be combined yet; pick one model-axis "
            "strategy per job",
            where="tp/pp/ep",
        ))
        return out  # per-axis arithmetic is meaningless on a bad combo
    if config.pp_microbatches and config.pp <= 1:
        out.append(_diag(
            "plan.pp.microbatches",
            "pp_microbatches is a pipeline knob; set pp>1 (a value "
            "silently ignored would fake GPipe accumulation)",
            where="pp_microbatches",
        ))
    if config.pp > 1 and config.model != "pipeline_mlp":
        out.append(_diag(
            "plan.pp.family",
            f"pp>1 training supports the pipeline_mlp family; got model "
            f"{config.model!r}",
            where="pp",
        ))
    if config.ep > 1 and config.model != "moe_mlp":
        out.append(_diag(
            "plan.ep.family",
            f"ep>1 training supports the moe_mlp family; got model "
            f"{config.model!r}",
            where="ep",
        ))
    if config.tp > 1:
        out += _check_tp_family(config)
    if config.pp > 1 and config.model == "pipeline_mlp":
        stages = _kwargs(config).get("stages")
        if stages is None:
            from tpuflow.models import build_model

            stages = build_model("pipeline_mlp").stages
        if isinstance(stages, int) and stages % config.pp:
            out.append(_diag(
                "plan.pp.stages",
                f"pipeline_mlp stages={stages} not divisible by "
                f"pp={config.pp} devices (each device owns an equal "
                "contiguous stage chunk)",
                where="model_kwargs.stages",
            ))
    if config.ep > 1 and config.model == "moe_mlp":
        experts = _kwargs(config).get("experts")
        if experts is None:
            from tpuflow.models import build_model

            experts = build_model("moe_mlp").experts
        if isinstance(experts, int) and experts % config.ep:
            out.append(_diag(
                "plan.ep.experts",
                f"moe_mlp experts={experts} not divisible by "
                f"ep={config.ep} devices (each device owns an equal "
                "contiguous expert chunk)",
                where="model_kwargs.experts",
            ))

    n_dev = config.n_devices or device_count
    if n_dev is None:
        out.append(_diag(
            "plan.devices.unknown", severity="warning",
            message="device count unknown (no n_devices in the config and "
            "no --devices given): divisibility checks skipped",
            where="n_devices",
        ))
        return out
    if n_dev < 1:
        out.append(_diag(
            "plan.devices.range",
            f"n_devices must be >= 1, got {n_dev}", where="n_devices",
        ))
        return out
    if (
        config.n_devices
        and device_count is not None
        and config.n_devices > device_count
    ):
        out.append(_diag(
            "plan.devices.visible",
            f"n_devices {config.n_devices} > {device_count} visible "
            "devices",
            where="n_devices",
        ))

    # The mesh-tiling arithmetic is the mesh factory's own rule
    # (tpuflow/parallel/mesh.py data_axis_size): a plan rejected here
    # and a mesh rejected at construction are the same check.
    from tpuflow.parallel.mesh import data_axis_size

    model_axis = 1
    for name in ("tp", "pp", "ep"):
        n = getattr(config, name)
        if n <= 1:
            continue
        model_axis = n
        if jit_epoch:
            out.append(_diag(
                f"plan.{name}.jit_epoch",
                f"{name}>1 trains through its per-batch sharded step; "
                f"jit_epoch is not supported with {name}",
                where="jit_epoch",
            ))
        try:
            data_axis_size(n_dev, n)
        except ValueError:
            out.append(_diag(
                f"plan.{name}.devices",
                f"n_devices {n_dev} not divisible by {name}={n}",
                where=name,
            ))
    if out and any(d.code.endswith(".devices") for d in out):
        return out  # dp-size arithmetic below would divide by air
    if config.pp > 1:
        n_micro = config.pp_microbatches or config.pp
        if config.batch_size % n_micro:
            out.append(_diag(
                "plan.pp.batch",
                f"batch_size {config.batch_size} not divisible by "
                f"{n_micro} pipeline microbatches",
                where="batch_size",
            ))
        elif (config.batch_size // n_micro) % (n_dev // config.pp):
            out.append(_diag(
                "plan.pp.microbatch_dp",
                f"microbatch {config.batch_size // n_micro} not divisible "
                f"by {n_dev // config.pp} data-parallel devices",
                where="batch_size",
            ))
    for name in ("tp", "ep"):
        n = getattr(config, name)
        if n > 1 and config.batch_size % (n_dev // n):
            out.append(_diag(
                f"plan.{name}.batch",
                f"batch_size {config.batch_size} not divisible by "
                f"{n_dev // n} data-parallel devices",
                where="batch_size",
            ))
    if model_axis == 1 and n_dev > 1 and config.batch_size % n_dev:
        out.append(_diag(
            "plan.dp.batch",
            f"batch_size {config.batch_size} not divisible by {n_dev} "
            "devices",
            where="batch_size",
        ))

    # Multi-host shape constraints (identical across tp/pp/ep — they ride
    # the same (data, model) mesh layout).
    if model_axis > 1 and process_count > 1:
        axis_name = (
            "tp" if config.tp > 1 else "pp" if config.pp > 1 else "ep"
        )
        total = device_count if device_count is not None else n_dev
        if n_dev != total:
            out.append(_diag(
                "plan.multihost.submesh",
                f"multi-host {axis_name} needs the full pod: n_devices "
                f"{n_dev} != device_count {total}",
                where="n_devices",
            ))
        if local_device_count is not None and local_device_count % model_axis:
            out.append(_diag(
                "plan.multihost.local",
                f"multi-host {axis_name}={model_axis} needs the "
                f"{local_device_count} local devices per process to be a "
                f"multiple of {axis_name}",
                where=axis_name,
            ))
    return out


def check_serve_plan(
    replicas: int, device_count: int | None = None
) -> list[Diagnostic]:
    """Serving-plan arithmetic: can ``replicas`` predictor replicas be
    placed one-per-device? The same contract as the train-plan checks —
    a count the hardware cannot place is a DIAGNOSTIC naming the device
    count and the fix, collected before any artifact loads or lanes
    open, never a runtime crash deep in a device_put. Pass
    ``device_count`` explicitly to check a remote topology from a
    loginless node; default reads the local placement seam."""
    out: list[Diagnostic] = []
    try:
        replicas = int(replicas)
    except (TypeError, ValueError):
        return [_diag(
            "plan.serve.replicas_invalid",
            f"replicas must be an integer >= 1, got {replicas!r}",
            where="replicas",
        )]
    if replicas < 1:
        return [_diag(
            "plan.serve.replicas_invalid",
            f"replicas must be >= 1, got {replicas}",
            where="replicas",
        )]
    # The placement seam's own validation is the one source of truth
    # for the can-these-replicas-be-placed rule AND its advice text —
    # re-implementing it here is how the diagnostic and the
    # construction-time ValueError would drift apart. A remote
    # topology checks against a synthetic device list of the given
    # length (replica_devices only counts and slices).
    from tpuflow.parallel.placement import replica_devices

    try:
        replica_devices(
            replicas,
            devices=(
                None if device_count is None
                else [None] * int(device_count)
            ),
        )
    except ValueError as e:
        out.append(_diag(
            "plan.serve.replicas_exceed_devices",
            str(e),
            where="replicas",
        ))
    return out
