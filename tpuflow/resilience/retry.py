"""Retry with exponential backoff + jitter + deadline for transient I/O.

The reference absorbed transient failure at the cluster layer: a Spark
task that died on a flaky HDFS read was simply rerun (SURVEY.md §5.3).
tpuflow's equivalent has to live at the I/O call sites — checkpoint
storage writes/restores and CSV/stream reads — where a transient error
(NFS hiccup, gs:// 503, a briefly-missing mount) should cost a short
sleep, not the whole training attempt.

``retry_call(policy, fn)`` retries ``fn`` on :class:`TransientFault`
(injected drills) and the policy's ``retry_on`` exception types (OSError
by default — the real-world transient class). Everything else — a
malformed CSV's ValueError, a real bug — propagates immediately:
retrying a deterministic failure just triples its latency.

Delays follow ``min(base * multiplier**attempt, max_delay)`` with
``±jitter`` proportional noise (decorrelates fleet-wide retry storms)
and a total ``deadline``; with ``seed`` set the jitter stream is
deterministic, so a drill's timing replays exactly. ``sleep`` is
injectable for zero-wall-clock tests.

Env knobs (read by :func:`io_policy`, the policy every built-in site
uses): ``TPUFLOW_RETRY_ATTEMPTS`` (default 4), ``TPUFLOW_RETRY_BASE``
(seconds, default 0.05), ``TPUFLOW_RETRY_MAX`` (default 2.0),
``TPUFLOW_RETRY_DEADLINE`` (default 30). Values are validated at read
time: a non-numeric or negative value (or a zero attempt count) raises
a ValueError naming the variable and the expected form — the
``TPUFLOW_FAULTS`` fail-loud precedent.
"""

from __future__ import annotations

import os
import random
import sys
import time
from dataclasses import dataclass, field
from typing import Callable

from tpuflow.resilience.faults import TransientFault

# OSError subclasses that are DETERMINISTIC in practice — a typo'd path
# or a permissions misconfiguration replays identically on every
# attempt, so retrying only adds latency and misleading "transient ...
# retrying" log lines. Never treated as transient (an explicit injected
# TransientFault still retries, whatever it subclasses).
NON_TRANSIENT_OSERRORS = (
    FileNotFoundError,
    IsADirectoryError,
    NotADirectoryError,
    PermissionError,
)


@dataclass
class RetryPolicy:
    """Exponential backoff + jitter + deadline."""

    max_attempts: int = 4
    base_delay: float = 0.05  # seconds before the first retry
    multiplier: float = 2.0
    max_delay: float = 2.0
    jitter: float = 0.5  # ± fraction of the delay
    deadline: float | None = 30.0  # total budget across attempts, seconds
    retry_on: tuple = (OSError,)  # TransientFault is always retryable
    seed: int | None = None  # deterministic jitter stream when set
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)

    def is_transient(self, exc: BaseException) -> bool:
        if isinstance(exc, TransientFault):
            return True
        if isinstance(exc, NON_TRANSIENT_OSERRORS):
            return False
        return isinstance(exc, self.retry_on)

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        base = min(
            self.base_delay * self.multiplier ** (attempt - 1),
            self.max_delay,
        )
        if not self.jitter:
            return base
        return base * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))


def retry_call(policy: RetryPolicy, fn: Callable, *args, **kwargs):
    """Call ``fn`` under ``policy``; returns its result or raises the
    last transient error once attempts/deadline are exhausted (tagged
    with ``retry_attempts`` so the failure names how hard it tried)."""
    rng = random.Random(policy.seed) if policy.seed is not None else random
    start = time.monotonic()
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            if not policy.is_transient(e) or attempt == policy.max_attempts:
                e.retry_attempts = attempt
                raise
            delay = policy.delay(attempt, rng)
            if (
                policy.deadline is not None
                and time.monotonic() - start + delay > policy.deadline
            ):
                e.retry_attempts = attempt
                raise
            print(
                f"tpuflow.resilience: transient {type(e).__name__} "
                f"(attempt {attempt}/{policy.max_attempts}), retrying in "
                f"{delay:.3f}s: {e}",
                file=sys.stderr,
            )
            try:
                # Fleet-visible retry pressure: a flaky storage backend
                # shows up as a rising counter, not just stderr noise.
                from tpuflow.obs import default_registry

                default_registry().counter(
                    "io_retries_total",
                    "transient-I/O retry sleeps by exception type",
                ).inc(error=type(e).__name__)
            except Exception:
                pass
            policy.sleep(delay)


def retryable(policy: RetryPolicy):
    """Decorator form of ``retry_call``."""

    def wrap(fn):
        def inner(*args, **kwargs):
            return retry_call(policy, fn, *args, **kwargs)

        inner.__name__ = getattr(fn, "__name__", "retryable")
        inner.__doc__ = fn.__doc__
        return inner

    return wrap


# One validated ``TPUFLOW_RETRY_*`` read (the ``TPUFLOW_FAULTS``
# precedent: the error surfaces deep inside whatever I/O path built the
# policy, far from the shell that exported the variable, so it must say
# exactly what to fix). The implementation is shared with the
# ``TPUFLOW_SERVE_*`` family — tpuflow/utils/env.py is the one copy.
from tpuflow.utils.env import env_number as _env_number  # noqa: E402


def io_policy() -> RetryPolicy:
    """The shared policy for the built-in I/O sites, env-tunable (see the
    module docstring). Built per call so a test's env tweak applies
    without reloads — construction is a few float parses. Malformed env
    knobs (non-numeric, negative, zero attempts) raise a ValueError
    naming the variable and the expected form."""
    return RetryPolicy(
        max_attempts=_env_number(
            "TPUFLOW_RETRY_ATTEMPTS", 4, cast=int, minimum=1,
            form="an integer attempt count >= 1",
        ),
        base_delay=_env_number(
            "TPUFLOW_RETRY_BASE", 0.05, cast=float, minimum=0.0,
            form="a non-negative number of seconds",
        ),
        max_delay=_env_number(
            "TPUFLOW_RETRY_MAX", 2.0, cast=float, minimum=0.0,
            form="a non-negative number of seconds",
        ),
        deadline=_env_number(
            "TPUFLOW_RETRY_DEADLINE", 30.0, cast=float, minimum=0.0,
            form="a non-negative number of seconds",
        ),
    )
