"""Resilience subsystem: failures as a first-class, testable input.

Three legs (see docs/resilience.md):

- ``faults``  — deterministic, seedable fault injection at named sites
  (``fault_point``), armed per-spec or via ``TPUFLOW_FAULTS``.
- ``retry``   — exponential backoff + jitter + deadline for transient
  I/O (``retry_call`` / ``io_policy``), applied at checkpoint storage
  and CSV/stream reads.
- ``degraded``— the Gilbert-equation baseline standing in for a
  missing/corrupt learned artifact in the serve path.

The supervisor's restart backoff, crash-loop classification, and stall
watchdog live with the supervisor (``tpuflow/train/supervisor.py``) and
are drilled through this package's fault sites.
"""

from tpuflow.resilience.degraded import GilbertFallbackPredictor, try_fallback
from tpuflow.resilience.faults import (
    SITES,
    FaultInjected,
    FaultSpec,
    TransientFault,
    arm,
    armed,
    clear_faults,
    disarm,
    fault_point,
    fired_log,
    parse_fault_spec,
)
from tpuflow.resilience.retry import (
    RetryPolicy,
    io_policy,
    retry_call,
    retryable,
)

__all__ = [
    "SITES",
    "FaultInjected",
    "FaultSpec",
    "GilbertFallbackPredictor",
    "RetryPolicy",
    "TransientFault",
    "arm",
    "armed",
    "clear_faults",
    "disarm",
    "fault_point",
    "fired_log",
    "io_policy",
    "parse_fault_spec",
    "retry_call",
    "retryable",
    "try_fallback",
]
