"""Deterministic, seedable fault injection: named sites, reproducible drills.

The reference system's only fault story is Spark task retry at the cluster
layer (SURVEY.md §5.3) — failures happen TO it, never AS an input. Here
failure is a first-class, testable input: code paths that can die in
production declare a **named fault site** (``fault_point("checkpoint.save",
index=epoch)``), and a drill arms a :class:`FaultSpec` against that site —
programmatically (``arm``), through the job spec (``TrainJobConfig.faults``),
or through the ``TPUFLOW_FAULTS`` environment variable (which child
processes inherit, so supervisor drills need no plumbing).

Every firing rule is deterministic:

- ``nth=K``    — fire on the K-th call to the site, once (one-shot by count).
- ``at=K``     — fire when the site's ``index`` equals K, once (one-shot by
  index — e.g. "the epoch-3 checkpoint write").
- ``p=F,seed=S`` — fire each call with probability F from a private
  ``random.Random(S)`` stream, so a probabilistic soak replays identically.

Fire modes:

- ``mode=raise`` (default) — raise :class:`FaultInjected`; with
  ``transient=1`` raise :class:`TransientFault` instead, which the I/O
  retry policy (``resilience/retry.py``) absorbs like a flaky disk.
- ``mode=exit`` — ``os._exit(code)``: a preemption/OOM-kill stand-in with
  no Python cleanup (the supervisor's detect-and-restart drill).
- ``mode=hang`` — sleep forever at the site: a wedged I/O backend, the
  supervisor's stall-watchdog drill.
- ``mode=delay`` — sleep ``delay`` seconds at the site, then continue: a
  slow disk, a congested link, a straggling worker. Combined with ``p=``
  it is the one-line straggler injection the elastic async-vs-sync A/B
  uses (``elastic.transport.send,p=1,mode=delay,delay=0.3``).

The text grammar (one entry per ``;`` in ``TPUFLOW_FAULTS``, or one string
per ``TrainJobConfig.faults`` element)::

    site[,key=value...]
    e.g.  checkpoint.save,at=3,mode=exit
          stream.read,nth=2,transient=1
          serve.execute,p=0.25,seed=7

``SITES`` is the canonical catalog; arming an unknown site fails loudly (a
typo'd drill that silently never fires would fake a passing drill), and a
tier-1 self-check asserts the catalog, the installed ``fault_point`` calls,
and the docs/resilience.md table all agree.

**Precedence.** When an in-process spec (``arm()`` /
``TrainJobConfig.faults``) and a ``TPUFLOW_FAULTS`` spec are armed at
the SAME site, the in-process spec is evaluated first at every
``fault_point`` hit: its hit counter advances first, and when both
would fire on the same call the in-process spec wins — the env drill's
counters only advance once no in-process spec fired. The contract is
deliberate: a job's own fault list is the more specific intent (it was
written for THIS run), the environment is ambient (it leaks into every
process in the tree). Preflight warns (``spec.faults.precedence``)
when a job config and the env collide on a site, naming it.

**Restart-deterministic storms.** ``TPUFLOW_FAULTS_CURSOR`` names a
JSON file persisting each env spec's firing state (hits, fired) —
written on every env-spec hit, restored when a fresh process re-arms
the same ``TPUFLOW_FAULTS`` value. A one-shot (``nth=``/``at=``) that
already fired stays consumed across the restart, and a ``p=,seed=``
stream fast-forwards past its consumed draws — so a supervised child
relaunched mid-storm resumes the SAME storm instead of replaying it
from hit zero, and a seeded soak replays identically even when its
workers die and restart at different moments. Opt-in by design (the
crash-loop drills DEPEND on an env fault re-firing in every attempt):
unset means no persistence, and the literal value ``auto`` is a
sentinel only ``train/supervisor.py`` resolves (to a path next to its
progress file) — unresolved ``auto`` means no persistence too.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

# The canonical fault-site catalog: name -> where it is installed.
# tests/test_resilience.py asserts this dict, the fault_point() calls in
# the source tree, and the docs/resilience.md catalog all name the same
# sites — docs and code cannot drift.
SITES: dict[str, str] = {
    "checkpoint.save": "train/checkpoint.py + train/resume.py: every "
    "Orbax save (best-params and full-run-state); index = epoch",
    "checkpoint.restore": "train/checkpoint.py + train/resume.py: every "
    "Orbax restore (serving load and resume)",
    "csv.read": "data/csv_io.py: whole-file CSV ingest",
    "stream.read": "data/stream.py: one streamed CSV chunk parse",
    "serve.execute": "serve.py JobRunner._execute (start of every "
    "train/compare/sweep job) AND PredictService._run_forward (the "
    "micro-batcher's coalesced dispatch) — one hit counter spans both",
    "train.epoch_start": "train/loop.py: top of each epoch, before any "
    "work (a crash here REPLAYS the epoch after resume); index = epoch",
    "train.epoch_end": "train/loop.py: after an epoch's bookkeeping "
    "(the legacy fault_epoch point); index = epoch",
    "elastic.heartbeat": "elastic/membership.py: every worker heartbeat "
    "write (a firing silences the worker — the eviction drill)",
    "elastic.push": "elastic/exchange.py: every parameter push to the "
    "coordinator; index = averaging round",
    "elastic.join": "elastic/worker.py: worker registration/warm-start, "
    "before the first epoch",
    "elastic.transport.send": "elastic/transport.py: before each RPC "
    "request frame is written to the exchange socket; index = averaging "
    "round for pushes (mode=delay here is the slow-link/straggler knob; "
    "mode=raise is a dropped request)",
    "elastic.transport.recv": "elastic/transport.py: before the RPC "
    "response frame is read back (a firing is a response lost in "
    "flight)",
    "elastic.transport.partition": "elastic/transport.py: at every "
    "exchange connect — arm with p=1 to partition this worker from the "
    "coordinator, disarm to heal",
    "online.drift": "online/drift.py: scoring of one streaming window "
    "against the artifact's reference stats; index = window number",
    "online.retrain": "online/controller.py: launch of one warm-start "
    "retrain (before the replay spill / candidate train); index = "
    "retrain number",
    "online.swap": "online/swap.py: candidate promotion into the serving "
    "artifact path, before any file is moved (a firing rejects the "
    "candidate cleanly)",
    "online.rollback": "online/swap.py: rollback to the retained "
    "previous artifact, before any file is moved",
    "storage.put": "storage/base.py: every object-store PUT (checkpoint "
    "payloads, artifact files, exchange pushes) before any byte lands",
    "storage.get": "storage/base.py: every object-store GET/tail "
    "(restores, artifact loads, exchange reads)",
    "storage.promote": "storage/base.py: every pointer promotion (the "
    "publish instant for BEST/CURRENT/LATEST), before the pointer "
    "object is written",
}

# Sites whose fault_point() passes an index (the at= reproducibility
# key). An at= spec on any other site could never fire — rejected at
# arm time, per this module's fail-loud promise.
INDEXED_SITES = frozenset({
    "checkpoint.save", "checkpoint.restore",
    "train.epoch_start", "train.epoch_end", "elastic.push",
    "elastic.transport.send",
    "online.drift", "online.retrain",
})


class FaultInjected(RuntimeError):
    """An armed fault fired. ``site`` names where; ``spec`` is the spec."""

    def __init__(self, message: str, site: str):
        super().__init__(message)
        self.site = site


class TransientFault(FaultInjected):
    """A fault the I/O retry policy treats as retryable (a flaky disk, a
    dropped connection) — absorbed by ``retry_call`` instead of killing
    the attempt."""


@dataclass
class FaultSpec:
    """One armed fault: where, when, and how it fires."""

    site: str
    nth: int | None = None  # fire on the nth call (1-based), one-shot
    at: int | None = None  # fire when index == at, one-shot
    p: float = 0.0  # fire probability per call (persistent)
    seed: int = 0  # seeds the private probability stream
    mode: str = "raise"  # raise | exit | hang | delay
    code: int = 42  # exit code for mode=exit
    delay: float = 0.05  # sleep seconds for mode=delay
    transient: bool = False  # raise TransientFault (retryable) instead
    on_fire: Callable | None = None  # called just before exit/raise
    # internal state
    hits: int = 0
    fired: int = 0
    _rng: random.Random | None = field(default=None, repr=False)
    # cursor-file key for env-armed specs (TPUFLOW_FAULTS_CURSOR);
    # compare=False keeps it out of the dataclass __eq__ so disarm()'s
    # equality match is unchanged.
    _cursor_key: str | None = field(default=None, repr=False, compare=False)

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r}; known: {sorted(SITES)}"
            )
        if self.mode not in ("raise", "exit", "hang", "delay"):
            raise ValueError(
                f"fault mode must be raise|exit|hang|delay, got "
                f"{self.mode!r}"
            )
        if self.mode == "delay" and (
            not isinstance(self.delay, (int, float)) or self.delay < 0
        ):
            raise ValueError(
                f"fault delay must be a non-negative number of seconds, "
                f"got {self.delay!r}"
            )
        if self.nth is None and self.at is None and not self.p:
            raise ValueError(
                f"fault spec for {self.site!r} never fires: set nth=, at=, "
                "or p="
            )
        if self.at is not None and self.site not in INDEXED_SITES:
            raise ValueError(
                f"fault site {self.site!r} passes no index, so at="
                f"{self.at} could never fire (a drill that silently never "
                f"fires fakes a pass); use nth= or p= here — at= works on "
                f"{sorted(INDEXED_SITES)}"
            )
        if self.p:
            self._rng = random.Random(self.seed)

    def describe(self) -> str:
        when = (
            f"nth={self.nth}" if self.nth is not None
            else f"at={self.at}" if self.at is not None
            else f"p={self.p},seed={self.seed}"
        )
        return f"{self.site},{when},mode={self.mode}"


# The one statement of the multi-entry grammar, shared by the arming
# error below and the preflight spec pass (tpuflow/analysis/spec.py) —
# the validator and the runtime must describe the SAME language.
FAULTS_ENV_GRAMMAR = (
    "';'-separated entries of the form 'site[,key=value...]' "
    "(e.g. 'checkpoint.save,at=3,mode=exit')"
)


def parse_fault_entries(value: str) -> tuple[list, list]:
    """Parse a ``;``-separated multi-spec value (the ``TPUFLOW_FAULTS``
    format). Returns ``(specs, errors)`` where ``errors`` is a list of
    ``(entry, message)`` pairs — never raises, so a validator can report
    EVERY bad entry while the arming path turns any error into its own
    fail-loud raise. One parse loop for both: the language the preflight
    validates is by construction the language the runtime arms."""
    specs, errors = [], []
    for entry in value.split(";"):
        if not entry.strip():
            continue
        try:
            specs.append(parse_fault_spec(entry))
        except ValueError as e:
            errors.append((entry.strip(), str(e)))
    return specs, errors


def parse_fault_spec(text: str) -> FaultSpec:
    """Parse one ``site[,key=value...]`` entry into a FaultSpec."""
    parts = [p.strip() for p in text.strip().split(",") if p.strip()]
    if not parts:
        raise ValueError("empty fault spec")
    kwargs: dict = {"site": parts[0]}
    casts = {
        "nth": int, "at": int, "p": float, "seed": int, "code": int,
        "mode": str, "delay": float, "transient": lambda v: bool(int(v)),
    }
    for opt in parts[1:]:
        if "=" not in opt:
            raise ValueError(
                f"fault spec option {opt!r} must be key=value "
                f"(in {text!r})"
            )
        key, value = opt.split("=", 1)
        key = key.strip()
        if key not in casts:
            raise ValueError(
                f"unknown fault spec option {key!r} (in {text!r}); "
                f"known: {sorted(casts)}"
            )
        kwargs[key] = casts[key](value.strip())
    return FaultSpec(**kwargs)


_LOCK = threading.Lock()
_ARMED: dict[str, list[FaultSpec]] = {}
_FIRED_LOG: list[dict] = []  # {site, spec, index} per firing — for tests
_ENV_CACHE: str | None = None  # last TPUFLOW_FAULTS value parsed
_ENV_SPECS: list[FaultSpec] = []
# Persisted firing state for env specs (TPUFLOW_FAULTS_CURSOR), keyed by
# each spec's position+description in the env value. Tracks EVERY env
# spec — including consumed one-shots no longer in _ARMED — so a restart
# restores the whole storm, not just the still-armed tail.
_CURSOR_ENV = "TPUFLOW_FAULTS_CURSOR"
_ENV_CURSOR: dict[str, dict] = {}


def _cursor_path() -> str | None:
    """The cursor file path, or None when persistence is off. The
    literal ``auto`` is the supervisor's resolve-me sentinel — reaching
    a fault_point unresolved means nobody owns a run directory to put
    the file in, so it degrades to no persistence (not an error: the
    same spec text must work under and outside the supervisor)."""
    value = os.environ.get(_CURSOR_ENV, "").strip()
    if not value or value == "auto":
        return None
    return value


def _read_cursor(path: str) -> dict:
    """Load the cursor file; missing is a clean first run ({}), corrupt
    is fail-loud — resuming a storm from guessed state would fake the
    determinism this file exists to provide."""
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as e:
        raise ValueError(
            f"unreadable {_CURSOR_ENV} file {path!r}: {e} — delete it or "
            f"point {_CURSOR_ENV} at a fresh path; refusing to guess at "
            "storm state"
        ) from e
    if not isinstance(doc, dict):
        raise ValueError(
            f"{_CURSOR_ENV} file {path!r} is not a JSON object — delete "
            f"it or point {_CURSOR_ENV} at a fresh path"
        )
    return doc


def _write_cursor(path: str, doc: dict) -> None:
    try:
        from tpuflow.utils.paths import atomic_write_json

        atomic_write_json(path, doc)
    except OSError as e:
        raise ValueError(
            f"cannot write {_CURSOR_ENV} file {path!r}: {e} — the cursor "
            "was requested, so losing firing state is an error, not a "
            "degraded mode"
        ) from e


def arm(spec: FaultSpec) -> FaultSpec:
    """Activate a fault spec; returns it (the handle for ``disarm``)."""
    with _LOCK:
        _ARMED.setdefault(spec.site, []).append(spec)
    return spec


def disarm(spec: FaultSpec) -> None:
    with _LOCK:
        specs = _ARMED.get(spec.site, [])
        if spec in specs:
            specs.remove(spec)


def clear_faults() -> None:
    """Disarm everything. The env cache is reset too, so a TPUFLOW_FAULTS
    value re-set after a clear re-arms at the next fault_point — even
    when the value is byte-identical to the one just cleared."""
    global _ENV_CACHE
    with _LOCK:
        _ARMED.clear()
        _FIRED_LOG.clear()
        _ENV_SPECS.clear()
        _ENV_CURSOR.clear()
        _ENV_CACHE = None


def armed() -> list[FaultSpec]:
    with _LOCK:
        return [s for specs in _ARMED.values() for s in specs]


def fired_log() -> list[dict]:
    with _LOCK:
        return list(_FIRED_LOG)


def _sync_env() -> None:
    """(Re)arm the TPUFLOW_FAULTS specs whenever the env value changes —
    so a test's monkeypatch.setenv takes effect without any install call,
    and child processes inherit drills through the environment alone.

    Double-checked: the fast path is one env-string compare under the
    lock; the slow path (parse + cursor-file read — file I/O must not
    run under the registry lock) happens outside it, then re-checks
    before swapping state in.
    """
    global _ENV_CACHE
    value = os.environ.get("TPUFLOW_FAULTS", "")
    with _LOCK:
        if value == _ENV_CACHE:
            return
    # Parse EVERY entry before arming ANY, and update the cache only
    # after a clean parse: a typo'd second entry must not leave the
    # first one armed with the rest silently dropped — and because the
    # cache stays stale on failure, EVERY subsequent fault_point keeps
    # raising until the env is fixed (fail-loud, not fail-once). The
    # re-raise names the env var and the grammar: this error surfaces
    # inside whatever code path hit the fault_point, far from where the
    # operator exported the variable.
    new_specs, errors = parse_fault_entries(value)
    if errors:
        detail = "; ".join(f"{entry!r}: {msg}" for entry, msg in errors)
        raise ValueError(
            f"malformed TPUFLOW_FAULTS entry — {detail} — expected "
            f"{FAULTS_ENV_GRAMMAR}; nothing was armed"
        )
    # Restore persisted firing state — but only when the cursor file was
    # written for THIS env value: a stale cursor from a different storm
    # must not pre-consume the new one.
    cursor_state: dict = {}
    path = _cursor_path()
    if path is not None:
        doc = _read_cursor(path)
        if doc.get("env") == value:
            state = doc.get("state")
            if isinstance(state, dict):
                cursor_state = state
    with _LOCK:
        if value == _ENV_CACHE:
            return  # another thread synced the same value while we parsed
        for old in _ENV_SPECS:
            lst = _ARMED.get(old.site, [])
            lst[:] = [s for s in lst if s is not old]
        _ENV_SPECS.clear()
        _ENV_CURSOR.clear()
        _ENV_CACHE = value
        for i, spec in enumerate(new_specs):
            key = f"{i}:{spec.describe()}"
            spec._cursor_key = key
            restored = cursor_state.get(key)
            if isinstance(restored, dict):
                spec.hits = int(restored.get("hits", 0))
                spec.fired = int(restored.get("fired", 0))
                if spec._rng is not None:
                    # Fast-forward the probability stream past the draws
                    # the previous process consumed — the resumed storm
                    # continues the SAME seeded sequence.
                    for _ in range(spec.hits):
                        spec._rng.random()
            _ENV_CURSOR[key] = {"hits": spec.hits, "fired": spec.fired}
            _ENV_SPECS.append(spec)
            if (spec.nth is not None or spec.at is not None) and spec.fired:
                continue  # a consumed one-shot stays consumed across restarts
            _ARMED.setdefault(spec.site, []).append(spec)


def fault_point(site: str, index: int | None = None) -> None:
    """Declare a named injection site; fires any armed spec that matches.

    ``index`` is the site's reproducibility key (the epoch for training
    sites, the checkpoint step for save sites) — what ``at=`` matches.
    A site with nothing armed costs one env-string compare and one dict
    lookup; hot loops can afford it.
    """
    if site not in SITES:
        raise RuntimeError(
            f"fault_point({site!r}) is not in the SITES catalog — add it "
            "to tpuflow/resilience/faults.py and docs/resilience.md"
        )
    _sync_env()
    to_fire: FaultSpec | None = None
    cursor_doc: dict | None = None
    cursor_file: str | None = None
    with _LOCK:
        specs = _ARMED.get(site)
        if not specs:
            return
        # Precedence: in-process specs (arm() / TrainJobConfig.faults)
        # before TPUFLOW_FAULTS specs — the sort key is env-membership,
        # and the sort is stable, so arming order is preserved within
        # each class. When an in-process spec fires, the break below
        # means the env specs' hit counters do not advance on this call
        # (see the module docstring's precedence contract).
        ordered = sorted(
            specs, key=lambda s: any(s is e for e in _ENV_SPECS)
        )
        for spec in ordered:
            spec.hits += 1
            fire = False
            if spec.nth is not None:
                fire = spec.hits == spec.nth
            elif spec.at is not None:
                fire = index is not None and index == spec.at
            elif spec.p:
                fire = spec._rng.random() < spec.p
            if fire:
                spec.fired += 1
                _FIRED_LOG.append(
                    {"site": site, "spec": spec.describe(), "index": index}
                )
                if spec.nth is not None or spec.at is not None:
                    # one-shot: never double-fires (identity filter — two
                    # field-equal specs must not shadow each other)
                    specs[:] = [s for s in specs if s is not spec]
                to_fire = spec
                break
        # Snapshot the cursor under the lock, write it after release
        # (file I/O never runs under the registry lock).
        cursor_file = _cursor_path()
        if cursor_file is not None and _ENV_SPECS:
            changed = False
            for spec in _ENV_SPECS:
                if spec._cursor_key is None:
                    continue
                state = {"hits": spec.hits, "fired": spec.fired}
                if _ENV_CURSOR.get(spec._cursor_key) != state:
                    _ENV_CURSOR[spec._cursor_key] = state
                    changed = True
            if changed:
                cursor_doc = {
                    "version": 1,
                    "env": _ENV_CACHE,
                    "state": {k: dict(v) for k, v in _ENV_CURSOR.items()},
                }
    if cursor_doc is not None and cursor_file is not None:
        # Persist BEFORE the firing tail: a mode=exit spec records its
        # own firing, so the restarted process sees it consumed.
        _write_cursor(cursor_file, cursor_doc)
    if to_fire is None:
        return
    # Every firing is observable: a labeled counter in the process-wide
    # registry (scraped via /metrics?format=prometheus) plus a forensics
    # ring event, so a drill's blast radius shows up in the same trail
    # as the spans it interrupted. The label set is exactly the SITES
    # catalog — tests/test_obs.py asserts the parity.
    try:
        from tpuflow.obs import default_registry, record_event

        default_registry().counter(
            "faults_injected_total",
            "armed fault-injection firings by site",
        ).inc(site=site)
        record_event(
            "fault_injected", site=site, spec=to_fire.describe(), index=index
        )
    except Exception:
        pass  # observability never blocks the drill itself
    if to_fire.on_fire is not None:
        to_fire.on_fire()
    message = (
        f"injected fault at {site!r} (spec {to_fire.describe()}, "
        f"index={index})"
    )
    if to_fire.mode == "exit":
        os._exit(to_fire.code)
    if to_fire.mode == "delay":
        # The straggler/slow-link mode: the site survives, just late.
        time.sleep(to_fire.delay)
        return
    if to_fire.mode == "hang":
        while True:  # noqa: TPF007 (a DELIBERATE wedge: only a kill gets out)
            time.sleep(3600)
    if to_fire.transient:
        raise TransientFault(message, site)
    raise FaultInjected(message, site)
