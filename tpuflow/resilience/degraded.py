"""Graceful degradation for serving: the Gilbert baseline as fallback.

The paper's own accuracy baseline — the closed-form Gilbert choke
correlation (``core/gilbert.py``, reference Readme.md:7-8) — does double
duty here as the degraded-mode model: when a trained artifact's
CHECKPOINT is missing or corrupt, ``PredictService`` answers from
physics instead of returning 500s, flagged ``degraded: true`` so the
caller knows the answer's provenance. A baseline the learned models are
judged against is by construction an acceptable worst-case stand-in for
them.

The gate is the schema sidecar (``{storage}/meta/{name}.json``): if it
is readable, the artifact demonstrably existed and only its weights are
gone — degrade. If even the sidecar is unreadable, the "artifact" most
likely never existed (a typo'd model name must NOT be silently answered
by physics) — ``try_fallback`` returns None and the original load error
propagates. The fallback itself needs only the three physical columns
(pressure, choke, glr), which ride every schema this system trains on.
"""

from __future__ import annotations

import json

import numpy as np


def try_fallback(storage_path: str, name: str, reason: str):
    """Build a degraded predictor for a partially-lost artifact, or None.

    None when the schema sidecar is unreadable too — then nothing proves
    the artifact ever existed, and degrading would mask a caller error.
    Same verdict when the sidecar parses but is structurally INVALID
    (``tpuflow.analysis.artifact``): degradation exists for lost
    checkpoints behind a healthy description, and answering a corrupt
    description with physics would bury the named-field diagnostic the
    load failure just raised.
    """
    try:
        from tpuflow.api.predict_api import _meta_path
        from tpuflow.utils.paths import open_file

        with open_file(
            _meta_path(storage_path, name), "r", encoding="utf-8"
        ) as f:
            meta = json.load(f)
    except Exception:
        return None
    from tpuflow.analysis.artifact import check_artifact_meta

    if check_artifact_meta(meta):
        return None  # broken sidecar: fail loudly, do not mask it
    return GilbertFallbackPredictor(name, meta, reason)


class GilbertFallbackPredictor:
    """Duck-types the ``Predictor`` serving surface (``predict_columns`` /
    ``predict_csv``) over the closed-form baseline. ``degraded`` marks it
    for the service layer; per-row physics predictions stand in for the
    learned model's (windowed models' per-window shape is NOT preserved —
    a degraded answer is a different, simpler model, and says so)."""

    degraded = True
    _NEEDED = ("pressure", "choke", "glr")

    def __init__(self, name: str, meta: dict, reason: str):
        self.model_name = name
        self.reason = reason  # why the real artifact failed to load
        self._meta = meta

    def predict_columns(self, columns: dict) -> np.ndarray:
        from tpuflow.core.gilbert import gilbert_flow

        missing = [n for n in self._NEEDED if n not in columns]
        if missing:
            raise ValueError(
                f"degraded (Gilbert-fallback) serving needs raw "
                f"{list(self._NEEDED)} columns; missing {missing}"
            )
        return np.asarray(
            gilbert_flow(
                np.asarray(columns["pressure"], np.float32),
                np.asarray(columns["choke"], np.float32),
                np.asarray(columns["glr"], np.float32),
            ),
            dtype=np.float32,
        )

    def _schema(self, with_target: bool):
        from tpuflow.data.schema import ColumnSpec, Schema

        p = self._meta["preprocessor"]
        if self._meta["kind"] == "tabular":
            cols = list(zip(p["names"], p["kinds"]))
        else:
            cols = [(c["name"], c["kind"]) for c in p["schema_columns"]]
        target = p["target"]
        if not with_target:
            cols = [(n, k) for n, k in cols if n != target]
            target = None
        return Schema(
            columns=tuple(ColumnSpec(n, k) for n, k in cols), target=target
        )

    def predict_csv(self, path: str) -> np.ndarray:
        from tpuflow.data.csv_io import read_csv

        with open(path, "r", encoding="utf-8") as f:
            first = f.readline()
        nfields = len(first.rstrip("\n").rstrip("\r").split(","))
        full = self._schema(with_target=True)
        serving = self._schema(with_target=False)
        if nfields == len(full.columns):
            schema = full
        elif nfields == len(serving.columns):
            schema = serving
        else:
            raise ValueError(
                f"{path}: first line has {nfields} fields; expected "
                f"{len(full.columns)} or {len(serving.columns)}"
            )
        return self.predict_columns(read_csv(path, schema))
