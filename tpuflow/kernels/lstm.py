"""Fused LSTM recurrence as Pallas TPU kernels.

The flagship hot loop (SURVEY.md §3.4; BASELINE.json north star is LSTM
samples/sec/chip). The surrounding model (``tpuflow.models.lstm``) already
hoists the input projection ``x @ W_x`` out of the recurrence as one large
MXU matmul; what remains per step is the recurrent matmul ``h @ W_h`` plus
the gate elementwise math. This module fuses that whole remainder into a
single Pallas kernel:

- the time loop runs *inside* the kernel (``fori_loop``), carrying ``h``
  and ``c`` in VMEM scratch — no per-step HBM round-trip for the carry;
- the recurrent matmul rides the MXU with float32 accumulation; the gate
  sigmoid/tanh elementwise work happens in-register on the VPU;
- the batch dimension is tiled over the Pallas grid, so arbitrary batch
  sizes stream through fixed VMEM blocks;
- backward is a second Pallas kernel running the standard reverse-time
  LSTM recurrence (recomputing gate activations from residuals rather
  than storing them — rematerialisation trades FLOPs for HBM, the right
  trade on TPU), wired up via ``jax.custom_vjp``.

On non-TPU backends the kernels run in Pallas interpret mode, so CI on the
8-virtual-CPU-device mesh exercises the identical code path (SURVEY.md §4).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _batch_block(B: int, T: int, H: int, itemsize: int) -> int:
    """Largest batch tile keeping the kernel's VMEM footprint under ~8 MB.

    On real TPU, raises when even the smallest tile (8) blows the budget —
    whole ``[T, Bb, 4H]`` blocks are VMEM-resident, so very long T simply
    does not fit this kernel; the XLA-scan backend handles those shapes.
    Interpret mode (non-TPU) has no VMEM, so the cap is advisory there.
    """
    for bb in (512, 256, 128, 64, 32, 16, 8):
        # fwd blocks: xw[T,bb,4H] + hs/cs[T,bb,H]*2 = 6 H-units of T-sized
        # blocks; the *2 factor covers the bwd kernel, whose enumerated
        # residency (xw + dxw = 8H, hs/cs/dhs = 3H → 11 H-units) sits just
        # under the 12 H-units this budget allows, so the bound is mildly
        # conservative for bwd, never optimistic.
        footprint = T * bb * 4 * H * itemsize * 2 + 2 * T * bb * H * itemsize * 2
        if footprint <= 8 * 1024 * 1024:
            return min(bb, max(B, 8))
    if _interpret():
        return 8
    raise ValueError(
        f"lstm_scan: smallest batch tile (8) exceeds the ~8MB VMEM budget "
        f"at T={T}, H={H}, itemsize={itemsize}; use the XLA scan backend "
        f"(backend='xla') or shorter sequence chunks for these shapes"
    )


def _split_gates(z: jnp.ndarray, H: int):
    return z[:, :H], z[:, H : 2 * H], z[:, 2 * H : 3 * H], z[:, 3 * H :]


def _fwd_kernel(xw_ref, wh_ref, b_ref, hs_ref, cs_ref, h_scr, c_scr):
    """One batch tile: scan T steps, write hidden/cell sequences."""
    T = xw_ref.shape[0]
    H = wh_ref.shape[0]
    dt = xw_ref.dtype
    h_scr[:] = jnp.zeros_like(h_scr)
    c_scr[:] = jnp.zeros_like(c_scr)

    def step(t, _):
        xw_t = xw_ref[pl.ds(t, 1)][0]  # [Bb, 4H]
        z = (
            xw_t.astype(jnp.float32)
            + jnp.dot(h_scr[:], wh_ref[:], preferred_element_type=jnp.float32)
            + b_ref[0].astype(jnp.float32)
        )
        i, f, g, o = _split_gates(z, H)
        c = jax.nn.sigmoid(f) * c_scr[:].astype(jnp.float32) + jax.nn.sigmoid(
            i
        ) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        h_scr[:] = h.astype(dt)
        c_scr[:] = c.astype(jnp.float32)
        hs_ref[pl.ds(t, 1)] = h.astype(dt)[None]
        cs_ref[pl.ds(t, 1)] = c.astype(dt)[None]
        return 0

    jax.lax.fori_loop(0, T, step, 0)


def _bwd_kernel(
    xw_ref, wh_ref, b_ref, hs_ref, cs_ref, dhs_ref,
    dxw_ref, dwh_ref, db_ref,
    dh_scr, dc_scr,
):
    """Reverse-time recurrence for one batch tile.

    Gate activations are recomputed from (xw, h_prev) rather than stored —
    the rematerialisation trade SURVEY.md's HBM-bandwidth note calls for.
    ``dwh``/``db`` accumulate per-tile partials (summed by the wrapper).
    """
    T = xw_ref.shape[0]
    H = wh_ref.shape[0]
    dt = xw_ref.dtype
    dh_scr[:] = jnp.zeros_like(dh_scr)
    dc_scr[:] = jnp.zeros_like(dc_scr)
    dwh_ref[0] = jnp.zeros(dwh_ref.shape[1:], dwh_ref.dtype)
    db_ref[0] = jnp.zeros(db_ref.shape[1:], db_ref.dtype)
    wh32 = wh_ref[:].astype(jnp.float32)

    def step(k, _):
        t = T - 1 - k
        prev = jnp.maximum(t - 1, 0)
        has_prev = (t > 0).astype(jnp.float32)
        h_prev = hs_ref[pl.ds(prev, 1)][0].astype(jnp.float32) * has_prev
        c_prev = cs_ref[pl.ds(prev, 1)][0].astype(jnp.float32) * has_prev

        # Recompute this step's pre-activations and gates.
        z = (
            xw_ref[pl.ds(t, 1)][0].astype(jnp.float32)
            + jnp.dot(
                h_prev.astype(dt), wh_ref[:], preferred_element_type=jnp.float32
            )
            + b_ref[0].astype(jnp.float32)
        )
        zi, zf, zg, zo = _split_gates(z, H)
        i, f, o = jax.nn.sigmoid(zi), jax.nn.sigmoid(zf), jax.nn.sigmoid(zo)
        g = jnp.tanh(zg)
        c = cs_ref[pl.ds(t, 1)][0].astype(jnp.float32)
        tanh_c = jnp.tanh(c)

        dh = dhs_ref[pl.ds(t, 1)][0].astype(jnp.float32) + dh_scr[:]
        do = dh * tanh_c
        dc = dc_scr[:] + dh * o * (1.0 - tanh_c * tanh_c)
        di, df, dg = dc * g, dc * c_prev, dc * i

        dz = jnp.concatenate(
            [
                di * i * (1.0 - i),
                df * f * (1.0 - f),
                dg * (1.0 - g * g),
                do * o * (1.0 - o),
            ],
            axis=-1,
        )  # [Bb, 4H]

        dxw_ref[pl.ds(t, 1)] = dz.astype(dt)[None]
        dwh_ref[0] += jax.lax.dot_general(
            h_prev, dz, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        db_ref[0] += jnp.sum(dz, axis=0, keepdims=True)
        dh_scr[:] = jax.lax.dot_general(
            dz, wh32, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dc_scr[:] = dc * f
        return 0

    jax.lax.fori_loop(0, T, step, 0)


def _pad_batch(a: jnp.ndarray, Bb: int):
    B = a.shape[1]
    pad = (-B) % Bb
    if pad:
        a = jnp.pad(a, ((0, 0), (0, pad), (0, 0)))
    return a, B


def _fwd(xw: jnp.ndarray, wh: jnp.ndarray, b: jnp.ndarray):
    T, B, H4 = xw.shape
    H = H4 // 4
    Bb = _batch_block(B, T, H, xw.dtype.itemsize)
    xw_p, B0 = _pad_batch(xw, Bb)
    Bp = xw_p.shape[1]
    grid = Bp // Bb
    b2 = b.reshape(1, H4)

    hs, cs = pl.pallas_call(
        _fwd_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((T, Bb, H4), lambda i: (0, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((H, H4), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, H4), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((T, Bb, H), lambda i: (0, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((T, Bb, H), lambda i: (0, i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, Bp, H), xw.dtype),
            jax.ShapeDtypeStruct((T, Bp, H), xw.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((Bb, H), xw.dtype),
            pltpu.VMEM((Bb, H), jnp.float32),
        ],
        interpret=_interpret(),
    )(xw_p, wh, b2)
    return hs[:, :B0], cs[:, :B0]


def _bwd(xw, wh, b, hs, cs, dhs):
    T, B, H4 = xw.shape
    H = H4 // 4
    Bb = _batch_block(B, T, H, xw.dtype.itemsize)
    xw_p, B0 = _pad_batch(xw, Bb)
    hs_p, _ = _pad_batch(hs, Bb)
    cs_p, _ = _pad_batch(cs, Bb)
    dhs_p, _ = _pad_batch(dhs, Bb)
    Bp = xw_p.shape[1]
    grid = Bp // Bb
    b2 = b.reshape(1, H4)

    dxw, dwh_parts, db_parts = pl.pallas_call(
        _bwd_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((T, Bb, H4), lambda i: (0, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((H, H4), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, H4), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((T, Bb, H), lambda i: (0, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((T, Bb, H), lambda i: (0, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((T, Bb, H), lambda i: (0, i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((T, Bb, H4), lambda i: (0, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, H, H4), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, H4), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, Bp, H4), xw.dtype),
            jax.ShapeDtypeStruct((grid, H, H4), jnp.float32),
            jax.ShapeDtypeStruct((grid, 1, H4), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((Bb, H), jnp.float32),
            pltpu.VMEM((Bb, H), jnp.float32),
        ],
        interpret=_interpret(),
    )(xw_p, wh, b2, hs_p, cs_p, dhs_p)

    dwh = jnp.sum(dwh_parts, axis=0).astype(wh.dtype)
    db = jnp.sum(db_parts, axis=(0, 1)).astype(b.dtype)
    return dxw[:, :B0], dwh, db


@jax.custom_vjp
def lstm_scan(xw: jnp.ndarray, wh: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Fused LSTM recurrence: ``xw [T,B,4H] -> hs [T,B,H]`` (time-major).

    ``xw`` is the pre-computed input projection for all steps (gate order
    i, f, g, o — matching ``tpuflow.models.lstm``); ``wh [H,4H]`` the
    recurrent weights; ``b [4H]`` the bias. Zero initial state, matching
    the XLA-scan reference implementation.
    """
    hs, _ = _fwd(xw, wh, b)
    return hs


def _lstm_scan_fwd(xw, wh, b):
    hs, cs = _fwd(xw, wh, b)
    return hs, (xw, wh, b, hs, cs)


def _lstm_scan_bwd(res, dhs):
    xw, wh, b, hs, cs = res
    return _bwd(xw, wh, b, hs, cs, dhs.astype(xw.dtype))


lstm_scan.defvjp(_lstm_scan_fwd, _lstm_scan_bwd)
