"""Pallas TPU kernels for the hot ops (SURVEY.md §7 layer 7).

The reference delegates all native compute to external runtimes (Spark/JVM
and the Theano backend — SURVEY.md §2 "Native components: none"); the
TPU-native equivalent of that delegated-native layer is XLA plus, where a
fused kernel pays off, Pallas (Mosaic) kernels:

- ``lstm_scan``      — fused LSTM recurrence (the north-star hot loop,
  SURVEY.md §3.4): per-step recurrent matmul on the MXU with the gate
  elementwise math fused in VMEM, forward AND backward as Pallas kernels
  under a ``jax.custom_vjp``.
- ``mae_clip_pallas`` — fused clipped-MAE loss (reference cnn.py:29-32
  semantics) as a single tiled reduction kernel.
- ``flash_attention`` — fused causal attention for the long-context
  family: online-softmax streaming over K/V blocks, the [T, T] score
  matrix never materialized, fwd + dQ + dK/dV kernels under a
  ``jax.custom_vjp``.

All kernels run compiled on TPU and fall back to Pallas interpret mode on
CPU so the same code paths are unit-testable on the 8-virtual-device CI
mesh (SURVEY.md §4).
"""

from tpuflow.kernels.attention import flash_attention
from tpuflow.kernels.lstm import lstm_scan
from tpuflow.kernels.losses import mae_clip_pallas

__all__ = ["flash_attention", "lstm_scan", "mae_clip_pallas"]
