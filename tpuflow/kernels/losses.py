"""Fused clipped-MAE loss as a Pallas TPU reduction kernel.

Same semantics as the reference's Theano loss — ``mean(clip(|y_true -
y_pred|, 0, 6))`` (reference cnn.py:29-32) and as ``tpuflow.core.losses
.mae_clip`` (the golden-value-tested jnp version). The forward pass is one
tiled Pallas kernel: abs-diff, clip, and partial-sum per tile in VMEM, so
the whole loss is a single HBM read of each operand. The backward pass is
the closed-form subgradient in plain jnp (memory-bound elementwise — XLA
already fuses it optimally; a kernel would buy nothing).

Runs compiled on TPU, interpret-mode elsewhere (CI per SURVEY.md §4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpuflow.core.losses import CLIP_VALUE

_LANES = 128
_ROWS_PER_TILE = 512


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _sum_kernel(clip_ref, yt_ref, yp_ref, out_ref):
    # TPU grid steps run sequentially, so one (1,1) SMEM cell accumulates
    # the partial sums across the whole grid.
    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[0, 0] = 0.0

    diff = jnp.abs(
        yt_ref[:].astype(jnp.float32) - yp_ref[:].astype(jnp.float32)
    )
    out_ref[0, 0] += jnp.sum(jnp.clip(diff, 0.0, clip_ref[0]))


def _clipped_abs_sum(y_true: jnp.ndarray, y_pred: jnp.ndarray, clip: float):
    """Sum of clip(|y_true - y_pred|, 0, clip) over all elements."""
    yt = y_true.reshape(-1)
    yp = y_pred.reshape(-1)
    n = yt.shape[0]
    # Pad both operands with zeros: |0 - 0| = 0 contributes nothing to the
    # SUM, so no in-kernel masking is needed.
    tile = _ROWS_PER_TILE * _LANES
    pad = (-n) % tile
    if pad:
        yt = jnp.pad(yt, (0, pad))
        yp = jnp.pad(yp, (0, pad))
    rows = yt.shape[0] // _LANES
    yt = yt.reshape(rows, _LANES)
    yp = yp.reshape(rows, _LANES)
    grid = rows // _ROWS_PER_TILE

    partials = pl.pallas_call(
        _sum_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (_ROWS_PER_TILE, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (_ROWS_PER_TILE, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        interpret=_interpret(),
    )(jnp.full((1,), clip, jnp.float32), yt, yp)
    return partials[0, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def mae_clip_pallas(
    y_true: jnp.ndarray, y_pred: jnp.ndarray, clip_value: float = CLIP_VALUE
) -> jnp.ndarray:
    """Fused ``mean(clip(|y_true - y_pred|, 0, clip_value))``."""
    n = y_true.size
    return _clipped_abs_sum(y_true, y_pred, clip_value) / n


def _fwd(y_true, y_pred, clip_value):
    n = y_true.size
    loss = _clipped_abs_sum(y_true, y_pred, clip_value) / n
    return loss, (y_true, y_pred)


def _bwd(clip_value, res, g):
    y_true, y_pred = res
    diff = y_true.astype(jnp.float32) - y_pred.astype(jnp.float32)
    # Subgradient of mean(clip(|d|, 0, c)) — zero where saturated.
    inner = jnp.sign(diff) * (jnp.abs(diff) < clip_value)
    scale = g / y_true.size
    dyt = (scale * inner).astype(y_true.dtype)
    return dyt, (-dyt).astype(y_pred.dtype)


mae_clip_pallas.defvjp(_fwd, _bwd)
