"""Fused causal attention (flash attention) as Pallas TPU kernels.

The attention family's on-chip hot op, completing the kernel trio (fused
LSTM recurrence, fused clipped-MAE). The XLA path
(``tpuflow.parallel.ring_attention.full_attention``) materializes the
[T, T] score matrix in HBM; this kernel never does:

- the query axis tiles over the Pallas grid; for each query block the
  kernel streams key/value blocks through the MXU, maintaining the
  online-softmax running max/normalizer/accumulator in f32 — the
  flash-attention recurrence, scores living only in VMEM/registers;
- causal masking is applied per block from global positions, and key
  blocks entirely above the diagonal are never visited (the work is
  O(T^2/2), not O(T^2));
- backward recomputes the probabilities blockwise from the saved
  logsumexp (rematerialisation over HBM residency, as in the LSTM
  kernel): one kernel produces dQ, a second produces dK/dV, wired via
  ``jax.custom_vjp``.

Whole K/V for one batch-head are VMEM-resident per grid cell, which caps
this kernel at T around 10-20k for typical head dims — beyond that the
time axis should shard across chips instead (``ring_attention`` /
``examples/long_context_cp.py``). The two COMPOSE: the ring-round
kernels at the bottom of this file run each CP ring round's block math
blockwise in VMEM (``ring_attention(..., impl="flash")``) — ring
outside, flash inside. The ring's custom VJP supplies differentiation,
so the round kernels carry none of their own.

On non-TPU backends the kernels run in Pallas interpret mode, so CI on
the 8-virtual-CPU-device mesh exercises the identical code path
(SURVEY.md §4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30  # finite mask value: keeps exp() NaN-free on masked rows

# Per-row stats (lse, delta, ring m/l) cannot travel as bare [BH, T]
# arrays with (1, Bt) blocks: Mosaic requires the last two block dims to
# be divisible by (8, 128) or equal to the array dims, and a row block's
# sublane dim of 1 violates that the moment the kernel compiles on a real
# chip (interpret mode never enforces it). So row stats travel as
# [BH, T, _LANES] with the value broadcast across the trailing lanes —
# the official TPU flash kernel's layout trick, at 8 lanes instead of 128
# so the HBM cost stays negligible next to q/k/v (the 8-wide last dim is
# legal because it EQUALS the array's last dim).
_LANES = 8


def _rows_to_lanes(x: jnp.ndarray) -> jnp.ndarray:
    """[..., T] row stats -> [..., T, _LANES] lane-broadcast layout."""
    return jnp.broadcast_to(x[..., None], (*x.shape, _LANES))


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block(T: int) -> int:
    """Query/key block length, or the (8-aligned) whole sequence when it
    is shorter. Default 256: the round-5 on-chip timing showed the
    128-row kernel neither HBM- nor MXU-bound (2.7% HBM util, 1.7% MFU)
    — serialization-bound on too-small inner matmuls — so bigger tiles
    put more arithmetic on the MXU per online-softmax iteration.
    TPUFLOW_FLASH_BLOCK overrides for on-chip sweeps."""
    import os

    blk = max(int(os.environ.get("TPUFLOW_FLASH_BLOCK", 256)), 8)
    blk = -(-blk // 8) * 8  # Mosaic sublane rule: blocks must be 8-aligned
    if T >= blk:
        return blk
    return max(8, -(-T // 8) * 8)


def _pad_time(x: jnp.ndarray, Bt: int) -> jnp.ndarray:
    pad = (-x.shape[1]) % Bt
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _online_block_update(q, k_blk, v_blk, scale, m, l, acc, allowed):
    """The flash forward recurrence for ONE (q-tile, kv-block) pair —
    the single source of the online-softmax math, shared by the
    standalone kernel and the CP ring-round kernel.

    ``q``/``k_blk``/``v_blk`` stay in their NATIVE dtype so bf16 inputs
    ride the MXU's native mode with f32 accumulation (an all-f32 operand
    matmul costs multiple MXU passes — the round-5 on-chip timing showed
    the f32-everything kernel serialization-bound). ``scale`` applies to
    the f32 scores, which keeps the softmax math and the VJP exact
    regardless of operand dtype. Stats/accumulator are f32."""
    s = scale * jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    s = jnp.where(allowed, s, _NEG)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[:, None]) * allowed.astype(jnp.float32)
    corr = jnp.exp(m - m_new)
    l = l * corr + jnp.sum(p, axis=-1)
    acc = acc * corr[:, None] + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l, acc


def _p_block(q, k_blk, scale, lse, allowed):
    """Backward-pass probabilities exp(s - lse) for one block pair —
    already FINAL softmax values (not running partials), so every
    block's contribution is correctly normalized independently."""
    s = scale * jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    p = jnp.exp(jnp.where(allowed, s, _NEG) - lse[:, None])
    return p * allowed.astype(jnp.float32)


def _dq_block(q, k_blk, v_blk, do, scale, lse, delta, allowed):
    """One block pair's contribution to dQ (the final * scale is applied
    by the caller, once, outside the accumulation loop)."""
    p = _p_block(q, k_blk, scale, lse, allowed)
    dp = jax.lax.dot_general(
        do, v_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta[:, None])
    return jax.lax.dot_general(
        ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _dkv_block(q, k_blk, v_blk, do, scale, lse, delta, allowed):
    """One block pair's contribution to (dK, dV). dK carries the score
    scale (dS/dK = scale * Q)."""
    p = _p_block(q, k_blk, scale, lse, allowed)
    dv = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        do, v_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta[:, None])
    dk = scale * jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dk, dv


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, Bk):
    """One (batch-head, query-block) cell: stream causal K/V blocks."""
    Bq, D = q_ref.shape[1], q_ref.shape[2]
    T = k_ref.shape[1]
    iq = pl.program_id(1)
    q = q_ref[0]  # [Bq, D], native dtype (scale applies to the scores)
    q_pos = iq * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)

    m0 = jnp.full((Bq,), _NEG, jnp.float32)
    l0 = jnp.zeros((Bq,), jnp.float32)
    acc0 = jnp.zeros((Bq, D), jnp.float32)
    # Causal: key blocks past this query block's last row never attend.
    n_kb = jnp.minimum((iq + 1) * Bq + Bk - 1, T) // Bk

    def body(kb, carry):
        k_blk = k_ref[0, pl.ds(kb * Bk, Bk)]  # [Bk, D]
        v_blk = v_ref[0, pl.ds(kb * Bk, Bk)]
        k_pos = kb * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
        return _online_block_update(
            q, k_blk, v_blk, scale, *carry, k_pos <= q_pos
        )

    m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = _rows_to_lanes(m + jnp.log(l_safe))


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, Bk
):
    """dQ for one (batch-head, query-block): dq = scale * sum_k ds @ K."""
    Bq, D = q_ref.shape[1], q_ref.shape[2]
    T = k_ref.shape[1]
    iq = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][:, 0]
    delta = delta_ref[0][:, 0]
    q_pos = iq * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
    n_kb = jnp.minimum((iq + 1) * Bq + Bk - 1, T) // Bk

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * Bk, Bk)]
        v_blk = v_ref[0, pl.ds(kb * Bk, Bk)]
        k_pos = kb * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
        return dq + _dq_block(
            q, k_blk, v_blk, do, scale, lse, delta, k_pos <= q_pos
        )

    dq = jax.lax.fori_loop(0, n_kb, body, jnp.zeros((Bq, D), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, scale, Bq,
):
    """dK/dV for one (batch-head, key-block): loop causal query blocks."""
    Bk, D = k_ref.shape[1], k_ref.shape[2]
    T = q_ref.shape[1]
    ik = pl.program_id(1)
    k_blk = k_ref[0]
    v_blk = v_ref[0]
    k_pos = ik * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
    nq = T // Bq
    first_qb = (ik * Bk) // Bq  # earlier query blocks are fully masked

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * Bq, Bq)]
        do = do_ref[0, pl.ds(qb * Bq, Bq)]
        lse = lse_ref[0, pl.ds(qb * Bq, Bq), 0]
        delta = delta_ref[0, pl.ds(qb * Bq, Bq), 0]
        q_pos = qb * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
        dk_p, dv_p = _dkv_block(
            q, k_blk, v_blk, do, scale, lse, delta, k_pos <= q_pos
        )
        return dk + dk_p, dv + dv_p

    dk, dv = jax.lax.fori_loop(
        first_qb,
        nq,
        body,
        (jnp.zeros((Bk, D), jnp.float32), jnp.zeros((Bk, D), jnp.float32)),
    )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _specs_btd(Bt, D, whole_T):
    """(1, Bt, D) blocks over (batch-head, time-block) vs whole-sequence."""

    def blocked(b, i):
        return (b, i, 0)

    def whole(b, i):
        return (b, 0, 0)

    return (
        pl.BlockSpec((1, Bt, D), blocked, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, whole_T, D), whole, memory_space=pltpu.VMEM),
    )


def _row_specs(Bt, whole_T):
    """Lane-broadcast row-stat blocks: per-q-tile vs whole-sequence."""
    return (
        pl.BlockSpec(
            (1, Bt, _LANES), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM
        ),
        pl.BlockSpec(
            (1, whole_T, _LANES), lambda b, i: (b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
    )


def _fwd(q, k, v, scale):
    """Returns (out, lse), BOTH truncated to the caller's T — padding is
    private to each pallas wrapper, never part of the residuals."""
    BH, T0, D = q.shape
    Bt = _block(T0)
    q_p = _pad_time(q, Bt)
    k_p = _pad_time(k, Bt)
    v_p = _pad_time(v, Bt)
    T = q_p.shape[1]
    grid = (BH, T // Bt)
    blk, whole = _specs_btd(Bt, D, T)

    row_blk, _ = _row_specs(Bt, T)

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, Bk=Bt),
        grid=grid,
        in_specs=[blk, whole, whole],
        out_specs=[blk, row_blk],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(q_p, k_p, v_p)
    return o[:, :T0], lse[:, :T0, 0]


def _bwd(q, k, v, o, lse, do, scale):
    BH, T0, D = q.shape
    Bt = _block(T0)
    q_p = _pad_time(q, Bt)
    k_p = _pad_time(k, Bt)
    v_p = _pad_time(v, Bt)
    do_p = _pad_time(do, Bt)
    T = q_p.shape[1]
    # delta_i = sum_d do_i * o_i — tiny elementwise pass, jnp is the right
    # tool; padded rows contribute zeros.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )
    pad = T - T0
    if pad:
        delta = jnp.pad(delta, ((0, 0), (0, pad)))
        # lse arrives at T0 (_fwd contract). Pad with a huge POSITIVE
        # value so padded rows get p = exp(s - huge) = 0 exactly — a 0
        # pad could overflow exp(s) to inf and poison ds with inf * 0.
        lse = jnp.pad(lse, ((0, 0), (0, pad)), constant_values=-_NEG)
    grid = (BH, T // Bt)
    blk, whole = _specs_btd(Bt, D, T)
    row_blk, row_whole = _row_specs(Bt, T)
    lse_l = _rows_to_lanes(lse)
    delta_l = _rows_to_lanes(delta)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, Bk=Bt),
        grid=grid,
        in_specs=[blk, whole, whole, blk, row_blk, row_blk],
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=_interpret(),
    )(q_p, k_p, v_p, do_p, lse_l, delta_l)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, Bq=Bt),
        grid=grid,
        in_specs=[whole, blk, blk, whole, row_whole, row_whole],
        out_specs=[blk, blk],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        ],
        interpret=_interpret(),
    )(q_p, k_p, v_p, do_p, lse_l, delta_l)
    return dq[:, :T0], dk[:, :T0], dv[:, :T0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, scale: float | None = None
) -> jnp.ndarray:
    """Fused causal attention: ``q, k, v [BH, T, D] -> [BH, T, D]``.

    Heads folded into the leading dim by the caller (the
    ``tpuflow.models.attention`` convention). Matches
    ``full_attention(..., causal=True)`` exactly (parity-tested, fwd and
    grads) without ever materializing the [T, T] score matrix.
    """
    out, _ = _fwd(q, k, v, scale if scale is not None else q.shape[-1] ** -0.5)
    return out


def _flash_fwd(q, k, v, scale):
    s = scale if scale is not None else q.shape[-1] ** -0.5
    out, lse = _fwd(q, k, v, s)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, res, do):
    q, k, v, out, lse = res
    s = scale if scale is not None else q.shape[-1] ** -0.5
    return _bwd(q, k, v, out, lse, do.astype(q.dtype), s)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# --------------------------------------------------------------------------
# Ring-round kernels: flash blockwise math INSIDE the CP ring
# (tpuflow.parallel.ring_attention impl="flash"). Each ring round attends
# the local Q chunk to ONE visiting KV block; global positions arrive as
# SMEM scalars because the block's origin is a traced device index. The
# ring's custom VJP supplies differentiation, so these kernels need none.
# --------------------------------------------------------------------------


def _round_fwd_kernel(
    off_ref, q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
    m_out, l_out, acc_out, *, scale, Bk, real_len,
):
    """Online-softmax update of one q-tile against the visiting block."""
    Bq, D = q_ref.shape[1], q_ref.shape[2]
    T = k_ref.shape[1]
    iq = pl.program_id(1)
    q_off, k_off = off_ref[0, 0], off_ref[0, 1]
    q = q_ref[0]
    q_pos = q_off + iq * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
    m = m_ref[0][:, 0].astype(jnp.float32)
    l = l_ref[0][:, 0].astype(jnp.float32)
    acc = acc_ref[0].astype(jnp.float32)

    def body(kb, carry):
        k_blk = k_ref[0, pl.ds(kb * Bk, Bk)]
        v_blk = v_ref[0, pl.ds(kb * Bk, Bk)]
        k_idx = kb * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
        # Padded K rows sit at global positions that ALIAS the next
        # block's territory — causality alone would admit them; mask by
        # the block's real length too.
        allowed = ((k_off + k_idx) <= q_pos) & (k_idx < real_len)
        return _online_block_update(q, k_blk, v_blk, scale, *carry, allowed)

    # Causal early-exit: sub-blocks wholly past this q-tile's last row
    # are never visited (~half of all device-rounds carry a fully-future
    # block and do zero loop iterations).
    n_kb = jnp.clip(
        (q_off + (iq + 1) * Bq - 1 - k_off) // Bk + 1, 0, T // Bk
    )
    m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m, l, acc))
    m_out[0] = _rows_to_lanes(m)
    l_out[0] = _rows_to_lanes(l)
    acc_out[0] = acc.astype(acc_out.dtype)


def ring_round_fwd(q, k_blk, v_blk, m, l, acc, q_off, k_off, scale):
    """One causal ring round: update (m, l, acc) with the visiting block.

    ``q [B, Tl, D]`` local queries; ``k_blk, v_blk [B, Tl, D]`` the block
    currently held; ``m, l [B, Tl]`` / ``acc [B, Tl, D]`` f32 running
    stats; ``q_off, k_off`` GLOBAL start positions (traced scalars).
    """
    B, Tl, D = q.shape
    Bt = _block(Tl)
    q_p = _pad_time(q, Bt)
    k_p = _pad_time(k_blk, Bt)
    v_p = _pad_time(v_blk, Bt)
    T = q_p.shape[1]
    pad = T - Tl
    if pad:
        # Padded q rows must stay neutral; padded k rows are masked out
        # by causality only if their global position exceeds every real
        # q position — guaranteed by placing them at k_off + [Tl, T).
        m = jnp.pad(m, ((0, 0), (0, pad)), constant_values=_NEG)
        l = jnp.pad(l, ((0, 0), (0, pad)))
        acc = jnp.pad(acc, ((0, 0), (0, pad), (0, 0)))
    off = jnp.stack([q_off, k_off]).astype(jnp.int32).reshape(1, 2)
    grid = (B, T // Bt)
    blk, whole = _specs_btd(Bt, D, T)
    row_blk, _ = _row_specs(Bt, T)
    smem = pl.BlockSpec((1, 2), lambda b, i: (0, 0), memory_space=pltpu.SMEM)

    m2, l2, acc2 = pl.pallas_call(
        functools.partial(_round_fwd_kernel, scale=scale, Bk=Bt, real_len=Tl),
        grid=grid,
        in_specs=[smem, blk, whole, whole, row_blk, row_blk, blk],
        out_specs=[row_blk, row_blk, blk],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((B, T, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((B, T, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(off, q_p, k_p, v_p, _rows_to_lanes(m), _rows_to_lanes(l), acc)
    return m2[:, :Tl, 0], l2[:, :Tl, 0], acc2[:, :Tl]


def _round_bwd_kernel(
    off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dk_ref, dv_ref, *, scale, Bt, real_len,
):
    """One (b, tile) cell: this round's dq for the q-tile AND the tile's
    dk/dv rows. dq tiles over q; dk/dv tile over the SAME index on the
    k side (both sequences have identical padded length)."""
    T = q_ref.shape[1]
    D = q_ref.shape[2]
    i = pl.program_id(1)
    q_off, k_off = off_ref[0, 0], off_ref[0, 1]

    # --- dq for q-tile i: loop k sub-blocks of the visiting block ---
    q = q_ref[0, pl.ds(i * Bt, Bt)]
    do = do_ref[0, pl.ds(i * Bt, Bt)]
    lse = lse_ref[0, pl.ds(i * Bt, Bt), 0]
    delta = delta_ref[0, pl.ds(i * Bt, Bt), 0]
    q_pos = q_off + i * Bt + jax.lax.broadcasted_iota(jnp.int32, (Bt, Bt), 0)

    def dq_body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * Bt, Bt)]
        v_blk = v_ref[0, pl.ds(kb * Bt, Bt)]
        k_idx = kb * Bt + jax.lax.broadcasted_iota(jnp.int32, (Bt, Bt), 1)
        allowed = ((k_off + k_idx) <= q_pos) & (k_idx < real_len)
        return dq + _dq_block(q, k_blk, v_blk, do, scale, lse, delta, allowed)

    n_kb = jnp.clip((q_off + (i + 1) * Bt - 1 - k_off) // Bt + 1, 0, T // Bt)
    dq = jax.lax.fori_loop(0, n_kb, dq_body, jnp.zeros((Bt, D), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)

    # --- dk/dv for k-tile i: loop q sub-blocks of the local chunk ---
    k_t = k_ref[0, pl.ds(i * Bt, Bt)]
    v_t = v_ref[0, pl.ds(i * Bt, Bt)]
    k_idx_t = i * Bt + jax.lax.broadcasted_iota(jnp.int32, (Bt, Bt), 1)
    k_valid_t = k_idx_t < real_len
    k_pos_t = k_off + k_idx_t

    def dkv_body(qb, carry):
        dk, dv = carry
        q_b = q_ref[0, pl.ds(qb * Bt, Bt)]
        do_b = do_ref[0, pl.ds(qb * Bt, Bt)]
        lse_b = lse_ref[0, pl.ds(qb * Bt, Bt), 0]
        delta_b = delta_ref[0, pl.ds(qb * Bt, Bt), 0]
        q_pos_b = q_off + qb * Bt + jax.lax.broadcasted_iota(
            jnp.int32, (Bt, Bt), 0
        )
        allowed = (k_pos_t <= q_pos_b) & k_valid_t
        dk_p, dv_p = _dkv_block(
            q_b, k_t, v_t, do_b, scale, lse_b, delta_b, allowed
        )
        return dk + dk_p, dv + dv_p

    # Causal early-exit: q sub-blocks wholly before this k-tile's first
    # row contribute nothing.
    first_qb = jnp.clip((k_off + i * Bt - q_off) // Bt, 0, T // Bt)
    dk, dv = jax.lax.fori_loop(
        first_qb, T // Bt, dkv_body,
        (jnp.zeros((Bt, D), jnp.float32), jnp.zeros((Bt, D), jnp.float32)),
    )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def ring_round_bwd(q, k_blk, v_blk, do, lse, delta, q_off, k_off, scale):
    """One backward ring round: (dq_partial, dk_blk, dv_blk) for the
    visiting block, from recomputed probabilities (final ``lse``)."""
    B, Tl, D = q.shape
    Bt = _block(Tl)
    q_p = _pad_time(q, Bt)
    k_p = _pad_time(k_blk, Bt)
    v_p = _pad_time(v_blk, Bt)
    do_p = _pad_time(do, Bt)
    T = q_p.shape[1]
    pad = T - Tl
    if pad:
        # Huge positive lse pad => p = exp(s - huge) = 0 for padded rows
        # (a 0 pad could overflow exp and poison ds with inf * 0).
        lse = jnp.pad(lse, ((0, 0), (0, pad)), constant_values=-_NEG)
        delta = jnp.pad(delta, ((0, 0), (0, pad)))
    off = jnp.stack([q_off, k_off]).astype(jnp.int32).reshape(1, 2)
    grid = (B, T // Bt)
    blk, whole = _specs_btd(Bt, D, T)
    _, row_whole = _row_specs(Bt, T)
    smem = pl.BlockSpec((1, 2), lambda b, i: (0, 0), memory_space=pltpu.SMEM)

    dq, dk, dv = pl.pallas_call(
        functools.partial(_round_bwd_kernel, scale=scale, Bt=Bt, real_len=Tl),
        grid=grid,
        in_specs=[smem, whole, whole, whole, whole, row_whole, row_whole],
        out_specs=[blk, blk, blk],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, T, D), q.dtype),
        ],
        interpret=_interpret(),
    )(off, q_p, k_p, v_p, do_p, _rows_to_lanes(lse), _rows_to_lanes(delta))
    return dq[:, :Tl], dk[:, :Tl], dv[:, :Tl]
