"""Fused causal attention (flash attention) as Pallas TPU kernels.

The attention family's on-chip hot op, completing the kernel trio (fused
LSTM recurrence, fused clipped-MAE). The XLA path
(``tpuflow.parallel.ring_attention.full_attention``) materializes the
[T, T] score matrix in HBM; this kernel never does:

- the query axis tiles over the Pallas grid; for each query block the
  kernel streams key/value blocks through the MXU, maintaining the
  online-softmax running max/normalizer/accumulator in f32 — the
  flash-attention recurrence, scores living only in VMEM/registers;
- causal masking is applied per block from global positions, and key
  blocks entirely above the diagonal are never visited (the work is
  O(T^2/2), not O(T^2));
- backward recomputes the probabilities blockwise from the saved
  logsumexp (rematerialisation over HBM residency, as in the LSTM
  kernel): one kernel produces dQ, a second produces dK/dV, wired via
  ``jax.custom_vjp``.

Whole K/V for one batch-head are VMEM-resident per grid cell, which caps
this kernel at T around 10-20k for typical head dims — beyond that the
time axis should shard across chips instead (``ring_attention`` /
``examples/long_context_cp.py``); the two compose, ring outside, flash
inside a chunk, but the composition is not wired here.

On non-TPU backends the kernels run in Pallas interpret mode, so CI on
the 8-virtual-CPU-device mesh exercises the identical code path
(SURVEY.md §4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30  # finite mask value: keeps exp() NaN-free on masked rows


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block(T: int) -> int:
    """Query/key block length: 128 MXU-friendly rows, or the (8-aligned)
    whole sequence when it is shorter."""
    if T >= 128:
        return 128
    return max(8, -(-T // 8) * 8)


def _pad_time(x: jnp.ndarray, Bt: int) -> jnp.ndarray:
    pad = (-x.shape[1]) % Bt
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, Bk):
    """One (batch-head, query-block) cell: stream causal K/V blocks."""
    Bq, D = q_ref.shape[1], q_ref.shape[2]
    T = k_ref.shape[1]
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [Bq, D]
    q_pos = iq * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)

    m0 = jnp.full((Bq,), _NEG, jnp.float32)
    l0 = jnp.zeros((Bq,), jnp.float32)
    acc0 = jnp.zeros((Bq, D), jnp.float32)
    # Causal: key blocks past this query block's last row never attend.
    n_kb = jnp.minimum((iq + 1) * Bq + Bk - 1, T) // Bk

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * Bk, Bk)].astype(jnp.float32)  # [Bk, D]
        v_blk = v_ref[0, pl.ds(kb * Bk, Bk)].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [Bq, Bk]
        k_pos = kb * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
        allowed = k_pos <= q_pos
        s = jnp.where(allowed, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None]) * allowed.astype(jnp.float32)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, Bk
):
    """dQ for one (batch-head, query-block): dq = scale * sum_k ds @ K."""
    Bq, D = q_ref.shape[1], q_ref.shape[2]
    T = k_ref.shape[1]
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    delta = delta_ref[0]
    q_pos = iq * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
    n_kb = jnp.minimum((iq + 1) * Bq + Bk - 1, T) // Bk

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * Bk, Bk)].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * Bk, Bk)].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        k_pos = kb * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
        allowed = k_pos <= q_pos
        p = jnp.exp(jnp.where(allowed, s, _NEG) - lse[:, None])
        p = p * allowed.astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(0, n_kb, body, jnp.zeros((Bq, D), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, scale, Bq,
):
    """dK/dV for one (batch-head, key-block): loop causal query blocks."""
    Bk, D = k_ref.shape[1], k_ref.shape[2]
    T = q_ref.shape[1]
    ik = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)
    v_blk = v_ref[0].astype(jnp.float32)
    k_pos = ik * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
    nq = T // Bq
    first_qb = (ik * Bk) // Bq  # earlier query blocks are fully masked

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * Bq, Bq)].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(qb * Bq, Bq)].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * Bq, Bq)]
        delta = delta_ref[0, pl.ds(qb * Bq, Bq)]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [Bq, Bk]
        q_pos = qb * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
        allowed = k_pos <= q_pos
        p = jnp.exp(jnp.where(allowed, s, _NEG) - lse[:, None])
        p = p * allowed.astype(jnp.float32)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [Bk, D]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [Bk, D] — note q already carries `scale`
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        first_qb,
        nq,
        body,
        (jnp.zeros((Bk, D), jnp.float32), jnp.zeros((Bk, D), jnp.float32)),
    )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _specs_btd(Bt, D, whole_T):
    """(1, Bt, D) blocks over (batch-head, time-block) vs whole-sequence."""

    def blocked(b, i):
        return (b, i, 0)

    def whole(b, i):
        return (b, 0, 0)

    return (
        pl.BlockSpec((1, Bt, D), blocked, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, whole_T, D), whole, memory_space=pltpu.VMEM),
    )


def _fwd(q, k, v, scale):
    """Returns (out, lse), BOTH truncated to the caller's T — padding is
    private to each pallas wrapper, never part of the residuals."""
    BH, T0, D = q.shape
    Bt = _block(T0)
    q_p = _pad_time(q, Bt)
    k_p = _pad_time(k, Bt)
    v_p = _pad_time(v, Bt)
    T = q_p.shape[1]
    grid = (BH, T // Bt)
    blk, whole = _specs_btd(Bt, D, T)

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, Bk=Bt),
        grid=grid,
        in_specs=[blk, whole, whole],
        out_specs=[
            blk,
            pl.BlockSpec((1, Bt), lambda b, i: (b, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T), jnp.float32),
        ],
        interpret=_interpret(),
    )(q_p, k_p, v_p)
    return o[:, :T0], lse[:, :T0]


def _bwd(q, k, v, o, lse, do, scale):
    BH, T0, D = q.shape
    Bt = _block(T0)
    q_p = _pad_time(q, Bt)
    k_p = _pad_time(k, Bt)
    v_p = _pad_time(v, Bt)
    do_p = _pad_time(do, Bt)
    T = q_p.shape[1]
    # delta_i = sum_d do_i * o_i — tiny elementwise pass, jnp is the right
    # tool; padded rows contribute zeros.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )
    pad = T - T0
    if pad:
        delta = jnp.pad(delta, ((0, 0), (0, pad)))
        # lse arrives at T0 (_fwd contract). Pad with a huge POSITIVE
        # value so padded rows get p = exp(s - huge) = 0 exactly — a 0
        # pad could overflow exp(s) to inf and poison ds with inf * 0.
        lse = jnp.pad(lse, ((0, 0), (0, pad)), constant_values=-_NEG)
    grid = (BH, T // Bt)
    blk, whole = _specs_btd(Bt, D, T)
    row_blk = pl.BlockSpec((1, Bt), lambda b, i: (b, i), memory_space=pltpu.VMEM)
    row_whole = pl.BlockSpec((1, T), lambda b, i: (b, 0), memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, Bk=Bt),
        grid=grid,
        in_specs=[blk, whole, whole, blk, row_blk, row_blk],
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=_interpret(),
    )(q_p, k_p, v_p, do_p, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, Bq=Bt),
        grid=grid,
        in_specs=[whole, blk, blk, whole, row_whole, row_whole],
        out_specs=[blk, blk],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        ],
        interpret=_interpret(),
    )(q_p, k_p, v_p, do_p, lse, delta)
    return dq[:, :T0], dk[:, :T0], dv[:, :T0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, scale: float | None = None
) -> jnp.ndarray:
    """Fused causal attention: ``q, k, v [BH, T, D] -> [BH, T, D]``.

    Heads folded into the leading dim by the caller (the
    ``tpuflow.models.attention`` convention). Matches
    ``full_attention(..., causal=True)`` exactly (parity-tested, fwd and
    grads) without ever materializing the [T, T] score matrix.
    """
    out, _ = _fwd(q, k, v, scale if scale is not None else q.shape[-1] ** -0.5)
    return out


def _flash_fwd(q, k, v, scale):
    s = scale if scale is not None else q.shape[-1] ** -0.5
    out, lse = _fwd(q, k, v, s)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, res, do):
    q, k, v, out, lse = res
    s = scale if scale is not None else q.shape[-1] ** -0.5
    return _bwd(q, k, v, out, lse, do.astype(q.dtype), s)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
