"""Fused causal attention (flash attention) as Pallas TPU kernels.

The attention family's on-chip hot op, completing the kernel trio (fused
LSTM recurrence, fused clipped-MAE). The XLA path
(``tpuflow.parallel.ring_attention.full_attention``) materializes the
[T, T] score matrix in HBM; this kernel never does:

- the (query-block, KV-block) pairs tile over a 3D Pallas grid with the
  KV axis INNERMOST, so Pallas streams K/V tiles with double-buffered
  DMA overlapped against compute; the online-softmax running
  max/normalizer/accumulator lives in f32 VMEM scratch across the KV
  iterations of one q-block — the flash-attention recurrence, scores
  living only in VMEM/registers;
- matmul operands stay in their NATIVE dtype (bf16 rides the MXU's
  native mode) with f32 accumulation; the softmax scale applies to the
  f32 scores, so the math is exact for any operand dtype;
- causal masking is applied per block from global positions, and KV
  blocks entirely above the diagonal skip compute (the compute is
  O(T^2/2); their DMA still streams — the price of a static grid);
- backward recomputes the probabilities blockwise from the saved
  logsumexp (rematerialisation over HBM residency, as in the LSTM
  kernel): one kernel produces dQ (KV streaming), a second produces
  dK/dV (q-side streaming), wired via ``jax.custom_vjp``.

Only per-tile blocks are VMEM-resident, so the standalone kernel scales
to long T on one chip; past one chip's HBM the time axis shards across
chips instead (``ring_attention`` / ``examples/long_context_cp.py``).
The two COMPOSE: the ring-round
kernels at the bottom of this file run each CP ring round's block math
blockwise in VMEM (``ring_attention(..., impl="flash")``) — ring
outside, flash inside. The ring's custom VJP supplies differentiation,
so the round kernels carry none of their own.

On non-TPU backends the kernels run in Pallas interpret mode, so CI on
the 8-virtual-CPU-device mesh exercises the identical code path
(SURVEY.md §4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30  # finite mask value: keeps exp() NaN-free on masked rows

# Per-row stats (lse, delta, ring m/l) cannot travel as bare [BH, T]
# arrays with (1, Bt) blocks: Mosaic requires the last two block dims to
# be divisible by (8, 128) or equal to the array dims, and a row block's
# sublane dim of 1 violates that the moment the kernel compiles on a real
# chip (interpret mode never enforces it). So row stats travel as
# [BH, T, _LANES] with the value broadcast across the trailing lanes —
# the official TPU flash kernel's layout trick, at 8 lanes instead of 128
# so the HBM cost stays negligible next to q/k/v (the 8-wide last dim is
# legal because it EQUALS the array's last dim).
_LANES = 8


def _rows_to_lanes(x: jnp.ndarray) -> jnp.ndarray:
    """[..., T] row stats -> [..., T, _LANES] lane-broadcast layout."""
    return jnp.broadcast_to(x[..., None], (*x.shape, _LANES))


def _tile_i(b, i, j):
    """3D-grid index map: this operand rides the q-/k-side dim (1)."""
    return (b, i, 0)


def _tile_j(b, i, j):
    """3D-grid index map: this operand STREAMS with the innermost dim."""
    return (b, j, 0)


def _btd(Bt, D, index):
    """[*, Bt, D] tile spec for the 3D streaming grids."""
    return pl.BlockSpec((1, Bt, D), index, memory_space=pltpu.VMEM)


def _rows(Bt, index):
    """[*, Bt, _LANES] lane-broadcast row-stat spec for the 3D grids."""
    return pl.BlockSpec((1, Bt, _LANES), index, memory_space=pltpu.VMEM)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# Upper bound for TPUFLOW_FLASH_BLOCK: at 1024 the kernel's VMEM-resident
# working set (q/k/v tiles plus the [blk, blk] f32 score tile — 4 MB at
# 1024, 16 MB at 2048) is still comfortably inside a core's ~16 MB VMEM;
# past it Mosaic fails at lowering time with an opaque allocation error,
# so the bound is enforced HERE with an error naming the env var.
_MAX_BLOCK = 1024


def _block(T: int) -> int:
    """Query/key block length, or the (8-aligned) whole sequence when it
    is shorter. Default 256: the round-5 on-chip timing showed the
    128-row kernel neither HBM- nor MXU-bound (2.7% HBM util, 1.7% MFU)
    — serialization-bound on too-small inner matmuls — so bigger tiles
    put more arithmetic on the MXU per online-softmax iteration.
    TPUFLOW_FLASH_BLOCK overrides for on-chip sweeps, clamped to
    [8, _MAX_BLOCK] — an oversized block fails here, by name, not
    on-chip as an opaque Mosaic error."""
    import os

    raw = os.environ.get("TPUFLOW_FLASH_BLOCK", 256)
    blk = int(raw)
    if blk > _MAX_BLOCK:
        raise ValueError(
            f"TPUFLOW_FLASH_BLOCK={blk} exceeds the {_MAX_BLOCK} upper "
            f"bound: the kernel keeps a [block, block] f32 score tile in "
            f"VMEM (~{blk * blk * 4 / 2**20:.0f} MB at {blk}) and Mosaic "
            "would fail allocation on-chip with an opaque error; use "
            f"8 <= TPUFLOW_FLASH_BLOCK <= {_MAX_BLOCK}"
        )
    blk = max(blk, 8)
    blk = -(-blk // 8) * 8  # Mosaic sublane rule: blocks must be 8-aligned
    if T >= blk:
        return blk
    return max(8, -(-T // 8) * 8)


def _pad_time(x: jnp.ndarray, Bt: int) -> jnp.ndarray:
    pad = (-x.shape[1]) % Bt
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _online_block_update(q, k_blk, v_blk, scale, m, l, acc, allowed):
    """The flash forward recurrence for ONE (q-tile, kv-block) pair —
    the single source of the online-softmax math, shared by the
    standalone kernel and the CP ring-round kernel.

    ``q``/``k_blk``/``v_blk`` stay in their NATIVE dtype so bf16 inputs
    ride the MXU's native mode with f32 accumulation (an all-f32 operand
    matmul costs multiple MXU passes — the round-5 on-chip timing showed
    the f32-everything kernel serialization-bound). ``scale`` applies to
    the f32 scores, which keeps the softmax math and the VJP exact
    regardless of operand dtype. Stats/accumulator are f32."""
    s = scale * jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    s = jnp.where(allowed, s, _NEG)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[:, None]) * allowed.astype(jnp.float32)
    corr = jnp.exp(m - m_new)
    l = l * corr + jnp.sum(p, axis=-1)
    acc = acc * corr[:, None] + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l, acc


def _p_block(q, k_blk, scale, lse, allowed):
    """Backward-pass probabilities exp(s - lse) for one block pair —
    already FINAL softmax values (not running partials), so every
    block's contribution is correctly normalized independently."""
    s = scale * jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    p = jnp.exp(jnp.where(allowed, s, _NEG) - lse[:, None])
    return p * allowed.astype(jnp.float32)


def _dq_block(q, k_blk, v_blk, do, scale, lse, delta, allowed):
    """One block pair's contribution to dQ (the final * scale is applied
    by the caller, once, outside the accumulation loop)."""
    p = _p_block(q, k_blk, scale, lse, allowed)
    dp = jax.lax.dot_general(
        do, v_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta[:, None])
    return jax.lax.dot_general(
        ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _dkv_block(q, k_blk, v_blk, do, scale, lse, delta, allowed):
    """One block pair's contribution to (dK, dV). dK carries the score
    scale (dS/dK = scale * Q)."""
    p = _p_block(q, k_blk, scale, lse, allowed)
    dv = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        do, v_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta[:, None])
    dk = scale * jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dk, dv


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, scale, nk,
):
    """One (batch-head, q-block, KV-block) grid cell.

    The KV axis is the INNERMOST grid dimension, so Pallas streams the
    K/V blocks with double-buffered DMA overlapped against the
    online-softmax compute — the serial in-kernel ``fori_loop`` version
    this replaces measured neither HBM- nor MXU-bound on-chip (round 5),
    i.e. stalled, with nothing overlapped. The running (m, l, acc) state
    lives in VMEM scratch across the KV iterations of one q-block;
    outputs are written on the last KV iteration. KV blocks wholly above
    the causal diagonal skip compute (their DMA still streams — the
    price of a static grid)."""
    Bq = q_ref.shape[1]
    Bk = k_ref.shape[1]
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: only KV blocks whose first row is <= this q-block's last.
    @pl.when(j * Bk <= (i + 1) * Bq - 1)
    def _compute():
        q_pos = i * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
        k_pos = j * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
        m, l, acc = _online_block_update(
            q_ref[0], k_ref[0], v_ref[0], scale,
            m_scr[:, 0], l_scr[:, 0], acc_scr[:, :],
            k_pos <= q_pos,
        )
        m_scr[:] = _rows_to_lanes(m)
        l_scr[:] = _rows_to_lanes(l)
        acc_scr[:] = acc

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0, 1.0, l)
        o_ref[0] = (acc_scr[:, :] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = _rows_to_lanes(m_scr[:, 0] + jnp.log(l_safe))


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, scale, nk,
):
    """dQ, one (batch-head, q-block, STREAMED kv-block) grid cell:
    dq = scale * sum_k ds @ K, accumulated in VMEM scratch across the
    pipelined KV iterations (same streaming layout as the forward)."""
    Bq = q_ref.shape[1]
    Bk = k_ref.shape[1]
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(j * Bk <= (i + 1) * Bq - 1)  # causal skip
    def _compute():
        q_pos = i * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
        k_pos = j * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
        dq_scr[:] += _dq_block(
            q_ref[0], k_ref[0], v_ref[0], do_ref[0], scale,
            lse_ref[0][:, 0], delta_ref[0][:, 0], k_pos <= q_pos,
        )

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = (dq_scr[:, :] * scale).astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, *, scale, nq,
):
    """dK/dV, one (batch-head, k-block, STREAMED q-block) grid cell:
    the q/do/lse/delta tiles stream through the innermost grid dim while
    (dk, dv) accumulate in VMEM scratch."""
    Bk = k_ref.shape[1]
    Bq = q_ref.shape[1]
    i, j = pl.program_id(1), pl.program_id(2)  # i: k-tile, j: q-tile

    @pl.when(j == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # Causal: q-tiles wholly before this k-tile contribute nothing.
    @pl.when((j + 1) * Bq - 1 >= i * Bk)
    def _compute():
        k_pos = i * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
        q_pos = j * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
        dk_p, dv_p = _dkv_block(
            q_ref[0], k_ref[0], v_ref[0], do_ref[0], scale,
            lse_ref[0][:, 0], delta_ref[0][:, 0], k_pos <= q_pos,
        )
        dk_scr[:] += dk_p
        dv_scr[:] += dv_p

    @pl.when(j == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:, :].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:, :].astype(dv_ref.dtype)


def _specs_btd(Bt, D, whole_T):
    """(1, Bt, D) blocks over (batch-head, time-block) vs whole-sequence."""

    def blocked(b, i):
        return (b, i, 0)

    def whole(b, i):
        return (b, 0, 0)

    return (
        pl.BlockSpec((1, Bt, D), blocked, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, whole_T, D), whole, memory_space=pltpu.VMEM),
    )


def _row_specs(Bt, whole_T):
    """Lane-broadcast row-stat blocks: per-q-tile vs whole-sequence."""
    return (
        pl.BlockSpec(
            (1, Bt, _LANES), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM
        ),
        pl.BlockSpec(
            (1, whole_T, _LANES), lambda b, i: (b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
    )


def _fwd(q, k, v, scale):
    """Returns (out, lse), BOTH truncated to the caller's T — padding is
    private to each pallas wrapper, never part of the residuals."""
    BH, T0, D = q.shape
    Bt = _block(T0)
    q_p = _pad_time(q, Bt)
    k_p = _pad_time(k, Bt)
    v_p = _pad_time(v, Bt)
    T = q_p.shape[1]
    nk = T // Bt
    grid = (BH, T // Bt, nk)  # (batch-head, q-block, STREAMED kv-block)
    q_blk = _btd(Bt, D, _tile_i)
    kv_blk = _btd(Bt, D, _tile_j)
    lse_blk = _rows(Bt, _tile_i)

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, nk=nk),
        grid=grid,
        in_specs=[q_blk, kv_blk, kv_blk],
        out_specs=[q_blk, lse_blk],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((Bt, _LANES), jnp.float32),
            pltpu.VMEM((Bt, _LANES), jnp.float32),
            pltpu.VMEM((Bt, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q_p, k_p, v_p)
    return o[:, :T0], lse[:, :T0, 0]


def _bwd(q, k, v, o, lse, do, scale):
    BH, T0, D = q.shape
    Bt = _block(T0)
    q_p = _pad_time(q, Bt)
    k_p = _pad_time(k, Bt)
    v_p = _pad_time(v, Bt)
    do_p = _pad_time(do, Bt)
    T = q_p.shape[1]
    # delta_i = sum_d do_i * o_i — tiny elementwise pass, jnp is the right
    # tool; padded rows contribute zeros.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )
    pad = T - T0
    if pad:
        delta = jnp.pad(delta, ((0, 0), (0, pad)))
        # lse arrives at T0 (_fwd contract). Pad with a huge POSITIVE
        # value so padded rows get p = exp(s - huge) = 0 exactly — a 0
        # pad could overflow exp(s) to inf and poison ds with inf * 0.
        lse = jnp.pad(lse, ((0, 0), (0, pad)), constant_values=-_NEG)
    n_t = T // Bt
    lse_l = _rows_to_lanes(lse)
    delta_l = _rows_to_lanes(delta)
    btd = functools.partial(_btd, Bt, D)
    rows = functools.partial(_rows, Bt)

    # dQ: grid (batch-head, q-block, streamed kv-block) — q-side tiles
    # ride dim 1, KV tiles stream through dim 2.
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, nk=n_t),
        grid=(BH, n_t, n_t),
        in_specs=[
            btd(_tile_i), btd(_tile_j), btd(_tile_j), btd(_tile_i),
            rows(_tile_i), rows(_tile_i),
        ],
        out_specs=btd(_tile_i),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((Bt, D), jnp.float32)],
        interpret=_interpret(),
    )(q_p, k_p, v_p, do_p, lse_l, delta_l)

    # dK/dV: grid (batch-head, k-block, streamed q-block) — k-side tiles
    # ride dim 1, q-side tiles (q, do, lse, delta) stream through dim 2.
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, nq=n_t),
        grid=(BH, n_t, n_t),
        in_specs=[
            btd(_tile_j), btd(_tile_i), btd(_tile_i), btd(_tile_j),
            rows(_tile_j), rows(_tile_j),
        ],
        out_specs=[btd(_tile_i), btd(_tile_i)],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((Bt, D), jnp.float32),
            pltpu.VMEM((Bt, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q_p, k_p, v_p, do_p, lse_l, delta_l)
    return dq[:, :T0], dk[:, :T0], dv[:, :T0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, scale: float | None = None
) -> jnp.ndarray:
    """Fused causal attention: ``q, k, v [BH, T, D] -> [BH, T, D]``.

    Heads folded into the leading dim by the caller (the
    ``tpuflow.models.attention`` convention). Matches
    ``full_attention(..., causal=True)`` exactly (parity-tested, fwd and
    grads) without ever materializing the [T, T] score matrix.
    """
    out, _ = _fwd(q, k, v, scale if scale is not None else q.shape[-1] ** -0.5)
    return out


def _flash_fwd(q, k, v, scale):
    s = scale if scale is not None else q.shape[-1] ** -0.5
    out, lse = _fwd(q, k, v, s)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, res, do):
    q, k, v, out, lse = res
    s = scale if scale is not None else q.shape[-1] ** -0.5
    return _bwd(q, k, v, out, lse, do.astype(q.dtype), s)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# --------------------------------------------------------------------------
# Ring-round kernels: flash blockwise math INSIDE the CP ring
# (tpuflow.parallel.ring_attention impl="flash"). Each ring round attends
# the local Q chunk to ONE visiting KV block; global positions arrive as
# SMEM scalars because the block's origin is a traced device index. The
# ring's custom VJP supplies differentiation, so these kernels need none.
# --------------------------------------------------------------------------


def _round_fwd_kernel(
    off_ref, q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
    m_out, l_out, acc_out, *, scale, Bk, real_len,
):
    """Online-softmax update of one q-tile against the visiting block."""
    Bq, D = q_ref.shape[1], q_ref.shape[2]
    T = k_ref.shape[1]
    iq = pl.program_id(1)
    q_off, k_off = off_ref[0, 0], off_ref[0, 1]
    q = q_ref[0]
    q_pos = q_off + iq * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
    m = m_ref[0][:, 0].astype(jnp.float32)
    l = l_ref[0][:, 0].astype(jnp.float32)
    acc = acc_ref[0].astype(jnp.float32)

    def body(kb, carry):
        k_blk = k_ref[0, pl.ds(kb * Bk, Bk)]
        v_blk = v_ref[0, pl.ds(kb * Bk, Bk)]
        k_idx = kb * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
        # Padded K rows sit at global positions that ALIAS the next
        # block's territory — causality alone would admit them; mask by
        # the block's real length too.
        allowed = ((k_off + k_idx) <= q_pos) & (k_idx < real_len)
        return _online_block_update(q, k_blk, v_blk, scale, *carry, allowed)

    # Causal early-exit: sub-blocks wholly past this q-tile's last row
    # are never visited (~half of all device-rounds carry a fully-future
    # block and do zero loop iterations).
    n_kb = jnp.clip(
        (q_off + (iq + 1) * Bq - 1 - k_off) // Bk + 1, 0, T // Bk
    )
    m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m, l, acc))
    m_out[0] = _rows_to_lanes(m)
    l_out[0] = _rows_to_lanes(l)
    acc_out[0] = acc.astype(acc_out.dtype)


def ring_round_fwd(q, k_blk, v_blk, m, l, acc, q_off, k_off, scale):
    """One causal ring round: update (m, l, acc) with the visiting block.

    ``q [B, Tl, D]`` local queries; ``k_blk, v_blk [B, Tl, D]`` the block
    currently held; ``m, l [B, Tl]`` / ``acc [B, Tl, D]`` f32 running
    stats; ``q_off, k_off`` GLOBAL start positions (traced scalars).
    """
    B, Tl, D = q.shape
    Bt = _block(Tl)
    q_p = _pad_time(q, Bt)
    k_p = _pad_time(k_blk, Bt)
    v_p = _pad_time(v_blk, Bt)
    T = q_p.shape[1]
    pad = T - Tl
    if pad:
        # Padded q rows must stay neutral; padded k rows are masked out
        # by causality only if their global position exceeds every real
        # q position — guaranteed by placing them at k_off + [Tl, T).
        m = jnp.pad(m, ((0, 0), (0, pad)), constant_values=_NEG)
        l = jnp.pad(l, ((0, 0), (0, pad)))
        acc = jnp.pad(acc, ((0, 0), (0, pad), (0, 0)))
    off = jnp.stack([q_off, k_off]).astype(jnp.int32).reshape(1, 2)
    grid = (B, T // Bt)
    blk, whole = _specs_btd(Bt, D, T)
    row_blk, _ = _row_specs(Bt, T)
    smem = pl.BlockSpec((1, 2), lambda b, i: (0, 0), memory_space=pltpu.SMEM)

    m2, l2, acc2 = pl.pallas_call(
        functools.partial(_round_fwd_kernel, scale=scale, Bk=Bt, real_len=Tl),
        grid=grid,
        in_specs=[smem, blk, whole, whole, row_blk, row_blk, blk],
        out_specs=[row_blk, row_blk, blk],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((B, T, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((B, T, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(off, q_p, k_p, v_p, _rows_to_lanes(m), _rows_to_lanes(l), acc)
    return m2[:, :Tl, 0], l2[:, :Tl, 0], acc2[:, :Tl]


def _round_bwd_kernel(
    off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dk_ref, dv_ref, *, scale, Bt, real_len,
):
    """One (b, tile) cell: this round's dq for the q-tile AND the tile's
    dk/dv rows. dq tiles over q; dk/dv tile over the SAME index on the
    k side (both sequences have identical padded length)."""
    T = q_ref.shape[1]
    D = q_ref.shape[2]
    i = pl.program_id(1)
    q_off, k_off = off_ref[0, 0], off_ref[0, 1]

    # --- dq for q-tile i: loop k sub-blocks of the visiting block ---
    q = q_ref[0, pl.ds(i * Bt, Bt)]
    do = do_ref[0, pl.ds(i * Bt, Bt)]
    lse = lse_ref[0, pl.ds(i * Bt, Bt), 0]
    delta = delta_ref[0, pl.ds(i * Bt, Bt), 0]
    q_pos = q_off + i * Bt + jax.lax.broadcasted_iota(jnp.int32, (Bt, Bt), 0)

    def dq_body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * Bt, Bt)]
        v_blk = v_ref[0, pl.ds(kb * Bt, Bt)]
        k_idx = kb * Bt + jax.lax.broadcasted_iota(jnp.int32, (Bt, Bt), 1)
        allowed = ((k_off + k_idx) <= q_pos) & (k_idx < real_len)
        return dq + _dq_block(q, k_blk, v_blk, do, scale, lse, delta, allowed)

    n_kb = jnp.clip((q_off + (i + 1) * Bt - 1 - k_off) // Bt + 1, 0, T // Bt)
    dq = jax.lax.fori_loop(0, n_kb, dq_body, jnp.zeros((Bt, D), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)

    # --- dk/dv for k-tile i: loop q sub-blocks of the local chunk ---
    k_t = k_ref[0, pl.ds(i * Bt, Bt)]
    v_t = v_ref[0, pl.ds(i * Bt, Bt)]
    k_idx_t = i * Bt + jax.lax.broadcasted_iota(jnp.int32, (Bt, Bt), 1)
    k_valid_t = k_idx_t < real_len
    k_pos_t = k_off + k_idx_t

    def dkv_body(qb, carry):
        dk, dv = carry
        q_b = q_ref[0, pl.ds(qb * Bt, Bt)]
        do_b = do_ref[0, pl.ds(qb * Bt, Bt)]
        lse_b = lse_ref[0, pl.ds(qb * Bt, Bt), 0]
        delta_b = delta_ref[0, pl.ds(qb * Bt, Bt), 0]
        q_pos_b = q_off + qb * Bt + jax.lax.broadcasted_iota(
            jnp.int32, (Bt, Bt), 0
        )
        allowed = (k_pos_t <= q_pos_b) & k_valid_t
        dk_p, dv_p = _dkv_block(
            q_b, k_t, v_t, do_b, scale, lse_b, delta_b, allowed
        )
        return dk + dk_p, dv + dv_p

    # Causal early-exit: q sub-blocks wholly before this k-tile's first
    # row contribute nothing.
    first_qb = jnp.clip((k_off + i * Bt - q_off) // Bt, 0, T // Bt)
    dk, dv = jax.lax.fori_loop(
        first_qb, T // Bt, dkv_body,
        (jnp.zeros((Bt, D), jnp.float32), jnp.zeros((Bt, D), jnp.float32)),
    )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def ring_round_bwd(q, k_blk, v_blk, do, lse, delta, q_off, k_off, scale):
    """One backward ring round: (dq_partial, dk_blk, dv_blk) for the
    visiting block, from recomputed probabilities (final ``lse``)."""
    B, Tl, D = q.shape
    Bt = _block(Tl)
    q_p = _pad_time(q, Bt)
    k_p = _pad_time(k_blk, Bt)
    v_p = _pad_time(v_blk, Bt)
    do_p = _pad_time(do, Bt)
    T = q_p.shape[1]
    pad = T - Tl
    if pad:
        # Huge positive lse pad => p = exp(s - huge) = 0 for padded rows
        # (a 0 pad could overflow exp and poison ds with inf * 0).
        lse = jnp.pad(lse, ((0, 0), (0, pad)), constant_values=-_NEG)
        delta = jnp.pad(delta, ((0, 0), (0, pad)))
    off = jnp.stack([q_off, k_off]).astype(jnp.int32).reshape(1, 2)
    grid = (B, T // Bt)
    blk, whole = _specs_btd(Bt, D, T)
    _, row_whole = _row_specs(Bt, T)
    smem = pl.BlockSpec((1, 2), lambda b, i: (0, 0), memory_space=pltpu.SMEM)

    dq, dk, dv = pl.pallas_call(
        functools.partial(_round_bwd_kernel, scale=scale, Bt=Bt, real_len=Tl),
        grid=grid,
        in_specs=[smem, whole, whole, whole, whole, row_whole, row_whole],
        out_specs=[blk, blk, blk],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, T, D), q.dtype),
        ],
        interpret=_interpret(),
    )(off, q_p, k_p, v_p, do_p, _rows_to_lanes(lse), _rows_to_lanes(delta))
    return dq[:, :Tl], dk[:, :Tl], dv[:, :Tl]
