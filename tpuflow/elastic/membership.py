"""Gang membership: per-worker heartbeat files + deadline-based liveness.

SparkNet/DeepSpark (PAPERS.md) tolerate worker loss because the driver's
view of the gang is *observed*, not assumed: a worker that stops talking
is simply no longer part of the next averaging step. This module is that
observation layer, deliberately file-based — it works on any shared
filesystem today (the same transport the param exchange uses), needs no
collective runtime to be healthy, and survives arbitrary membership
churn because membership IS just the set of files whose mtimes are
fresh.

Each worker overwrites ``{gang_dir}/members/{worker_id}.json`` with a
small heartbeat record (atomic tmp+rename, so a reader never sees a torn
write)::

    {"worker_id": 2, "time": <clock>, "epoch": 7, "round": 7,
     "status": "running", "pid": 12345}

The coordinator classifies each member against ``heartbeat_timeout``:

- **live** — heartbeat age <= timeout and status != "done"/"failed".
- **stale** — age > timeout: the worker is presumed dead and EVICTED
  from averaging (it keeps its file; a fresh heartbeat readmits it — the
  rejoin path, no registration handshake needed).
- **done/failed** — the worker said goodbye; never waited on again.

Clocks and sleeps are injectable everywhere so eviction/rejoin logic is
drilled in tier-1 with a fake clock — no wall-clock waits.

The ``elastic.heartbeat`` fault site fires inside every heartbeat write:
arming it (``mode=raise`` kills the heartbeat thread, ``mode=exit`` the
worker) is the reproducible "worker goes silent" drill the eviction
deadline exists for.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass

from tpuflow.resilience import fault_point
from tpuflow.utils.paths import atomic_write_json

MEMBERS_DIR = "members"

# Heartbeat states a worker reports about itself. "joining" covers the
# warm-start window (the worker is alive but not yet pushing rounds);
# terminal states tell the coordinator to stop waiting on this worker
# without any eviction deadline.
STATUSES = ("joining", "running", "done", "failed")
TERMINAL_STATUSES = ("done", "failed")


@dataclass
class Member:
    """One worker's last heartbeat, as the coordinator reads it."""

    worker_id: int
    time: float
    epoch: int = 0
    round: int = 0
    status: str = "joining"
    pid: int | None = None

    def age(self, now: float) -> float:
        return now - self.time


def members_dir(gang_dir: str) -> str:
    return os.path.join(gang_dir, MEMBERS_DIR)


def heartbeat_path(gang_dir: str, worker_id: int) -> str:
    return os.path.join(members_dir(gang_dir), f"{worker_id}.json")


def goodbye_path(gang_dir: str, worker_id: int) -> str:
    """The sticky-terminal marker (deliberately not ``*.json`` — the
    member scanner globs heartbeats; this file is a flag, not one)."""
    return os.path.join(members_dir(gang_dir), f"{worker_id}.goodbye")


def read_goodbye(gang_dir: str, worker_id: int) -> str | None:
    """The marker's terminal status, or None when no goodbye stands."""
    try:
        with open(goodbye_path(gang_dir, worker_id), encoding="utf-8") as f:
            status = json.load(f).get("status")
    except (OSError, ValueError, TypeError, AttributeError,
            json.JSONDecodeError):
        return None
    return status if status in TERMINAL_STATUSES else None


def write_heartbeat(
    gang_dir: str,
    worker_id: int,
    *,
    epoch: int = 0,
    round: int = 0,
    status: str = "running",
    clock=time.time,
) -> bool:
    """Overwrite this worker's heartbeat file (atomic tmp+rename);
    returns False when a standing goodbye suppressed the write.

    Raises on an unknown status — a typo'd terminal state would leave
    the coordinator waiting on a worker that thinks it said goodbye.

    **Terminal statuses are sticky.** A ``done``/``failed`` beat also
    drops the goodbye-marker file; once it exists, a late ``running``
    beat from a wedged heartbeat thread is (1) skipped here
    (compare-before-write) and (2) even if its rename races past the
    check, overridden at read time — ``read_members`` folds the marker
    back into the record. Only an explicit ``joining`` beat (a NEW
    incarnation announcing itself at ``join()``) clears the marker, so
    the supervised restart+rejoin path is unaffected.
    """
    if status not in STATUSES:
        raise ValueError(
            f"unknown heartbeat status {status!r}; valid: {STATUSES}"
        )
    fault_point("elastic.heartbeat")
    os.makedirs(members_dir(gang_dir), exist_ok=True)
    marker = goodbye_path(gang_dir, worker_id)
    if status == "joining":
        try:  # a new incarnation's hello revokes the old goodbye
            os.remove(marker)
        except OSError:
            pass
    elif status not in TERMINAL_STATUSES and os.path.exists(marker):
        return False  # the goodbye stands; never beat over it
    # atomic_write_json's tmp name is unique per (process, thread): the
    # worker's heartbeat thread and its main-thread sync beats write
    # this path concurrently.
    atomic_write_json(
        heartbeat_path(gang_dir, worker_id),
        {
            "worker_id": worker_id,
            "time": clock(),
            "epoch": epoch,
            "round": round,
            "status": status,
            "pid": os.getpid(),
        },
    )
    if status in TERMINAL_STATUSES:
        atomic_write_json(marker, {"status": status})
    return True


def read_members(gang_dir: str) -> list[Member]:
    """Every member file, torn/corrupt ones skipped (the write side is
    atomic, so unreadable means "being replaced right now" — the next
    scan sees it)."""
    d = members_dir(gang_dir)
    out: list[Member] = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    # One directory listing serves both the heartbeat scan and the
    # goodbye-marker probe: in the steady state (no goodbyes) no extra
    # per-member open() is issued — this scan runs every poll_interval,
    # and doubling its metadata ops would cost exactly what deriving
    # the poll cadence from heartbeat_interval saves.
    goodbyes = {n for n in names if n.endswith(".goodbye")}
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name), encoding="utf-8") as f:
                rec = json.load(f)
            if not isinstance(rec, dict):
                continue  # stray JSON that isn't a heartbeat record
            status = str(rec.get("status", "running"))
            if (
                status not in TERMINAL_STATUSES
                and f"{rec.get('worker_id')}.goodbye" in goodbyes
            ):
                # Sticky goodbye: a standing marker overrides whatever a
                # racing late beat managed to rename into place (the
                # read-side half of write_heartbeat's terminal contract).
                goodbye = read_goodbye(gang_dir, int(rec["worker_id"]))
                if goodbye is not None:
                    status = goodbye
            out.append(Member(
                worker_id=int(rec["worker_id"]),
                time=float(rec["time"]),
                epoch=int(rec.get("epoch", 0)),
                round=int(rec.get("round", 0)),
                status=status,
                pid=rec.get("pid"),
            ))
        except (OSError, ValueError, TypeError, KeyError,
                json.JSONDecodeError):
            continue  # torn/corrupt/alien file: the next scan decides
    return out


@dataclass
class MembershipView:
    """One scan's classification of the gang (see module docstring)."""

    live: list[Member]
    stale: list[Member]
    finished: list[Member]

    @property
    def live_ids(self) -> set[int]:
        return {m.worker_id for m in self.live}

    @property
    def stale_ids(self) -> set[int]:
        return {m.worker_id for m in self.stale}


def classify_view(
    members: list[Member], heartbeat_timeout: float, now: float
) -> MembershipView:
    """Partition already-read member records into live / stale
    (evictable) / finished against the eviction deadline at observation
    time ``now`` — the transport-agnostic half of classification. The
    file backend's records carry worker-side write times; the socket
    backend's carry coordinator-side ARRIVAL times, which makes
    eviction a transport-level liveness verdict (a partitioned worker's
    beats never land, so it goes stale even though it is still beating
    into the void)."""
    live, stale, finished = [], [], []
    for m in members:
        if m.status in TERMINAL_STATUSES:
            finished.append(m)
        elif m.age(now) > heartbeat_timeout:
            stale.append(m)
        else:
            live.append(m)
    return MembershipView(live=live, stale=stale, finished=finished)


def classify_members(
    gang_dir: str, heartbeat_timeout: float, now: float
) -> MembershipView:
    """Read-and-classify over the file transport (see
    :func:`classify_view`)."""
    return classify_view(read_members(gang_dir), heartbeat_timeout, now)
