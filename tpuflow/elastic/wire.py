"""Wire encodings for parameter pushes: delta against the last adopted
average, optionally quantized to bfloat16 on the wire.

The TPFX payload (``exchange.encode_leaves``) carries full-f32 leaves by
default. At gang scale the push traffic dominates the exchange, and two
orthogonal reductions compose here:

- **Delta encoding** (``delta``): the worker sends ``params - base``
  where ``base`` is the average it last adopted. The receiver, which
  published that average, reconstructs ``base + delta`` exactly (both
  sides hold the same f32 base). Deltas shrink the *quantization* cost
  of the second reduction — the error of rounding a delta is
  proportional to the delta's magnitude, not the parameter's.
- **bf16 quantization** (``wire_dtype="bf16"``): each floating leaf is
  round-to-nearest-even truncated to the top 16 bits of its f32
  pattern and shipped as ``uint16`` — exactly half the bytes. numpy has
  no native bfloat16, so the bits ride as ``uint16`` and the per-leaf
  flag list in the encoding header says which leaves to re-expand.

Masters stay f32 (the PR 10 precision policy): quantization happens at
the moment of encoding and is undone at the moment of decoding —
every fold (``exchange.average_leaf_sets``) runs on f32/f64, at every
tier. Non-floating leaves (step counters under the ``opt_policy=
"average"`` payload) pass through both stages untouched.

The encoding header (``enc`` on the TPFX frame) is self-describing::

    {"delta": true, "base_round": 7, "bf16": [1, 1, 0, ...]}

A receiver that no longer holds ``base_round``'s average (pruned past
it) answers ``stored: false`` instead of an error and the sender
re-pushes a full encoding — a slow path, never a lost push.
"""

from __future__ import annotations

import numpy as np

from tpuflow.elastic import exchange

WIRE_DTYPES = ("f32", "bf16")


class DeltaBaseUnavailable(ValueError):
    """A delta-encoded payload references a base average the decoder
    does not hold (pruned, or never published here). The transport
    layer turns this into a ``stored: false`` response so the sender
    falls back to a full push."""


def quantize_bf16(a: np.ndarray) -> np.ndarray:
    """f32 array -> its bfloat16 bit pattern as ``uint16`` (IEEE
    round-to-nearest-even on the dropped mantissa half), half the
    bytes of the input. NaN never rounds: the bias add would carry a
    high-mantissa NaN's bits into the sign (0x7FFFFFFF + 0x8000 wraps
    to -0.0 bits), silently zeroing the very divergence the wire must
    surface — so NaNs are truncated with the quiet bit forced instead,
    the standard bf16 treatment."""
    f = np.ascontiguousarray(a, np.float32)
    bits = f.view(np.uint32)
    # Round-to-nearest-even: add 0x7FFF plus the current LSB of the
    # kept half, so exactly-halfway values round to an even result.
    rounding = ((bits >> 16) & np.uint32(1)) + np.uint32(0x7FFF)
    out = ((bits + rounding) >> 16).astype(np.uint16)
    nan = np.isnan(f)
    if nan.any():
        out = np.where(
            nan,
            (bits >> 16).astype(np.uint16) | np.uint16(0x0040),
            out,
        )
    return out


def dequantize_bf16(u: np.ndarray) -> np.ndarray:
    """bfloat16 bit pattern (``uint16``) -> f32 (exact expansion)."""
    return (
        np.ascontiguousarray(u, np.uint16).astype(np.uint32) << 16
    ).view(np.float32)


def encode_push(
    leaves: list[np.ndarray],
    *,
    wire_dtype: str = "f32",
    base: list[np.ndarray] | None = None,
    base_round: int | None = None,
) -> tuple[dict, bytes]:
    """Leaves -> ``(enc_header, payload_bytes)``.

    ``base`` (with its ``base_round``) switches on delta encoding;
    ``wire_dtype="bf16"`` quantizes floating leaves. The header is
    ``{}`` for a plain full-f32 push — absent from the frame, so the
    non-tree wire format is byte-identical to what it always was.
    """
    if wire_dtype not in WIRE_DTYPES:
        raise ValueError(
            f"wire_dtype must be one of {WIRE_DTYPES}, got {wire_dtype!r}"
        )
    enc: dict = {}
    out = [np.asarray(leaf) for leaf in leaves]
    if base is not None:
        if base_round is None:
            raise ValueError("delta encoding needs base_round")
        if len(base) != len(out):
            raise ValueError(
                f"delta base has {len(base)} leaves; push has "
                f"{len(out)} — stale base from a different layout"
            )
        deltas = []
        for leaf, b in zip(out, base):
            if np.issubdtype(leaf.dtype, np.floating):
                deltas.append(
                    np.asarray(leaf, np.float32)
                    - np.asarray(b, np.float32)
                )
            else:
                deltas.append(leaf)  # counters ship whole
        out = deltas
        enc["delta"] = True
        enc["base_round"] = int(base_round)
    if wire_dtype == "bf16":
        flags = []
        packed = []
        for leaf in out:
            if np.issubdtype(leaf.dtype, np.floating):
                packed.append(quantize_bf16(leaf))
                flags.append(1)
            else:
                packed.append(leaf)
                flags.append(0)
        out = packed
        enc["bf16"] = flags
    return enc, exchange.encode_leaves(out)


def decode_push(
    enc: dict | None,
    payload: bytes,
    *,
    base: list[np.ndarray] | None = None,
) -> list[np.ndarray]:
    """``(enc_header, payload)`` -> full f32 leaves, undoing bf16 then
    delta. A delta payload with no ``base`` raises
    :class:`DeltaBaseUnavailable` (the caller resolves the base round
    and decides the fallback)."""
    enc = enc or {}
    leaves = exchange.decode_leaves(payload)
    flags = enc.get("bf16")
    if flags:
        if len(flags) != len(leaves):
            raise ValueError(
                f"bf16 flag list covers {len(flags)} leaves; payload "
                f"has {len(leaves)}"
            )
        leaves = [
            dequantize_bf16(leaf) if flag else leaf
            for leaf, flag in zip(leaves, flags)
        ]
    if enc.get("delta"):
        if base is None:
            raise DeltaBaseUnavailable(
                f"delta push against round {enc.get('base_round')!r} "
                "but that average is not held here"
            )
        if len(base) != len(leaves):
            raise ValueError(
                f"delta base has {len(base)} leaves; payload has "
                f"{len(leaves)} — mixed layouts"
            )
        leaves = [
            np.asarray(
                np.asarray(leaf, np.float32)
                + np.asarray(b, np.float32),
                np.float32,
            )
            if np.issubdtype(np.asarray(leaf).dtype, np.floating)
            else leaf
            for leaf, b in zip(leaves, base)
        ]
    return leaves
