"""Socket transport for the elastic gang: framed RPC, no shared disk.

The file exchange (``exchange.py``) is SparkNet's design point — workers
and coordinator rendezvous through a shared filesystem. This module is
the DeepSpark-shaped upgrade (PAPERS.md, arXiv:1602.08191): a
lightweight coordinator-hosted TCP server carrying the SAME
push/average/rebroadcast contract over length-prefixed, checksummed
frames, so a gang needs a route to one host:port instead of an NFS
mount. This is the ONLY module in tpuflow allowed to touch the raw
``socket`` API outside the serve stack (lint rule TPF012 — the TPF008
compat-seam precedent): every other module speaks the backend
interface, never the wire.

Topology and split of labor::

    coordinator process                      worker processes
    ┌─────────────────────────┐              ┌──────────────────┐
    │ GangStore (in-memory)   │   TCP RPC    │ SocketExchange   │
    │   ↑ direct (no socket)  │ <=========== │   TransportClient│
    │ Coordinator             │              │ heartbeat/push/  │
    │ ExchangeServer (thread) │              │ pull as frames   │
    └─────────────────────────┘              └──────────────────┘

- :class:`GangStore` — the gang's state (heartbeats, pushes, averages,
  offsets) in memory, same semantics as the file layout (sticky
  goodbyes, atomic publishes, prune). The coordinator co-hosts it and
  reads it DIRECTLY — its scans never pay a round trip.
- :class:`ExchangeServer` — a threaded TCP server exposing the
  worker-side ops over the wire. Heartbeat records are stamped with the
  SERVER's clock at arrival: liveness is a transport-level observation,
  so a partitioned worker goes stale even while it beats into the void.
- :class:`SocketExchange` — the worker-side backend: the same interface
  ``FileExchange`` implements, carried by :class:`TransportClient`.

Wire format (one request/response pair per connection)::

    magic "TPFX" | u32 header_len | u64 payload_len | u32 payload_crc32
    | header JSON | payload bytes

The payload is the checksummed npz encoding ``exchange.encode_leaves``
produces — the SAME bytes the file backend writes — so a truncated read
fails the frame CRC first and the npz CRC second, and never reaches the
averaging math.

Resilience wiring: every client request runs under
``resilience/retry.py``'s ``io_policy`` (transient ``ECONNREFUSED`` /
``EPIPE`` / timeouts cost backoff sleeps, not the attempt), and three
fault sites make network chaos one line to inject —
``elastic.transport.send`` (drop/delay a request; index = round for
pushes), ``elastic.transport.recv`` (lose a response), and
``elastic.transport.partition`` (fired at connect; arm with ``p=1`` to
partition, disarm to heal). A worker whose requests exhaust the retry
deadline degrades to local training and resyncs on reconnect
(``worker.py``), it does not die.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time

import numpy as np

from tpuflow.elastic import exchange
from tpuflow.elastic.membership import (
    STATUSES,
    TERMINAL_STATUSES,
    Member,
)
from tpuflow.resilience import fault_point
from tpuflow.resilience.retry import io_policy, retry_call
from tpuflow.utils.env import env_num

MAGIC = b"TPFX"
_PREFIX = struct.Struct(">4sIQI")  # magic, header_len, payload_len, crc32
# A frame header is a small JSON dict; anything bigger is garbage or an
# attack, and a bounded reader fails fast instead of allocating it.
MAX_HEADER = 1 << 20


class TransportError(ConnectionError):
    """A protocol-level failure (bad magic, short read, frame checksum
    mismatch). Subclasses ``ConnectionError`` so the shared io_policy
    treats it exactly like the transient socket errors it rides with."""


def parse_addr(addr: str) -> tuple[str, int]:
    """``"host:port"`` -> ``(host, port)``, fail-loud on malformed."""
    host, sep, port = str(addr).rpartition(":")
    if not sep or not host or not port.isdigit():
        raise ValueError(
            f"elastic transport addr must be 'host:port', got {addr!r}"
        )
    return host, int(port)


def connect_timeout() -> float:
    """The per-connection socket timeout, env-tunable
    (``TPUFLOW_ELASTIC_CONNECT_TIMEOUT``, seconds; validated at read
    time like every TPUFLOW_* knob)."""
    return env_num(
        "TPUFLOW_ELASTIC_CONNECT_TIMEOUT", 5.0, float, minimum=0.001,
        form="a positive number of seconds",
    )


# ---------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise TransportError(
                f"connection closed mid-frame ({got}/{n} bytes)"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_frame(
    sock: socket.socket, header: dict, payload: bytes = b""
) -> None:
    """Write one framed message (see module docstring for the layout)."""
    import zlib

    hdr = json.dumps(header, separators=(",", ":")).encode()
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    sock.sendall(
        _PREFIX.pack(MAGIC, len(hdr), len(payload), crc) + hdr + payload
    )


def recv_frame(sock: socket.socket) -> tuple[dict, bytes]:
    """Read one framed message; raises :class:`TransportError` on torn,
    alien, or checksum-failing frames — corruption is DETECTED here,
    never handed to ``np.load``."""
    import zlib

    prefix = _recv_exact(sock, _PREFIX.size)
    magic, hlen, plen, crc = _PREFIX.unpack(prefix)
    if magic != MAGIC:
        raise TransportError(f"bad frame magic {magic!r}")
    if hlen > MAX_HEADER:
        raise TransportError(f"frame header too large ({hlen} bytes)")
    try:
        header = json.loads(_recv_exact(sock, hlen).decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise TransportError(f"unparseable frame header: {e}") from None
    payload = _recv_exact(sock, plen) if plen else b""
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise TransportError(
            "frame payload checksum mismatch (truncated or corrupted "
            "in flight)"
        )
    return header, payload


# ---------------------------------------------------------------------
# the coordinator-side store (same semantics as the file layout)
# ---------------------------------------------------------------------


class GangStore:
    """In-memory gang state with the file layout's semantics: sticky
    terminal goodbyes, publish-then-repoint averages, staleness-horizon
    prune. Thread-safe (the server's handler threads and the
    coordinator's scan share it); ``clock`` is injectable so liveness
    drills run wall-clock-free.

    ``keep_rounds`` bounds the store's own memory the way the file
    backend's prune bounds disk: every publish drops pushes and
    averages older than ``latest - keep_rounds``, whether or not the
    coordinator ever calls :meth:`prune` (the async path and the
    aggregator tier both publish without driving the coordinator's
    live-member-aware prune on every round). 0 disables the bound.

    A push record carries a ``weight`` and a ``covers`` set — a
    mid-tier aggregator's partial average arrives as ONE push whose
    weight is its subtree's fold weight and whose covers list the
    worker ids folded into it (``aggregator.py``); a plain worker push
    is the degenerate record (weight 1, covers = itself). Weighted
    re-averaging of partial averages reproduces the flat mean exactly
    (the weighted-mean math is associative)."""

    def __init__(self, clock=time.time, keep_rounds: int = 64):
        self.clock = clock
        self.keep_rounds = int(keep_rounds)
        self._lock = threading.Lock()
        self._members: dict[int, dict] = {}
        self._goodbyes: dict[int, str] = {}
        # round key -> {pusher_id: {"leaves", "weight", "covers"}}
        self._pushes: dict = {}
        self._averages: dict[int, list[np.ndarray]] = {}
        self._latest: int | None = None
        self._offsets: dict[int, int] = {}
        # wid -> the worker's run trace id (off the TPFX frame headers):
        # coordinator-side spans (averaging rounds, staleness
        # rejections) name the pushing worker's trace, so the fleet
        # timeline links a push to the average it landed in.
        self._traces: dict[int, str] = {}

    # --- trace propagation (TPFX header -> coordinator spans) ---

    def note_trace(self, worker_id: int, trace_id) -> None:
        from tpuflow.obs.tracing import clean_trace_id

        tid = clean_trace_id(trace_id)
        if tid is not None:
            with self._lock:
                self._traces[int(worker_id)] = tid

    def worker_traces(self) -> dict[int, str]:
        with self._lock:
            return dict(self._traces)

    # --- membership (server-stamped arrival times) ---

    def write_heartbeat(
        self, worker_id: int, *, epoch: int = 0, round: int = 0,
        status: str = "running", clock=None,
    ) -> bool:
        """Record a heartbeat at the STORE's clock (the coordinator-side
        arrival time — transport-level liveness). The ``clock`` kwarg is
        accepted for interface parity with FileExchange and ignored:
        trusting a sender-side timestamp would let a worker with a
        skewed clock dodge eviction."""
        if status not in STATUSES:
            raise ValueError(
                f"unknown heartbeat status {status!r}; valid: {STATUSES}"
            )
        wid = int(worker_id)
        with self._lock:
            if status == "joining":
                self._goodbyes.pop(wid, None)
            elif (
                status not in TERMINAL_STATUSES
                and wid in self._goodbyes
            ):
                return False  # the goodbye stands; never beat over it
            self._members[wid] = {
                "worker_id": wid,
                "time": self.clock(),
                "epoch": int(epoch),
                "round": int(round),
                "status": status,
            }
            if status in TERMINAL_STATUSES:
                self._goodbyes[wid] = status
        return True

    def read_members(self) -> list[Member]:
        with self._lock:
            out = []
            for wid, rec in sorted(self._members.items()):
                status = rec["status"]
                if status not in TERMINAL_STATUSES:
                    status = self._goodbyes.get(wid, status)
                out.append(Member(
                    worker_id=wid, time=rec["time"],
                    epoch=rec["epoch"], round=rec["round"],
                    status=status,
                ))
            return out

    # --- params ---

    def push(self, round, worker_id: int, params) -> None:
        self.push_leaves(
            round, worker_id, exchange.flatten_params(params)
        )

    def push_leaves(
        self, round, worker_id: int, leaves, *,
        weight: float = 1.0, covers=None,
    ) -> None:
        key = round if round == exchange.FINAL_ROUND else int(round)
        wid = int(worker_id)
        rec = {
            "leaves": leaves,
            "weight": float(weight),
            "covers": (
                (wid,) if covers is None
                else tuple(sorted(int(c) for c in covers))
            ),
        }
        with self._lock:
            self._pushes.setdefault(key, {})[wid] = rec

    def pushed_ids(self, round) -> set[int]:
        """The WORKER ids a round's pushes cover — the union of every
        push record's ``covers``, so the coordinator's waiting-set math
        sees through aggregator partial averages to the workers whose
        params they fold."""
        key = round if round == exchange.FINAL_ROUND else int(round)
        out: set[int] = set()
        with self._lock:
            for rec in self._pushes.get(key, {}).values():
                out.update(rec["covers"])
        return out

    def read_pushes(
        self, round, include: set[int] | None = None
    ) -> list[tuple[int, list[np.ndarray]]]:
        key = round if round == exchange.FINAL_ROUND else int(round)
        with self._lock:
            items = sorted(
                (wid, rec["leaves"])
                for wid, rec in self._pushes.get(key, {}).items()
            )
        if include is not None:
            items = [(w, ls) for w, ls in items if w in include]
        return items

    def read_weighted_pushes(
        self, round
    ) -> list[tuple[int, list[np.ndarray], float, tuple[int, ...]]]:
        """Every push for ``round`` as ``(pusher_id, leaves, weight,
        covers)`` — the fold input the coordinator (and the runner's
        final average) uses so aggregator partials re-average into the
        exact flat mean. Direct pushes whose worker is already covered
        by a partial (a lost-response failover re-send — the aggregator
        stored the push but the reply died, so :class:`FailoverClient`
        re-sent it here) are dropped so no worker is folded twice."""
        key = round if round == exchange.FINAL_ROUND else int(round)
        with self._lock:
            recs = sorted(
                (wid, rec["leaves"], rec["weight"], rec["covers"])
                for wid, rec in self._pushes.get(key, {}).items()
            )
        return exchange.dedupe_weighted_records(recs)

    def _newest_push_rounds_locked(self, min_round: int) -> dict:
        newest: dict[int, int] = {}
        for key, by_wid in self._pushes.items():
            if key == exchange.FINAL_ROUND or key < min_round:
                continue
            for wid in by_wid:
                if newest.get(wid, -1) < key:
                    newest[wid] = key
        return newest

    def latest_push_rounds(
        self, min_round: int
    ) -> list[tuple[int, int]]:
        """Each worker's newest push ROUND (metadata only — the async
        coordinator's every-poll scan; ``final`` pushes never count)."""
        with self._lock:
            newest = self._newest_push_rounds_locked(min_round)
            return [(wid, newest[wid]) for wid in sorted(newest)]

    def latest_pushes(
        self, min_round: int
    ) -> list[tuple[int, int, list[np.ndarray]]]:
        """Each worker's newest push with round >= ``min_round`` — the
        payload scan, paid only when a publication happens."""
        with self._lock:
            newest = self._newest_push_rounds_locked(min_round)
            return [
                (wid, newest[wid],
                 self._pushes[newest[wid]][wid]["leaves"])
                for wid in sorted(newest)
            ]

    def publish(self, round: int, leaves, clock=None) -> None:
        with self._lock:
            self._averages[int(round)] = leaves
            if self._latest is None or round > self._latest:
                self._latest = int(round)
            if self.keep_rounds:
                # The store's own memory bound (file-backend parity):
                # the coordinator's live-member-aware prune is the
                # primary policy, this backstop guarantees the
                # in-memory store cannot grow without bound even when
                # nobody drives prune().
                self._prune_locked(self._latest - self.keep_rounds)

    def read_average(self, round: int):
        with self._lock:
            return self._averages.get(int(round))

    def latest_round(self) -> int | None:
        with self._lock:
            return self._latest

    def latest_average(self):
        with self._lock:
            if self._latest is None:
                return None
            leaves = self._averages.get(self._latest)
            if leaves is None:  # pruned past the pointer (file parity)
                return None
            return self._latest, leaves

    def _prune_locked(self, below: int) -> int:
        removed = 0
        for key in [
            k for k in self._pushes
            if k != exchange.FINAL_ROUND and k < below
        ]:
            del self._pushes[key]
            removed += 1
        for key in [k for k in self._averages if k < below]:
            del self._averages[key]
            removed += 1
        return removed

    def prune(self, below: int) -> int:
        with self._lock:
            return self._prune_locked(below)

    # --- offsets ---

    def set_offset(self, worker_id: int, offset: int) -> None:
        with self._lock:
            self._offsets[int(worker_id)] = int(offset)

    def get_offset(self, worker_id: int) -> tuple[int, bool]:
        with self._lock:
            if int(worker_id) in self._offsets:
                return self._offsets[int(worker_id)], True
            return 0, False


# ---------------------------------------------------------------------
# the server
# ---------------------------------------------------------------------


class _Handler(socketserver.BaseRequestHandler):
    """One request/response pair per connection. Op errors become
    ``{"ok": false, "error": ...}`` responses; framing errors close the
    connection (the client's retry policy owns the rest)."""

    def handle(self):  # noqa: D102
        store: GangStore = self.server.store  # type: ignore[attr-defined]
        try:
            header, payload = recv_frame(self.request)
        except (OSError, TransportError):
            return  # torn request: nothing to answer
        try:
            resp, out_payload = self._dispatch(store, header, payload)
        except Exception as e:  # an op bug must not kill the server
            resp, out_payload = (
                {"ok": False, "error": f"{type(e).__name__}: {e}"}, b""
            )
        try:
            send_frame(self.request, resp, out_payload)
        except OSError:
            pass  # the client is gone; its retry policy re-asks

    @staticmethod
    def _round_key(header):
        r = header.get("round")
        return r if r == exchange.FINAL_ROUND else int(r)

    def _dispatch(self, store, header, payload):
        op = header.get("op")
        if op == "ping":
            return {"ok": True}, b""
        if op == "heartbeat":
            if header.get("trace"):
                store.note_trace(int(header["worker_id"]), header["trace"])
            accepted = store.write_heartbeat(
                int(header["worker_id"]),
                epoch=int(header.get("epoch", 0)),
                round=int(header.get("round", 0)),
                status=str(header.get("status", "running")),
            )
            return {"ok": True, "accepted": bool(accepted)}, b""
        if op == "push":
            if header.get("trace"):
                store.note_trace(int(header["worker_id"]), header["trace"])
            enc = header.get("enc") or {}
            base = None
            if enc.get("delta"):
                base = store.read_average(int(enc["base_round"]))
                if base is None:
                    # Pruned past the sender's base: a structured slow
                    # path, not an error — the sender re-pushes full.
                    return {
                        "ok": True, "stored": False,
                        "reason": (
                            f"delta base round {enc['base_round']} "
                            "not held here"
                        ),
                    }, b""
            from tpuflow.elastic import wire

            store.push_leaves(
                self._round_key(header), int(header["worker_id"]),
                wire.decode_push(enc, payload, base=base),
                weight=float(header.get("weight", 1.0)),
                covers=header.get("covers"),
            )
            return {"ok": True, "stored": True}, b""
        if op == "read_average":
            leaves = store.read_average(int(header["round"]))
            if leaves is None:
                return {"ok": True, "found": False}, b""
            return (
                {"ok": True, "found": True},
                exchange.encode_leaves(leaves),
            )
        if op == "latest_round":
            return {"ok": True, "round": store.latest_round()}, b""
        if op == "latest_average":
            latest = store.latest_average()
            if latest is None:
                return {"ok": True, "found": False}, b""
            round_, leaves = latest
            return (
                {"ok": True, "found": True, "round": round_},
                exchange.encode_leaves(leaves),
            )
        if op == "set_offset":
            store.set_offset(
                int(header["worker_id"]), int(header["offset"])
            )
            return {"ok": True}, b""
        if op == "get_offset":
            offset, found = store.get_offset(int(header["worker_id"]))
            return {"ok": True, "offset": offset, "found": found}, b""
        if op == "members":
            # A wire-side gang-status probe (monitors, ops tooling —
            # the coordinator itself reads the store directly).
            return {"ok": True, "members": [
                {"worker_id": m.worker_id, "time": m.time,
                 "epoch": m.epoch, "round": m.round, "status": m.status}
                for m in store.read_members()
            ]}, b""
        if op == "pushed_ids":
            ids = store.pushed_ids(self._round_key(header))
            return {"ok": True, "ids": sorted(ids)}, b""
        return {"ok": False, "error": f"unknown op {op!r}"}, b""


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class ExchangeServer:
    """The coordinator-hosted exchange endpoint: a threaded TCP server
    over a :class:`GangStore`. ``start()`` binds (port 0 = ephemeral)
    and serves from a daemon thread; ``addr`` is the ``host:port``
    workers dial."""

    def __init__(
        self, store: GangStore | None = None,
        host: str = "127.0.0.1", port: int = 0,
        handler=_Handler,
    ):
        # ``handler`` lets a mid-tier aggregator reuse the whole server
        # scaffold (framing, threading, lifecycle) with its own
        # dispatch; ``store`` is then the aggregator itself.
        self.store = store if store is not None else GangStore()
        self._server = _TCPServer((host, port), handler)
        self._server.store = self.store  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None

    @property
    def addr(self) -> str:
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> "ExchangeServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="tpuflow-elastic-exchange-server", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "ExchangeServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------
# the client + worker-side backend
# ---------------------------------------------------------------------


class TransportClient:
    """One RPC = connect, send a frame, read a frame, close. Stateless
    between calls by design: gang churn means connections are the least
    durable thing in the system, so none are kept. Each request runs
    under the shared transient-I/O retry policy; the three
    ``elastic.transport.*`` fault sites fire inside the attempt, so an
    injected drop/delay/partition exercises the SAME backoff+deadline
    path a real flaky network would."""

    def __init__(self, addr: str, *, timeout: float | None = None):
        from tpuflow.obs import default_registry

        self.host, self.port = parse_addr(addr)
        self.addr = addr
        self.timeout = timeout if timeout is not None else connect_timeout()
        # Client-side payload-byte accounting per op and direction —
        # the measurement behind the tree/delta/bf16 wire-byte claims
        # (benchmarks/bench_elastic_tree.py reads counter deltas).
        self._wire_bytes = default_registry().counter(
            "elastic_wire_bytes_total",
            "TPFX payload bytes sent/received on the client side",
        )

    def request(
        self, op: str, header: dict | None = None,
        payload: bytes = b"", index: int | None = None,
    ) -> tuple[dict, bytes]:
        """Send one op; returns ``(response_header, response_payload)``.
        Raises the last transport error once the retry policy is
        exhausted, or ``RuntimeError`` on an op-level server error."""

        # The caller's bound trace rides every frame header: the
        # coordinator-side store remembers each worker's trace, so
        # averaging-round spans link back to the pushing workers on the
        # merged fleet timeline. Read once per request, outside retries.
        from tpuflow.obs.tracing import current_trace_id

        trace = current_trace_id()

        def attempt():
            fault_point("elastic.transport.partition")
            with socket.create_connection(
                (self.host, self.port), timeout=self.timeout
            ) as sock:
                fault_point("elastic.transport.send", index=index)
                hdr = {"op": op, **(header or {})}
                if trace is not None:
                    hdr.setdefault("trace", trace)
                send_frame(sock, hdr, payload)
                self._wire_bytes.inc(len(payload), op=op, dir="sent")
                fault_point("elastic.transport.recv")
                got = recv_frame(sock)
                self._wire_bytes.inc(len(got[1]), op=op, dir="recv")
                return got

        resp, data = retry_call(io_policy(), attempt)
        if not resp.get("ok"):
            raise RuntimeError(
                f"elastic transport op {op!r} failed at {self.addr}: "
                f"{resp.get('error')}"
            )
        return resp, data


class FailoverClient:
    """A :class:`TransportClient` over an ordered address list: the
    primary (a worker's assigned mid-tier aggregator) first, fallbacks
    (root — or a sibling aggregator) after it. A transport-class
    failure on one address marks it dead for ``retry_after`` seconds
    and the SAME request proceeds against the next — so a killed
    aggregator costs its subtree one retry-policy exhaustion, after
    which every op goes straight to the fallback and the round
    completes with nobody degraded. The dead mark expires: the primary
    is re-probed every ``retry_after`` and the subtree re-parents back
    the moment it answers (the sticky-goodbye machinery upstream never
    notices — heartbeats simply arrive via a different path).

    Op-level server errors (``RuntimeError``) do NOT fail over: the
    peer answered, the request itself was bad. ``clock`` is injectable
    so the death-classification drills run wall-clock-free."""

    def __init__(
        self, addrs, *, timeout: float | None = None,
        retry_after: float = 5.0, clock=time.monotonic,
    ):
        from tpuflow.obs import default_registry

        addrs = list(addrs)
        if not addrs:
            raise ValueError("FailoverClient needs at least one addr")
        self._clients = [
            TransportClient(a, timeout=timeout) for a in addrs
        ]
        self._dead_until = [0.0] * len(self._clients)
        self._dead_lock = threading.Lock()  # heartbeat thread + sync
        # path share the dead marks
        self.retry_after = float(retry_after)
        self.clock = clock
        self._failovers = default_registry().counter(
            "elastic_agg_failovers_total",
            "exchange addresses marked dead and failed over from",
        )

    @property
    def addr(self) -> str:
        return self._clients[0].addr

    def alive_index(self) -> int:
        """The index of the first address not currently marked dead
        (len(addrs) when all are) — the death-classification probe the
        drills and the re-parenting tests read."""
        now = self.clock()
        with self._dead_lock:
            for i, until in enumerate(self._dead_until):
                if until <= now:
                    return i
            return len(self._dead_until)

    def request(
        self, op: str, header: dict | None = None,
        payload: bytes = b"", index: int | None = None,
    ) -> tuple[dict, bytes]:
        now = self.clock()
        with self._dead_lock:
            marks = list(self._dead_until)
        order = [i for i, t in enumerate(marks) if t <= now]
        # Everything marked dead still gets tried LAST: a fully-dark
        # address list must surface the real transport error (the
        # worker's degrade policy owns what happens next), not wedge.
        order += [i for i, t in enumerate(marks) if t > now]
        last_err: BaseException | None = None
        for i in order:
            try:
                return self._clients[i].request(
                    op, header, payload, index=index
                )
            except RuntimeError:
                raise  # the server answered; not a liveness problem
            except (OSError, TransportError) as e:
                last_err = e
                with self._dead_lock:
                    self._dead_until[i] = (
                        self.clock() + self.retry_after
                    )
                self._failovers.inc(addr=self._clients[i].addr)
        assert last_err is not None
        raise last_err


class SocketExchange:
    """The worker-side backend over TCP — the same contract
    ``FileExchange`` implements, minus the coordinator-only scans (the
    coordinator co-hosts the :class:`GangStore` and reads it directly).
    ``network = True`` tells the worker that errors here are a PEER
    problem: degrade to local training and resync on reconnect, never
    die (``worker.py`` owns that policy).

    ``fallbacks`` names failover exchange addresses (tree mode: the
    root server behind the worker's aggregator — see
    :class:`FailoverClient`). ``wire_dtype``/``delta`` select the push
    encoding (``wire.py``); the worker notes each adopted average via
    :meth:`note_adopted` so delta pushes have a base both sides hold."""

    network = True

    def __init__(
        self, addr: str, *, timeout: float | None = None,
        fallbacks=(), wire_dtype: str = "f32", delta: bool = False,
        retry_after: float = 5.0,
    ):
        from tpuflow.elastic import wire

        if wire_dtype not in wire.WIRE_DTYPES:
            raise ValueError(
                f"wire_dtype must be one of {wire.WIRE_DTYPES}, got "
                f"{wire_dtype!r}"
            )
        self.addr = addr
        self.wire_dtype = wire_dtype
        self.delta = bool(delta)
        self._base: tuple[int, list] | None = None  # last adopted avg
        self._client = FailoverClient(
            [addr, *fallbacks], timeout=timeout, retry_after=retry_after,
        )

    # --- params ---

    def note_adopted(self, round: int, leaves) -> None:
        """Remember the average this worker last adopted — the delta
        base for subsequent pushes (one extra host copy of the params;
        only kept when delta encoding is on)."""
        if self.delta:
            self._base = (int(round), list(leaves))

    def push(self, round, worker_id: int, params) -> None:
        from tpuflow.elastic import wire

        index = None if round == exchange.FINAL_ROUND else int(round)
        fault_point("elastic.push", index=index)
        leaves = exchange.flatten_params(params)
        # The final push is the gang's deliverable: always full f32 —
        # quantizing it would quantize the final average itself.
        final = round == exchange.FINAL_ROUND
        base_round, base = (
            self._base if (self.delta and not final and self._base)
            else (None, None)
        )
        enc, payload = wire.encode_push(
            leaves,
            wire_dtype="f32" if final else self.wire_dtype,
            base=base, base_round=base_round,
        )
        header = {"round": round, "worker_id": int(worker_id)}
        if enc:
            header["enc"] = enc
        resp, _ = self._client.request(
            "push", header, payload, index=index
        )
        if not resp.get("stored", True):
            # The receiver pruned past our delta base: re-push full
            # (still bf16-quantized when configured) — slow path, never
            # a lost push.
            enc, payload = wire.encode_push(
                leaves, wire_dtype="f32" if final else self.wire_dtype,
            )
            header = {"round": round, "worker_id": int(worker_id)}
            if enc:
                header["enc"] = enc
            self._client.request("push", header, payload, index=index)

    def read_average(self, round: int):
        resp, data = self._client.request(
            "read_average", {"round": int(round)}
        )
        if not resp.get("found"):
            return None
        return exchange.decode_leaves(data)

    def latest_round(self) -> int | None:
        resp, _ = self._client.request("latest_round")
        round_ = resp.get("round")
        return None if round_ is None else int(round_)

    def latest_average(self):
        resp, data = self._client.request("latest_average")
        if not resp.get("found"):
            return None
        return int(resp["round"]), exchange.decode_leaves(data)

    def pushed_ids(self, round) -> set[int]:
        resp, _ = self._client.request("pushed_ids", {"round": round})
        return set(resp.get("ids", []))

    # --- membership ---

    def write_heartbeat(
        self, worker_id: int, *, epoch: int = 0, round: int = 0,
        status: str = "running", clock=None,
    ) -> bool:
        # The elastic.heartbeat site fires here for drill parity with
        # the file backend (membership.write_heartbeat): arming it
        # silences THIS worker whichever transport carries the beats.
        fault_point("elastic.heartbeat")
        resp, _ = self._client.request("heartbeat", {
            "worker_id": int(worker_id), "epoch": int(epoch),
            "round": int(round), "status": status,
        })
        return bool(resp.get("accepted", True))

    # --- offsets ---

    def set_offset(self, worker_id: int, offset: int) -> None:
        self._client.request(
            "set_offset",
            {"worker_id": int(worker_id), "offset": int(offset)},
        )

    def get_offset(self, worker_id: int) -> tuple[int, bool]:
        resp, _ = self._client.request(
            "get_offset", {"worker_id": int(worker_id)}
        )
        return int(resp.get("offset", 0)), bool(resp.get("found"))

    def read_members(self) -> list[Member]:
        """Wire-side gang status (monitors/ops tooling; the coordinator
        reads its co-hosted store directly)."""
        resp, _ = self._client.request("members")
        return [
            Member(
                worker_id=int(m["worker_id"]), time=float(m["time"]),
                epoch=int(m.get("epoch", 0)),
                round=int(m.get("round", 0)),
                status=str(m.get("status", "running")),
            )
            for m in resp.get("members", [])
        ]

    def ping(self) -> bool:
        self._client.request("ping")
        return True
