"""StoreExchange: the elastic gang over an object store — no renames.

The third transport behind the exchange backend contract
(``FileExchange`` over a shared directory, ``SocketExchange`` over TCP,
and now any ``tpuflow.storage.ObjectStore``): pushes, averages,
heartbeats, goodbye markers, and round offsets become **objects** under
the gang's key namespace, the LATEST average is published by pointer
**promotion** instead of tmp+rename, and payloads ride the exchange's
own checksummed npz encoding (``encode_leaves``/``decode_leaves`` — the
socket transport's format, byte-identical on disk and in a bucket).

``elastic`` blocks select this transport by URI: ``{"dir":
"fake://bucket/gang", ...}`` resolves through
``tpuflow.storage.resolve_store`` (``make_backend``), so a 2-worker
in-process gang can run end to end against ``FakeRemoteStore`` — the
drill that proves the gang's storage contract needs no rename anywhere.

Key layout mirrors the file transport's directory layout one-to-one
(``push/r000007/3.npz``, ``avg/r000007.npz``, ``avg/LATEST``,
``members/3.json``/``.goodbye``/``.offset``), so operators can read a
bucket listing the way they read a gang dir.
"""

from __future__ import annotations

import json
import time

import numpy as np

from tpuflow.elastic import exchange
from tpuflow.elastic.membership import (
    STATUSES,
    TERMINAL_STATUSES,
    Member,
)
from tpuflow.resilience import fault_point
from tpuflow.storage import join_key
from tpuflow.storage.base import ObjectStore


class StoreExchange:
    """The exchange backend contract over an ``ObjectStore``.

    ``network`` stays False: a store op failing is a storage problem to
    fail (and supervise-restart) on, exactly like the file transport —
    the degrade-and-resync path is for lost *peers*, not lost buckets.
    """

    network = False

    def __init__(self, store: ObjectStore, prefix: str = ""):
        self.store = store
        self.prefix = prefix.strip("/")

    def _key(self, *parts: str) -> str:
        return join_key(self.prefix, *parts)

    # --- params ---

    def _push_key(self, round, worker_id: int) -> str:
        return self._key(
            exchange.PUSH_DIR, exchange._round_name(round),
            f"{worker_id}.npz",
        )

    def _avg_key(self, round) -> str:
        return self._key(
            exchange.AVG_DIR, exchange._round_name(round) + ".npz"
        )

    def push(self, round, worker_id: int, params) -> None:
        index = None if round == exchange.FINAL_ROUND else int(round)
        fault_point("elastic.push", index=index)
        self.store.put(
            self._push_key(round, worker_id),
            exchange.encode_leaves(exchange.flatten_params(params)),
        )

    def pushed_ids(self, round) -> set[int]:
        prefix = self._key(
            exchange.PUSH_DIR, exchange._round_name(round)
        ) + "/"
        out = set()
        for key in self.store.list(prefix):
            stem = key[len(prefix):]
            if stem.endswith(".npz") and stem[:-4].isdigit():
                out.add(int(stem[:-4]))
        return out

    def _read_push(self, round, wid: int) -> list[np.ndarray] | None:
        try:
            return exchange.decode_leaves(
                self.store.get(self._push_key(round, wid))
            )
        except (OSError, ValueError, KeyError):
            return None  # put is atomic: unreadable = absent/corrupt

    def read_pushes(
        self, round, include: set[int] | None = None
    ) -> list[tuple[int, list[np.ndarray]]]:
        ids = sorted(self.pushed_ids(round))
        if include is not None:
            ids = [i for i in ids if i in include]
        out = []
        for wid in ids:
            leaves = self._read_push(round, wid)
            if leaves is not None:
                out.append((wid, leaves))
        return out

    def _newest_push_rounds(self, min_round: int) -> dict[int, int]:
        prefix = self._key(exchange.PUSH_DIR) + "/"
        newest: dict[int, int] = {}
        for key in self.store.list(prefix):
            parts = key[len(prefix):].split("/")
            if len(parts) != 2:
                continue
            r = exchange._parse_round(parts[0])
            stem = parts[1]
            if (
                r is None or r < min_round
                or not stem.endswith(".npz") or not stem[:-4].isdigit()
            ):
                continue
            wid = int(stem[:-4])
            if newest.get(wid, -1) < r:
                newest[wid] = r
        return newest

    def latest_push_rounds(self, min_round: int) -> list[tuple[int, int]]:
        newest = self._newest_push_rounds(min_round)
        return [(wid, newest[wid]) for wid in sorted(newest)]

    def latest_pushes(
        self, min_round: int
    ) -> list[tuple[int, int, list[np.ndarray]]]:
        newest = self._newest_push_rounds(min_round)
        out = []
        for wid in sorted(newest):
            leaves = self._read_push(newest[wid], wid)
            if leaves is not None:
                out.append((wid, newest[wid], leaves))
        return out

    def publish(self, round: int, leaves, clock=time.time) -> None:
        """Average first, pointer second: the promotion flip is the
        publication instant, and a crash in between leaves the previous
        LATEST standing — the file transport's write-then-repoint
        ordering, expressed without a rename."""
        self.store.put(self._avg_key(round), exchange.encode_leaves(leaves))
        self.store.promote(
            self._key(exchange.AVG_DIR, exchange.LATEST),
            self._avg_key(round),
            meta={"round": int(round)},
            clock=clock,
        )

    def read_average(self, round: int) -> list[np.ndarray] | None:
        try:
            return exchange.decode_leaves(
                self.store.get(self._avg_key(round))
            )
        except (OSError, ValueError, KeyError):
            return None

    def latest_round(self) -> int | None:
        doc = self.store.resolve(
            self._key(exchange.AVG_DIR, exchange.LATEST)
        )
        if doc is None:
            return None
        try:
            return int(doc["meta"]["round"])
        except (KeyError, TypeError, ValueError):
            return None

    def latest_average(self) -> tuple[int, list[np.ndarray]] | None:
        doc = self.store.resolve(
            self._key(exchange.AVG_DIR, exchange.LATEST)
        )
        if doc is None:
            return None
        try:
            return (
                int(doc["meta"]["round"]),
                exchange.decode_leaves(self.store.get(doc["target"])),
            )
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def prune(self, below: int) -> int:
        removed = 0
        push_prefix = self._key(exchange.PUSH_DIR) + "/"
        pruned_rounds = set()
        for key in self.store.list(push_prefix):
            parts = key[len(push_prefix):].split("/")
            r = exchange._parse_round(parts[0]) if parts else None
            if r is not None and r < below and self.store.delete(key):
                pruned_rounds.add(r)
        removed += len(pruned_rounds)
        avg_prefix = self._key(exchange.AVG_DIR) + "/"
        for key in self.store.list(avg_prefix):
            name = key[len(avg_prefix):]
            if not name.endswith(".npz"):
                continue
            r = exchange._parse_round(name[: -len(".npz")])
            if r is not None and r < below and self.store.delete(key):
                removed += 1
        return removed

    def write_final(self, leaves) -> str:
        """The runner's deliverable: the final cross-worker average as
        ``avg/final.npz`` in the store; returns the key."""
        key = self._key(exchange.AVG_DIR, "final.npz")
        self.store.put(key, exchange.encode_leaves(leaves))
        return key

    # --- membership ---

    def _member_key(self, worker_id: int, ext: str = "json") -> str:
        return self._key("members", f"{worker_id}.{ext}")

    def write_heartbeat(
        self, worker_id: int, *, epoch: int = 0, round: int = 0,
        status: str = "running", clock=time.time,
    ) -> bool:
        """The file transport's sticky-goodbye contract over objects:
        a terminal beat also puts the goodbye marker; once it exists a
        late non-terminal beat is skipped, and only an explicit
        ``joining`` beat (a new incarnation) deletes it."""
        if status not in STATUSES:
            raise ValueError(
                f"unknown heartbeat status {status!r}; valid: {STATUSES}"
            )
        fault_point("elastic.heartbeat")
        marker = self._member_key(worker_id, "goodbye")
        if status == "joining":
            self.store.delete(marker)
        elif status not in TERMINAL_STATUSES and self.store.exists(marker):
            return False  # the goodbye stands; never beat over it
        self.store.put_atomic(
            self._member_key(worker_id),
            json.dumps({
                "worker_id": worker_id,
                "time": clock(),
                "epoch": epoch,
                "round": round,
                "status": status,
                "pid": None,  # threads of one process share a pid
            }).encode("utf-8"),
        )
        if status in TERMINAL_STATUSES:
            self.store.put_atomic(
                marker, json.dumps({"status": status}).encode("utf-8")
            )
        return True

    def read_members(self) -> list[Member]:
        prefix = self._key("members") + "/"
        keys = self.store.list(prefix)
        goodbyes = {k for k in keys if k.endswith(".goodbye")}
        out: list[Member] = []
        for key in keys:
            if not key.endswith(".json"):
                continue
            try:
                rec = json.loads(self.store.get(key).decode("utf-8"))
                if not isinstance(rec, dict):
                    continue
                status = str(rec.get("status", "running"))
                wid = int(rec["worker_id"])
                if (
                    status not in TERMINAL_STATUSES
                    and self._member_key(wid, "goodbye") in goodbyes
                ):
                    try:
                        marker = json.loads(
                            self.store.get(
                                self._member_key(wid, "goodbye")
                            ).decode("utf-8")
                        ).get("status")
                    except (OSError, ValueError, AttributeError):
                        marker = None
                    if marker in TERMINAL_STATUSES:
                        status = marker
                out.append(Member(
                    worker_id=wid,
                    time=float(rec["time"]),
                    epoch=int(rec.get("epoch", 0)),
                    round=int(rec.get("round", 0)),
                    status=status,
                    pid=rec.get("pid"),
                ))
            except (OSError, ValueError, TypeError, KeyError):
                continue  # torn/alien object: the next scan decides
        return out

    # --- the persisted round offset (survives restarts) ---

    def set_offset(self, worker_id: int, offset: int) -> None:
        self.store.put_atomic(
            self._member_key(worker_id, "offset"),
            json.dumps({"round_offset": int(offset)}).encode("utf-8"),
        )

    def get_offset(self, worker_id: int) -> tuple[int, bool]:
        try:
            rec = json.loads(
                self.store.get(
                    self._member_key(worker_id, "offset")
                ).decode("utf-8")
            )
            return int(rec["round_offset"]), True
        except (OSError, ValueError, TypeError, KeyError):
            return 0, False

    def has_state(self) -> bool:
        """True when the namespace already holds a previous gang's
        members/pushes/averages — the runner's stale-gang refusal."""
        for sub in ("members", exchange.PUSH_DIR, exchange.AVG_DIR):
            if self.store.list(self._key(sub) + "/"):
                return True
        return False
