"""The averaging coordinator: collect pushes, evict the dead, rebroadcast.

The driver role from SparkNet/BigDL (PAPERS.md), reduced to its
essentials and hardened for churn: per round it (1) classifies gang
membership against the heartbeat deadline (``membership.py``), (2) waits
— bounded by ``round_timeout`` — for the live set's parameter pushes,
(3) averages whatever arrived and publishes the result, then opens the
next round. Everything is observation over shared files; the coordinator
holds no connection to any worker, so a worker dying at ANY point costs
at most one round-timeout of waiting, after which its stale heartbeat
evicts it and averaging proceeds over the survivors.

Rejoin is symmetric and handshake-free: a restarted worker's fresh
heartbeat readmits it to the live set, and its pushes count again the
moment its round counter catches up with the gang's (historic rounds it
replays resolve instantly against the already-published averages).

Structured as ``step()`` (one scan, non-blocking, returns what changed)
driven by ``run(stop)`` — so tier-1 drills call ``step()`` directly
under a fake clock and never wait on the wall.

State is continuously checkpointed to ``{gang_dir}/coordinator.json``
and, on an abort, dumped to forensics
(``{gang_dir}/forensics-coordinator.jsonl``) alongside the event ring —
the "what was the gang doing?" trail for a dead coordinator.
"""

from __future__ import annotations

import os
import time

from tpuflow.elastic import exchange
from tpuflow.elastic.membership import classify_view

STATE_FILE = "coordinator.json"


class Coordinator:
    """See the module docstring. ``clock``/``sleep`` are injectable for
    zero-wall-clock drills; metrics go to the process-wide registry
    (``elastic_workers`` gauge, eviction/rejoin/round counters,
    ``elastic.round`` spans).

    Concurrency contract (the TPF016 pass's terms): this class holds NO
    locks by design — every mutable attribute is owned by the one
    thread driving ``run()``/``step()``; cross-thread communication
    happens through the exchange backend (``GangStore`` is internally
    locked) and the ``stop`` event. The single sanctioned cross-thread
    read is ``state()`` on a wedged-join diagnosis (the runner's
    timeout path), which snapshots each container before iterating so
    a concurrent ``step()`` can never tear it mid-iteration. Keep it
    this way: adding a lock for one field would put every attribute
    under guarded-access inference, and the right fix for new shared
    state is the backend, not a coordinator lock."""

    def __init__(
        self,
        gang_dir: str,
        *,
        heartbeat_timeout: float = 30.0,
        heartbeat_interval: float = 0.25,
        round_timeout: float = 60.0,
        poll_interval: float | None = None,
        min_round_interval: float = 0.0,
        min_round: int = 1,
        keep_rounds: int = 16,
        expected_workers: int = 0,
        assembly_timeout: float = 60.0,
        backend=None,
        async_push: bool = False,
        max_staleness: int = 2,
        clock=time.time,
        sleep=time.sleep,
        verbose: bool = False,
        trail_path: str | None = "auto",
    ):
        from tpuflow.obs import default_registry

        self.gang_dir = gang_dir
        # The exchange backend: FileExchange over gang_dir by default;
        # in socket mode the runner passes the server's GangStore — the
        # coordinator co-hosts it and scans in memory, no round trips.
        self.backend = (
            backend if backend is not None
            else exchange.FileExchange(gang_dir)
        )
        # DeepSpark-style async publication: fold each worker's newest
        # push, down-weighted by its distance behind the push frontier,
        # rejecting anything more than max_staleness rounds behind; no
        # waiting set, so one straggler never stalls a round.
        self.async_push = bool(async_push)
        self.max_staleness = int(max_staleness)
        self._frontier = 0  # newest worker push round folded so far
        self._consumed: dict[int, int] = {}  # wid -> newest folded round
        self._stale_rejected: dict[int, int] = {}  # wid -> newest
        # rejected round (so one stale push counts one rejection)
        self.heartbeat_timeout = heartbeat_timeout
        self.round_timeout = round_timeout
        # Poll cadence derives from the gang's heartbeat cadence unless
        # pinned: a fixed fast default would hammer NFS-class gang dirs
        # with metadata scans a slow-beating production gang never needs
        # (drills stay wall-clock-free via the injectable clock/sleep).
        from tpuflow.elastic import derive_poll_interval

        self.poll_interval = (
            derive_poll_interval(heartbeat_interval)
            if poll_interval is None
            else poll_interval
        )
        # Floor on the publication cadence (0 = as fast as pushes
        # arrive). A paced gang gives a briefly-absent worker rounds to
        # rejoin INTO instead of a fait accompli — and gives churn
        # drills a deterministic window to observe eviction + rejoin.
        self.min_round_interval = min_round_interval
        self._last_publish: float | None = None
        # Disk bound: after each publication, push dirs and averages
        # for rounds older than BOTH keep_rounds and the slowest live
        # member's round are pruned — a long gang must not write one
        # param copy per worker per round forever. 0 disables pruning.
        self.keep_rounds = keep_rounds
        # How many workers the gang was launched with (0 = unknown):
        # all_finished() must not declare a natural end before every
        # expected worker has even been SEEN — a fast first worker
        # finishing its tiny job before slower siblings' first
        # heartbeat would otherwise end the coordinator under them.
        self.expected_workers = expected_workers
        # The assembly gate must itself be deadline-bounded (the TPF007
        # discipline): a worker that permanently fails before its first
        # heartbeat must cost one assembly window, not disable
        # averaging for the whole run.
        self.assembly_timeout = assembly_timeout
        self._first_step: float | None = None
        self.clock = clock
        self.sleep = sleep
        self.verbose = verbose
        self.round = min_round  # the round currently being collected
        self.evicted: set[int] = set()
        self.rejoins = 0
        self.rounds: dict[int, list[int]] = {}  # round -> ids averaged
        self.ever_seen: set[int] = set()
        self._round_opened: float | None = None  # first push observed at
        self._last_view = None  # step()'s scan, reused by run()
        reg = default_registry()
        self._workers_gauge = reg.gauge(
            "elastic_workers", "live elastic workers at the last scan"
        )
        self._evictions = reg.counter(
            "elastic_evictions_total",
            "workers evicted on a stale heartbeat deadline",
        )
        self._rejoins = reg.counter(
            "elastic_rejoins_total",
            "evicted workers readmitted by a fresh heartbeat",
        )
        self._rounds = reg.counter(
            "elastic_rounds_total", "averaging rounds published"
        )
        self._stale = reg.counter(
            "elastic_stale_pushes_total",
            "async pushes rejected for exceeding the staleness bound",
        )
        os.makedirs(gang_dir, exist_ok=True)
        # The coordinator's on-disk trail (the fleet-timeline lane for
        # this process): every averaging-round span and membership event
        # is appended as JSONL next to the gang files, so `python -m
        # tpuflow.obs fleet <storage>` can merge the coordinator's view
        # with the workers' metrics trails — the ring alone dies with
        # the process unless something crashes. "auto" = the default
        # path under gang_dir; None disables.
        self._mlog = None
        if trail_path is not None:
            from tpuflow.utils.logging import MetricsLogger

            if trail_path == "auto":
                trail_path = os.path.join(
                    gang_dir, "coordinator-metrics.jsonl"
                )
            self._mlog = MetricsLogger(trail_path)

    def _event(self, name: str, **fields) -> None:
        """One membership/round event: the forensics ring always, the
        on-disk trail when one is configured."""
        from tpuflow.obs import record_event

        rec = record_event(name, **fields)
        if self._mlog is not None:
            self._mlog.write(
                name,
                **{k: v for k, v in rec.items() if k not in ("event", "time")},
            )

    def _traces_for(self, worker_ids) -> dict | None:
        """{wid: trace_id} for the workers folded into a publication —
        the cross-process link from a coordinator-side round span back
        to each pushing worker's run trace. The socket transport's
        GangStore learns traces from TPFX frame headers; backends
        without the surface (the file reference implementation) yield
        None (the span simply omits the field)."""
        traces_fn = getattr(self.backend, "worker_traces", None)
        if traces_fn is None:
            return None
        try:
            known = traces_fn()
        except Exception:
            return None
        out = {
            str(wid): known[wid] for wid in worker_ids if wid in known
        }
        return out or None

    # ---- one scan ----

    def step(self) -> bool:
        """One non-blocking scan: update membership accounting, publish
        the current round if it is ready (live set covered, or the round
        deadline expired with at least one push). Returns True when a
        round was published."""
        from tpuflow.obs import record_span

        now = self.clock()
        if self._first_step is None:
            self._first_step = now
        view = classify_view(
            self.backend.read_members(), self.heartbeat_timeout, now
        )
        self._last_view = view  # reused by run()'s end-of-gang check
        self.ever_seen |= view.live_ids | view.stale_ids
        self.ever_seen |= {m.worker_id for m in view.finished}
        changed = False
        for wid in sorted(view.stale_ids - self.evicted):
            self.evicted.add(wid)
            self._evictions.inc()
            self._event(
                "elastic_worker_evicted", worker_id=wid, round=self.round,
            )
            changed = True
            if self.verbose:
                print(
                    f"elastic: evicted worker {wid} (heartbeat older "
                    f"than {self.heartbeat_timeout:g}s) at round "
                    f"{self.round}"
                )
        for wid in sorted(view.live_ids & self.evicted):
            self.evicted.discard(wid)
            self.rejoins += 1
            self._rejoins.inc()
            self._event(
                "elastic_worker_rejoined", worker_id=wid, round=self.round,
            )
            changed = True
            if self.verbose:
                print(f"elastic: worker {wid} rejoined at round {self.round}")
        self._workers_gauge.set(len(view.live))

        if self.async_push:
            published = self._step_async(view, now, record_span)
            if published or changed:
                self._write_state(now)
            return published

        pushed = self.backend.pushed_ids(self.round)
        published = False
        if pushed:
            if self._round_opened is None:
                self._round_opened = now
            # Wait only for live RUNNING workers AT this round:
            # "joining" members are warm-starting (not pushing rounds
            # yet), finished members said goodbye, evicted members are
            # exactly who this deadline exists to stop waiting for —
            # and a rejoined catch-up worker (reported round lagging
            # the gang's) only ADOPTS history, so waiting on it would
            # collapse cadence to round_timeout per round until it
            # caught up. A healthy member reports round or round-1
            # (mid-epoch), so lag of one is still waited on; anything
            # older is catching up. An EMPTY waiting set publishes
            # immediately: nobody current is expected to push more.
            waiting = {
                m.worker_id
                for m in view.live
                if m.status == "running" and m.round >= self.round - 1
            }
            deadline_passed = now - self._round_opened > self.round_timeout
            paced = (
                self._last_publish is None
                or now - self._last_publish >= self.min_round_interval
            )
            # Launch stagger: a fast worker can push round 1 before its
            # siblings' first heartbeat even lands (they are invisible
            # to the waiting set) — hold publication until every
            # expected worker has been SEEN at least once, or early
            # rounds average over a subset of a perfectly healthy gang.
            # Bounded by assembly_timeout: a worker that never shows up
            # must not disable averaging forever.
            assembled = (
                len(self.ever_seen) >= self.expected_workers
                or now - self._first_step > self.assembly_timeout
            )
            if (
                paced and assembled
                and (waiting <= pushed or deadline_passed)
            ):
                published = self._publish(now, record_span)
        if published and self.keep_rounds:
            # Prune only behind the slowest LIVE member: a lagging
            # catch-up worker's historic rounds stay readable; an
            # evicted worker that returns needing even older ones
            # skips them (worker-side latest_round check).
            min_live = min(
                (m.round for m in view.live), default=self.round
            )
            below = min(min_live, self.round - self.keep_rounds)
            if below > 0:
                self.backend.prune(below)
        if changed or published:
            self._write_state(now)
        return published

    def _publish(self, now: float, record_span) -> bool:
        # Average EVERY readable push for the round — including one from
        # a worker that pushed and then died: its params are legitimate
        # round data; eviction only stops the *waiting*.
        reader = getattr(self.backend, "read_weighted_pushes", None)
        if reader is not None:
            # Tree mode (aggregator.py): a pusher may be a mid-tier
            # aggregator whose record carries its subtree's total
            # weight and the worker ids it covers. The weighted
            # re-average of partials IS the flat mean (the fold is
            # associative), and `used` must name WORKERS, not
            # aggregator ids, for spans/summaries/waiting-set parity.
            recs = reader(self.round)
            leaves, used_pushers = exchange.average_leaf_sets(
                [(wid, ls) for wid, ls, _w, _c in recs],
                weights=[w for _, _, w, _ in recs],
                context=f"for round {self.round} ",
            )
            if leaves is not None:
                pushers = set(used_pushers)
                used = sorted({
                    c
                    for wid, _ls, _w, cov in recs
                    if wid in pushers
                    for c in cov
                })
        else:
            leaves, used = exchange.average_leaf_sets(
                self.backend.read_pushes(self.round),
                context=f"for round {self.round} ",
            )
        if leaves is None:
            return False
        self.backend.publish(self.round, leaves, clock=self.clock)
        opened = self._round_opened if self._round_opened is not None else now
        record_span(
            "elastic.round", max(now - opened, 0.0), logger=self._mlog,
            round=self.round, workers=len(used), worker_ids=used,
            worker_traces=self._traces_for(used),
        )
        self.rounds[self.round] = used
        # The mirrored per-round membership is a diagnostic window, not
        # an archive: unbounded it would grow one entry per round and
        # make every state-file rewrite O(rounds) — quadratic
        # cumulative I/O over a long gang.
        cap = max(self.keep_rounds * 4, 64) if self.keep_rounds else 0
        while cap and len(self.rounds) > cap:
            del self.rounds[min(self.rounds)]
        self._rounds.inc()
        if self.verbose:
            print(
                f"elastic: published round {self.round} averaged over "
                f"workers {used}"
            )
        self.round += 1
        self._round_opened = None
        self._last_publish = now
        return True

    # ---- the async (DeepSpark-style) publish path ----

    def _step_async(self, view, now: float, record_span) -> bool:
        """One async scan: fold each worker's newest push into a
        staleness-weighted average and publish it, with no waiting set.

        There is ONE round numbering space — worker push rounds — and
        the average is published AT the **push frontier** (the newest
        round any worker has pushed, i.e. the gang's actual progress),
        re-published in place when a slower worker's push lands at the
        same frontier. A separate publish counter would race ahead of
        worker epochs and poison every consumer of round numbers: a
        late joiner warm-starting its offset from ``latest_round``
        would inflate the frontier and get the whole gang's pushes
        staleness-rejected, and pruning computed in one space would
        never reach keys in the other.

        Staleness of a push is its distance behind the frontier:
        ``s`` rounds behind is down-weighted by ``1/(1+s)`` and
        rejected outright past ``max_staleness`` (counted once per
        rejected push in ``elastic_stale_pushes_total``). Publication
        happens whenever at least one within-bound push is NEW since
        the last publish — a straggler neither stalls the round
        (nobody waits on it) nor poisons the average (its stale params
        fade, then fall off the bound). Round-number metadata is
        scanned cheaply every poll; full param payloads are read only
        when a publication is actually happening."""
        rounds = self.backend.latest_push_rounds(0)
        if not rounds:
            return False
        frontier = max(self._frontier, max(r for _, r in rounds))
        horizon = frontier - self.max_staleness
        eligible_rounds: list[tuple[int, int]] = []
        for wid, r in rounds:
            if r < horizon:
                if self._stale_rejected.get(wid, -1) < r:
                    self._stale_rejected[wid] = r
                    self._stale.inc()
                    self._event(
                        "elastic_stale_push_rejected", worker_id=wid,
                        push_round=r, frontier=frontier,
                        staleness=frontier - r,
                        worker_trace=(self._traces_for([wid]) or {}).get(
                            str(wid)
                        ),
                    )
                    if self.verbose:
                        print(
                            f"elastic: rejected worker {wid}'s push for "
                            f"round {r} (staleness {frontier - r} > "
                            f"bound {self.max_staleness})"
                        )
                continue
            eligible_rounds.append((wid, r))
        fresh = any(
            r > self._consumed.get(wid, -1) for wid, r in eligible_rounds
        )
        paced = (
            self._last_publish is None
            or now - self._last_publish >= self.min_round_interval
        )
        assembled = (
            len(self.ever_seen) >= self.expected_workers
            or now - self._first_step > self.assembly_timeout
        )
        if not (eligible_rounds and fresh and paced and assembled):
            return False
        # Payloads only now — and only within the staleness horizon.
        pushes = self.backend.latest_pushes(max(horizon, 0))
        if not pushes:
            return False
        # A push may have landed between the two scans; fold it in
        # (the frontier only ever advances).
        frontier = max(frontier, max(r for _, r, _ in pushes))
        leaves, used = exchange.average_leaf_sets(
            [(wid, ls) for wid, _, ls in pushes],
            weights=[
                1.0 / (1.0 + (frontier - r)) for _, r, _ in pushes
            ],
            context=f"(async, frontier {frontier}) ",
        )
        if leaves is None:
            return False
        self.backend.publish(frontier, leaves, clock=self.clock)
        record_span(
            "elastic.round", 0.0, logger=self._mlog,
            round=frontier, workers=len(used), worker_ids=used,
            worker_traces=self._traces_for(used),
            mode="async",
        )
        self.rounds[frontier] = used
        cap = max(self.keep_rounds * 4, 64) if self.keep_rounds else 0
        while cap and len(self.rounds) > cap:
            del self.rounds[min(self.rounds)]
        self._rounds.inc()
        if self.verbose:
            print(
                f"elastic: published async round {frontier} "
                f"averaged over workers {used}"
            )
        for wid, r, _ in pushes:
            self._consumed[wid] = max(self._consumed.get(wid, -1), r)
        self._frontier = frontier
        # ``round`` keeps its sync-mode meaning — "the round currently
        # being collected" — so summaries/state read the same way in
        # both modes.
        self.round = frontier + 1
        self._last_publish = now
        if self.keep_rounds:
            min_live = min(
                (m.round for m in view.live), default=frontier
            )
            below = min(
                min_live,
                frontier - max(self.max_staleness, self.keep_rounds),
            )
            if below > 0:
                self.backend.prune(below)
        return True

    # ---- lifecycle ----

    def all_finished(self, view=None) -> bool:
        """True once every worker ever seen has said ``done`` — the
        natural end of a gang. A ``failed`` goodbye is deliberately NOT
        terminal here: under the supervised runner the worker's restart
        loop may be mid-backoff, and a coordinator that exited on the
        failure would leave the resurrected worker pushing into a void
        (blocking ``pull_timeout`` per round for averages that never
        come). Permanently-failed gangs are ended by ``run``'s stop
        event — the runner, which watches the worker threads, owns that
        decision. ``view`` reuses a scan the caller already did;
        without it the membership dir is re-read."""
        if view is None:
            view = classify_view(
                self.backend.read_members(), self.heartbeat_timeout,
                self.clock(),
            )
        if len(self.ever_seen) < self.expected_workers:
            return False  # launched workers haven't all checked in yet
        done = {
            m.worker_id for m in view.finished if m.status == "done"
        }
        return bool(self.ever_seen) and self.ever_seen <= done

    def run(self, stop=None) -> dict:
        """Drive ``step()`` until ``stop`` is set or every worker has
        finished. On an unexpected abort the coordinator state and the
        recent-event ring are dumped next to the gang files before the
        error propagates."""
        from tpuflow.obs import dump_forensics

        try:
            while stop is None or not stop.is_set():
                self.step()
                if self.all_finished(self._last_view):
                    break
                self.sleep(self.poll_interval)
            self._write_state(self.clock())
            return self.state()
        except BaseException as e:
            self._event(
                "elastic_coordinator_abort",
                round=self.round,
                error=f"{type(e).__name__}: {e}",
            )
            try:
                self._write_state(self.clock())
            except OSError:
                pass
            dump_forensics(
                os.path.join(self.gang_dir, "forensics-coordinator.jsonl"),
                reason=f"elastic coordinator aborted at round {self.round}",
            )
            raise
        finally:
            if self._mlog is not None:
                self._mlog.close()

    # ---- state ----

    def state(self) -> dict:
        # Snapshot each container BEFORE iterating: the runner reads
        # state() cross-thread when coord_thread.join() times out (the
        # wedged-coordinator diagnosis), and sorting a dict view the
        # coordinator thread is concurrently publishing into could die
        # mid-iteration — masking the wedge this summary exists to
        # report. A C-level copy of builtin containers is atomic under
        # the GIL; snapshot-granularity staleness is fine for a
        # diagnostic read.
        rounds = dict(self.rounds)
        evicted = set(self.evicted)
        ever_seen = set(self.ever_seen)
        return {
            "round": self.round,
            "evicted": sorted(evicted),
            "rejoins": self.rejoins,
            "rounds": {str(r): ids for r, ids in sorted(rounds.items())},
            "ever_seen": sorted(ever_seen),
        }

    def _write_state(self, now: float) -> None:
        from tpuflow.utils.paths import atomic_write_json

        try:
            atomic_write_json(
                os.path.join(self.gang_dir, STATE_FILE),
                {**self.state(), "time": now},
            )
        except OSError:
            pass  # state mirroring is observability, never the run


def read_coordinator_state(gang_dir: str) -> dict | None:
    from tpuflow.storage import read_json

    try:
        return read_json(os.path.join(gang_dir, STATE_FILE))
    except (OSError, ValueError):
        return None
