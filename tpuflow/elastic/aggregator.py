"""Mid-tier gang aggregators: tree fan-in over the socket transport.

The star-hub exchange (``transport.py``) makes the coordinator's server
touch every worker's full parameter payload every round — wire bytes
and fold work at the root scale with gang size, which caps the gang in
the tens (ROADMAP item 4; DeepSpark's parameter-server fan-in and
SparkNet's round-trip-amortized driver are the lineage, PAPERS.md).
This module puts a tier (or several) of aggregators between the
workers and the root::

                         root ExchangeServer + Coordinator
                        /                                \\
              aggregator A                         aggregator B
             /     |     \\                        /     |     \\
           w0     w1     w2                      w3     w4     w5

Each :class:`Aggregator` speaks the SAME ``TPFX`` framed protocol on
both sides — downstream it *is* an exchange server (workers dial it
exactly as they would dial the root), upstream it is a client of its
parent (another aggregator, or the root). Per round it:

- **folds** its subtree's pushes with the weighted
  ``exchange.average_leaf_sets`` math (decode + fold in f32, whatever
  the wire dtype — masters stay f32 at every tier) and forwards ONE
  partial-average push upstream carrying the subtree's total weight
  and the worker ids it covers. Weighted means compose associatively,
  so the root's re-average of partials is exactly the flat mean — and
  the root's ingress bytes and fold count scale with ITS fan-out, not
  with gang size;
- **serves** its subtree's average reads from a local cache (one
  upstream fetch per round amortized over the whole subtree — root
  egress drops by the same fan-out factor), with a short negative-TTL
  so a not-yet-published round costs the root at most one probe per
  TTL instead of one per polling worker;
- **relays** everything else (heartbeats, offsets, membership probes)
  verbatim — liveness stays a transport-level observation stamped at
  the root, and the sticky-goodbye/rejoin machinery is unchanged.

Failure is asymmetric by design. An aggregator holds NO durable state
— every cache entry is reconstructable from the root — so a killed
mid-tier node is healed entirely by its subtree's
:class:`~tpuflow.elastic.transport.FailoverClient`: the workers mark it
dead, re-parent to the fallback (root or a sibling), and the round
completes over the survivors with no round lost and nobody degraded;
when the address answers again the subtree re-parents back. The
upstream direction retries with deferral and drops a round's partial
only after a bounded number of failed forwards (the workers then see
the round miss — the same observation a slow coordinator produces).

``plan_tree`` lays out the tiers; the runner (``runner.py``) starts
the aggregators, points each worker at its leaf aggregator with the
root as fallback, and stops them leaf-tier-first so final pushes flush
upward. Fan-out knobs ride ``TPUFLOW_ELASTIC_FANOUT`` /
``TPUFLOW_ELASTIC_TIER`` (validated reads, ``utils/env.py``).
"""

from __future__ import annotations

import sys
import threading
import time
from dataclasses import dataclass, replace

from tpuflow.elastic import exchange, wire
from tpuflow.elastic.transport import (
    ExchangeServer,
    TransportClient,
    _Handler,
)
from tpuflow.utils.env import env_num

# Aggregator ids live far above any plausible worker id: they appear as
# pusher ids on the wire and in fold diagnostics, and must never
# collide with (or be mistaken for) gang worker ids.
AGG_ID_BASE = 1_000_000


def default_fanout() -> int:
    """The tree fan-out when the caller leaves it unset (0 = star hub,
    no aggregator tier) — ``TPUFLOW_ELASTIC_FANOUT``, validated at
    read time like every TPUFLOW_* knob."""
    return env_num(
        "TPUFLOW_ELASTIC_FANOUT", 0, int, minimum=0,
        form="an integer subtree fan-out >= 0 (0 = star, >= 2 = tree)",
    )


def default_tiers() -> int:
    """Aggregator tier count when unset — ``TPUFLOW_ELASTIC_TIER``."""
    return env_num(
        "TPUFLOW_ELASTIC_TIER", 1, int, minimum=1,
        form="an integer aggregator tier count >= 1",
    )


@dataclass(frozen=True)
class AggNode:
    """One planned aggregator: its id, tier (1 = leaf tier, workers
    below), the ids it folds (worker ids at tier 1, child aggregator
    ids above), and its parent aggregator id (None = the root)."""

    agg_id: int
    tier: int
    children: tuple[int, ...]
    parent: int | None


def plan_tree(
    n_workers: int, fanout: int, tiers: int = 1
) -> list[list[AggNode]]:
    """Lay out the aggregation tree: ``tiers`` levels of aggregators,
    each folding at most ``fanout`` nodes of the level below. Returns
    tiers bottom-up (``[0]`` is the leaf tier). Levels that would hold
    a single node stop the stacking early — an aggregator chain above
    one aggregator adds latency and nothing else."""
    if fanout < 2:
        raise ValueError(
            f"tree aggregation needs fanout >= 2 (0 = star hub), got "
            f"{fanout}"
        )
    if tiers < 1:
        raise ValueError(f"tiers must be >= 1, got {tiers}")
    if n_workers < 1:
        raise ValueError(f"n_workers must be >= 1, got {n_workers}")
    levels: list[list[AggNode]] = []
    below = list(range(n_workers))
    for tier in range(1, tiers + 1):
        if len(below) <= 1:
            break
        nodes = [
            AggNode(
                agg_id=AGG_ID_BASE + tier * 10_000 + g,
                tier=tier,
                children=tuple(below[g * fanout:(g + 1) * fanout]),
                parent=None,
            )
            for g in range((len(below) + fanout - 1) // fanout)
        ]
        levels.append(nodes)
        below = [n.agg_id for n in nodes]
    for t in range(len(levels) - 1):
        parent_of = {
            child: up.agg_id
            for up in levels[t + 1]
            for child in up.children
        }
        levels[t] = [
            replace(n, parent=parent_of[n.agg_id]) for n in levels[t]
        ]
    return levels


class _AggHandler(_Handler):
    """The aggregator's wire dispatch: same framing/lifecycle as the
    root's handler, but ``server.store`` is the :class:`Aggregator`
    itself."""

    def _dispatch(self, agg, header, payload):
        return agg.dispatch(header, payload)


class Aggregator:
    """One mid-tier fold/forward/cache node (see module docstring).

    Thread shape: the embedded :class:`ExchangeServer`'s handler
    threads write pushes into ``_pending`` and read the caches; one
    flush thread folds ready rounds and forwards them upstream. ALL
    mutable state is guarded by ``_lock`` (``_cond`` wraps the same
    lock); upstream requests always run outside it. ``clock`` is
    injectable so flush-timing drills run wall-clock-free."""

    def __init__(
        self,
        agg_id: int,
        upstream_addr: str,
        *,
        expected_children: int = 0,
        flush_after: float = 1.0,
        cache_ttl: float = 0.05,
        keep_rounds: int = 16,
        wire_dtype: str = "f32",
        delta: bool = False,
        max_forward_retries: int = 3,
        host: str = "127.0.0.1",
        port: int = 0,
        clock=time.monotonic,
    ):
        from tpuflow.obs import default_registry

        if wire_dtype not in wire.WIRE_DTYPES:
            raise ValueError(
                f"wire_dtype must be one of {wire.WIRE_DTYPES}, got "
                f"{wire_dtype!r}"
            )
        self.agg_id = int(agg_id)
        self.expected_children = int(expected_children)
        self.flush_after = float(flush_after)
        self.cache_ttl = float(cache_ttl)
        self.keep_rounds = int(keep_rounds)
        self.wire_dtype = wire_dtype
        self.delta = bool(delta)
        self.max_forward_retries = int(max_forward_retries)
        self.clock = clock
        self._upstream = TransportClient(upstream_addr)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # round key -> {pusher_id: (leaves, weight, covers)}
        self._pending: dict = {}
        self._opened: dict = {}  # round key -> first-push time
        self._defer: dict = {}  # round key -> not-before (retry pacing)
        self._retries: dict = {}  # round key -> failed forwards so far
        # round key -> {pusher_id: rec} behind each successful forward:
        # late pushes for an already-flushed round fold together with
        # these into one CUMULATIVE re-forward (see _forward). Pruned
        # when the round publishes and bounded by keep_rounds.
        self._forwarded: dict = {}
        self._avg_cache: dict[int, list] = {}  # round -> avg leaves
        self._neg_until: dict[int, float] = {}  # round -> miss expiry
        self._latest_cache: tuple | None = None  # (expiry, round)
        self._latest_avg: tuple | None = None  # (expiry, round | None)
        self._stopping = False
        self._server = ExchangeServer(
            store=self, host=host, port=port, handler=_AggHandler
        )
        self._thread: threading.Thread | None = None
        reg = default_registry()
        self._pushes_ctr = reg.counter(
            "elastic_agg_pushes_total",
            "subtree pushes received by mid-tier aggregators",
        )
        self._folds_ctr = reg.counter(
            "elastic_agg_folds_total",
            "subtree partial averages folded and forwarded upstream",
        )
        self._cache_hits = reg.counter(
            "elastic_agg_cache_hits_total",
            "subtree reads served from an aggregator's local cache",
        )

    @property
    def addr(self) -> str:
        return self._server.addr

    # ---- lifecycle ----

    def start(self) -> "Aggregator":
        self._server.start()
        self._thread = threading.Thread(
            target=self._flush_loop,
            name=f"tpuflow-elastic-agg-{self.agg_id}", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Graceful: stop accepting, flush every pending round upstream
        (the leaf tier's final pushes ride this), stop. The runner
        stops tiers leaf-first so each flush lands in a live parent."""
        self._server.stop()
        self._join_flush_thread()
        with self._cond:
            batch = {k: self._pending.pop(k) for k in list(self._pending)}
            self._opened.clear()
            self._defer.clear()
        for key in sorted(batch, key=str):
            self._forward(key, batch[key])
        with self._cond:
            self._forwarded.clear()
            self._retries.clear()

    def kill(self) -> None:
        """Abrupt death for the failover drills: the server vanishes
        mid-round, nothing is flushed — the subtree's FailoverClient
        and the root's round machinery own the healing."""
        self._server.stop()
        self._join_flush_thread()
        with self._cond:
            self._pending.clear()
            self._opened.clear()
            self._defer.clear()
            self._retries.clear()
            self._forwarded.clear()

    def _join_flush_thread(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self) -> "Aggregator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- wire dispatch (the _AggHandler entry) ----

    def dispatch(self, header: dict, payload: bytes):
        op = header.get("op")
        if op == "ping":
            return {"ok": True}, b""
        if op == "push":
            return self._handle_push(header, payload)
        if op == "read_average":
            return self._handle_read_average(int(header["round"]))
        if op == "latest_round":
            return self._handle_latest_round()
        if op == "latest_average":
            return self._handle_latest_average()
        # Everything else (heartbeat, offsets, members, pushed_ids) is
        # relayed verbatim: membership and liveness stay root-stamped.
        fwd = {k: v for k, v in header.items() if k != "op"}
        return self._upstream.request(op, fwd, payload)

    def _handle_push(self, header: dict, payload: bytes):
        enc = header.get("enc") or {}
        base = None
        if enc.get("delta"):
            with self._lock:
                base = self._avg_cache.get(int(enc["base_round"]))
            if base is None:
                return {
                    "ok": True, "stored": False,
                    "reason": (
                        f"delta base round {enc['base_round']} not "
                        "held by this aggregator"
                    ),
                }, b""
        leaves = wire.decode_push(enc, payload, base=base)
        wid = int(header["worker_id"])
        covers = header.get("covers")
        rec = (
            leaves,
            float(header.get("weight", 1.0)),
            (wid,) if covers is None
            else tuple(sorted(int(c) for c in covers)),
        )
        key = self._round_key(header)
        with self._cond:
            if key not in self._pending:
                self._opened[key] = self.clock()
            self._pending.setdefault(key, {})[wid] = rec
            self._cond.notify_all()
        self._pushes_ctr.inc()
        return {"ok": True, "stored": True}, b""

    def _handle_read_average(self, round_: int):
        now = self.clock()
        with self._lock:
            cached = self._avg_cache.get(round_)
            missing_until = self._neg_until.get(round_, 0.0)
        if cached is not None:
            self._cache_hits.inc(op="read_average")
            return (
                {"ok": True, "found": True},
                exchange.encode_leaves(cached),
            )
        if missing_until > now:
            self._cache_hits.inc(op="read_average")
            return {"ok": True, "found": False}, b""
        resp, data = self._upstream.request(
            "read_average", {"round": round_}
        )
        if not resp.get("found"):
            with self._lock:
                self._neg_until[round_] = self.clock() + self.cache_ttl
            return {"ok": True, "found": False}, b""
        self._note_average(round_, exchange.decode_leaves(data))
        return {"ok": True, "found": True}, data

    def _handle_latest_round(self):
        now = self.clock()
        with self._lock:
            cached = self._latest_cache
        if cached is not None and now < cached[0]:
            self._cache_hits.inc(op="latest_round")
            return {"ok": True, "round": cached[1]}, b""
        resp, _ = self._upstream.request("latest_round")
        round_ = resp.get("round")
        with self._lock:
            self._latest_cache = (self.clock() + self.cache_ttl, round_)
        return {"ok": True, "round": round_}, b""

    def _handle_latest_average(self):
        now = self.clock()
        with self._lock:
            pointer = self._latest_avg
            leaves = (
                self._avg_cache.get(pointer[1])
                if pointer is not None and pointer[1] is not None
                else None
            )
        if pointer is not None and now < pointer[0]:
            if pointer[1] is None:
                self._cache_hits.inc(op="latest_average")
                return {"ok": True, "found": False}, b""
            if leaves is not None:
                self._cache_hits.inc(op="latest_average")
                return (
                    {"ok": True, "found": True, "round": pointer[1]},
                    exchange.encode_leaves(leaves),
                )
        resp, data = self._upstream.request("latest_average")
        if not resp.get("found"):
            with self._lock:
                self._latest_avg = (self.clock() + self.cache_ttl, None)
            return {"ok": True, "found": False}, b""
        round_ = int(resp["round"])
        self._note_average(round_, exchange.decode_leaves(data))
        with self._lock:
            self._latest_avg = (self.clock() + self.cache_ttl, round_)
        return {"ok": True, "found": True, "round": round_}, data

    def _note_average(self, round_: int, leaves) -> None:
        now = self.clock()
        with self._lock:
            self._avg_cache[round_] = leaves
            self._neg_until.pop(round_, None)
            while len(self._avg_cache) > max(self.keep_rounds, 1):
                del self._avg_cache[min(self._avg_cache)]
            # Expired negative entries, and rounds behind the oldest
            # kept average, will never be consulted again — without
            # this sweep the dict grows one entry per probed-but-
            # never-published round for the life of the gang.
            oldest = min(self._avg_cache)
            for r in [
                r for r, until in self._neg_until.items()
                if until <= now or r < oldest
            ]:
                del self._neg_until[r]
            # A published round's fold is settled at the root: the
            # records kept for cumulative re-forwards are done too.
            for k in [
                k for k in self._forwarded
                if k != exchange.FINAL_ROUND and k <= round_
            ]:
                del self._forwarded[k]

    @staticmethod
    def _round_key(header):
        r = header.get("round")
        return r if r == exchange.FINAL_ROUND else int(r)

    # ---- the fold/forward loop ----

    def _ready_keys_locked(self, now: float) -> list:
        ready = []
        for key, recs in self._pending.items():
            if now < self._defer.get(key, 0.0):
                continue  # a failed forward is pacing this round
            if (
                self.expected_children
                and len(recs) >= self.expected_children
            ):
                ready.append(key)
            elif now - self._opened.get(key, now) >= self.flush_after:
                ready.append(key)
        return ready

    def _flush_loop(self) -> None:
        tick = max(self.flush_after / 4.0, 0.01)
        while True:
            with self._cond:
                while (
                    not self._stopping
                    and not self._ready_keys_locked(self.clock())
                ):
                    self._cond.wait(timeout=tick)
                if self._stopping:
                    return
                batch = {}
                for key in self._ready_keys_locked(self.clock()):
                    batch[key] = self._pending.pop(key)
                    self._opened.pop(key, None)
            for key in sorted(batch, key=str):
                self._forward(key, batch[key])

    def _forward(self, key, recs: dict) -> None:
        """Fold one round's subtree pushes into a weighted partial
        average and push it upstream. Runs OUTSIDE the lock; on an
        upstream transport failure the records are re-queued with a
        deferral, a bounded number of times.

        An already-flushed round can accumulate more pushes — a
        straggler slower than ``flush_after``, or a client retry after
        a lost response. The upstream store keys push records by pusher
        id, so a second partial under this agg_id REPLACES the first;
        it must therefore cover everything forwarded so far, not just
        the late arrivals. The records behind each successful forward
        are kept per round (``_forwarded``) and merged under the late
        ones here — same pusher superseded, everything else folded in —
        so every re-forward is cumulative and the round's covered set
        only ever grows."""
        with self._lock:
            merged = dict(self._forwarded.get(key, {}))
        merged.update(recs)
        items = exchange.dedupe_weighted_records([
            (wid, rec[0], rec[1], rec[2])
            for wid, rec in sorted(merged.items())
        ])
        leaves, used = exchange.average_leaf_sets(
            [(wid, ls) for wid, ls, _w, _c in items],
            weights=[w for _, _, w, _ in items],
            context=f"(aggregator {self.agg_id}, round {key}) ",
        )
        if leaves is None:
            return
        used_set = set(used)
        total_weight = sum(
            w for wid, _ls, w, _c in items if wid in used_set
        )
        covers = sorted({
            c
            for wid, _ls, _w, cov in items if wid in used_set
            for c in cov
        })
        final = key == exchange.FINAL_ROUND
        base_round = base = None
        if self.delta and not final:
            with self._lock:
                if self._avg_cache:
                    base_round = max(self._avg_cache)
                    base = self._avg_cache[base_round]
        header = {
            "round": key, "worker_id": self.agg_id,
            "weight": total_weight, "covers": covers,
        }
        try:
            enc, payload = wire.encode_push(
                leaves,
                wire_dtype="f32" if final else self.wire_dtype,
                base=base, base_round=base_round,
            )
            if enc:
                header["enc"] = enc
            resp, _ = self._upstream.request("push", header, payload)
            if not resp.get("stored", True):
                # Parent pruned past our base: re-push full.
                enc, payload = wire.encode_push(
                    leaves,
                    wire_dtype="f32" if final else self.wire_dtype,
                )
                header = {k: v for k, v in header.items() if k != "enc"}
                if enc:
                    header["enc"] = enc
                self._upstream.request("push", header, payload)
        except (OSError, RuntimeError) as e:
            self._requeue(key, recs, e)
            return
        self._folds_ctr.inc()
        with self._lock:
            self._forwarded[key] = merged
            # The forward landed: its retry/pacing state is spent —
            # left behind, both dicts grow one entry per round forever.
            self._retries.pop(key, None)
            self._defer.pop(key, None)
            ints = sorted(
                k for k in self._forwarded if k != exchange.FINAL_ROUND
            )
            while len(ints) > max(self.keep_rounds, 1):
                del self._forwarded[ints.pop(0)]

    def _requeue(self, key, recs: dict, err: BaseException) -> None:
        with self._cond:
            tries = self._retries.get(key, 0) + 1
            self._retries[key] = tries
            if tries <= self.max_forward_retries and not self._stopping:
                pending = self._pending.setdefault(key, {})
                for wid, rec in recs.items():
                    pending.setdefault(wid, rec)
                self._opened.setdefault(key, self.clock())
                self._defer[key] = self.clock() + self.flush_after
                dropped = False
            else:
                dropped = True
        print(
            f"elastic: aggregator {self.agg_id} failed to forward "
            f"round {key} upstream ({type(err).__name__}: {err}); "
            + (
                "dropping the partial (retries exhausted) — the "
                "subtree sees a missed round"
                if dropped else
                f"will retry (attempt {tries}/{self.max_forward_retries})"
            ),
            file=sys.stderr,
        )
