"""Gang runner: coordinator + N supervised workers, one call.

``run_elastic(spec, n_workers)`` is the cluster layer the paper's system
got from Spark, built from tpuflow's own resilience parts:

- the **coordinator** (``coordinator.py``) runs in a thread of this
  process, averaging rounds over the live set;
- each **worker** is a child process running the ordinary ``train()``
  on its shard, driven by its own ``train/supervisor.py`` attempt loop
  (``mode="supervised"``) — a worker that dies is backed off,
  relaunched with ``resume=True``, and rejoins the gang; crash-loop /
  stall / numerics classification all apply per worker;
- ``mode="inprocess"`` runs the workers as threads calling ``train()``
  directly — no restart loop, but no per-worker process launch either:
  the fast path for tier-1 drills and fixed-membership reference runs.

Each worker checkpoints under ``{storagePath}/worker{N}`` (disjoint
trees — supervisor restarts resume the right worker), and the gang's
shared files live under ``gang_dir`` (default
``{storagePath}/elastic``). After every worker returns, the runner
averages their *final* pushes into ``{gang_dir}/avg/final.npz`` — the
gang's deliverable, well-defined even when workers finished rounds at
different times.

Shell entry (see ``python -m tpuflow.elastic --help``)::

    python -m tpuflow.elastic spec.json --workers 3 --sync-every 1
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

from tpuflow.elastic import exchange
from tpuflow.elastic.coordinator import Coordinator

MODES = ("supervised", "inprocess")


@dataclass
class WorkerOutcome:
    """One worker's end state: its job report (None if it never
    finished), the supervisor's attempt/failure trail, or the error
    that exhausted it."""

    worker_id: int
    report: dict | None = None
    attempts: int = 0
    failures: list = field(default_factory=list)
    error: str | None = None
    # A cooperative stop (run_elastic(stop_event=...)) is an OUTCOME,
    # not a failure: the reason string lands here and the worker stays
    # error-free, so a drained gang still reports ok=True.
    stopped: str | None = None


@dataclass
class ElasticRunResult:
    gang_dir: str
    workers: list[WorkerOutcome]
    coordinator: dict
    final_params: list | None  # averaged leaves over the final pushes
    final_worker_ids: list[int]
    final_path: str | None

    @property
    def ok(self) -> bool:
        # A crashed coordinator means no averaging happened — a run
        # like that must not report success just because the workers
        # (training solo on local params) all returned.
        return (
            bool(self.workers)
            and all(w.error is None for w in self.workers)
            and "error" not in self.coordinator
        )

    def summary(self) -> dict:
        # _json_finite: a diverged worker's report is exactly where
        # inf/nan best_val_loss appears, and raw json.dumps would emit
        # RFC-8259-invalid Infinity/NaN tokens to the CLI's stdout.
        from tpuflow.serve import _json_finite

        return _json_finite({
            "ok": self.ok,
            "coordinator_error": self.coordinator.get("error"),
            "workers": [
                {
                    "worker_id": w.worker_id,
                    "attempts": w.attempts,
                    "error": w.error,
                    "stopped": w.stopped,
                    "epochs_ran": (w.report or {}).get("epochs_ran"),
                    "best_val_loss": (w.report or {}).get("best_val_loss"),
                }
                for w in self.workers
            ],
            "rounds": self.coordinator.get("round", 1) - 1,
            "evicted": self.coordinator.get("evicted", []),
            "rejoins": self.coordinator.get("rejoins", 0),
            "final_averaged_over": self.final_worker_ids,
            "final_path": self.final_path,
        })


def worker_spec(
    base_spec: dict,
    gang_dir: str,
    worker_id: int,
    n_workers: int,
    *,
    sync_every: int = 1,
    elastic_overrides: dict | None = None,
) -> dict:
    """One worker's job spec: the base job plus its ``elastic`` block,
    a per-worker checkpoint tree, and the supervisor's preconditions
    (``save_every >= 1`` so restarts resume instead of restart-over;
    ``n_devices`` defaults to 1 when unset — an explicit ``n_devices``
    in the base spec makes each worker data-parallel across that many
    LOCAL devices via ``parallel/compat.py`` + ``make_mesh``: a fleet
    of meshes, not a fleet of cores)."""
    spec = dict(base_spec)
    storage = spec.pop("storagePath", None) or spec.pop("storage_path", None)
    spec.pop("storage_path", None)
    if not storage:
        raise ValueError(
            "run_elastic needs storagePath in the spec — workers "
            "checkpoint under {storagePath}/workerN and restarts resume "
            "from there"
        )
    spec["storagePath"] = os.path.join(storage, f"worker{worker_id}")
    # Explicit None (dataclasses.asdict specs) counts as unset too.
    if not spec.get("save_every"):
        spec["save_every"] = 1
    if spec.get("n_devices") is None:
        spec["n_devices"] = 1
    # Per-worker metrics trail by default: each worker's spans (ingest/
    # step/elastic.sync, each stamped with the worker's run trace) land
    # under its own checkpoint tree, so `python -m tpuflow.obs fleet
    # <storagePath>` gets one lane per worker out of the box. An
    # explicit metrics path in the base spec is honored untouched.
    if not spec.get("metrics_path") and not spec.get("metricsPath"):
        spec["metrics_path"] = os.path.join(
            spec["storagePath"], "metrics.jsonl"
        )
    spec["elastic"] = {
        "dir": gang_dir,
        "worker_id": worker_id,
        "n_workers": n_workers,
        "sync_every": sync_every,
        **(elastic_overrides or {}),
    }
    return spec


def _ensure_fresh_gang_dir(gang_dir: str) -> None:
    """Refuse a gang_dir that holds a previous gang's state. Reusing it
    would be silently catastrophic: the old ``done`` heartbeats satisfy
    ``all_finished`` before the new workers even launch (the
    coordinator exits instantly), and the stale ``avg/LATEST``
    warm-starts every worker into rounds nobody is collecting — N solo
    trainings reporting themselves as an elastic gang."""
    from tpuflow.elastic.membership import MEMBERS_DIR

    stale = [
        sub
        for sub in (MEMBERS_DIR, exchange.PUSH_DIR, exchange.AVG_DIR)
        if os.path.isdir(os.path.join(gang_dir, sub))
        and os.listdir(os.path.join(gang_dir, sub))
    ]
    if stale:
        raise ValueError(
            f"gang_dir {gang_dir!r} holds a previous gang's state "
            f"({', '.join(s + '/' for s in stale)}) — stale heartbeats "
            "would end the new gang instantly and its workers would "
            "warm-start into rounds nobody collects; remove the old "
            "state or pass a fresh gang_dir"
        )


def run_elastic(
    spec: dict,
    n_workers: int,
    *,
    gang_dir: str | None = None,
    mode: str = "supervised",
    transport: str = "file",
    transport_addr: str | None = None,
    async_push: bool = False,
    max_staleness: int = 2,
    fanout: int | None = None,
    tiers: int | None = None,
    delta: bool | None = None,
    wire_dtype: str | None = None,
    opt_policy: str = "carry",
    on_gang_up=None,
    sync_every: int = 1,
    heartbeat_interval: float = 0.25,
    heartbeat_timeout: float = 30.0,
    round_timeout: float = 60.0,
    min_round_interval: float = 0.0,
    pull_timeout: float = 120.0,
    poll_interval: float | None = None,
    max_restarts: int = 2,
    stall_timeout: float | None = None,
    term_grace: float = 5.0,
    backoff_base: float = 0.05,
    backoff_jitter: float = 0.0,
    worker_faults: dict | None = None,
    stop_event: threading.Event | None = None,
    verbose: bool = False,
) -> ElasticRunResult:
    """Run one elastic gang to completion; see the module docstring.

    ``transport="socket"`` hosts a TCP exchange server in this process
    (``elastic/transport.py``; ephemeral 127.0.0.1 port) and points
    every worker's ``elastic`` block at it — heartbeats, pushes, and
    rebroadcast pulls all ride the wire, and the gang dir is used only
    for each worker's own checkpoints. ``async_push`` switches the
    gang to DeepSpark-style asynchronous averaging with the
    ``max_staleness`` bound (see docs/elastic.md).

    ``worker_faults`` maps worker_id -> a ``faults`` spec list for that
    worker's job (the churn drills: kill worker 1 at epoch 3, watch the
    gang absorb it). Targeting is exact only under ``supervised`` mode
    (each worker is its own process with its own registry); in
    ``inprocess`` mode the fault registry is process-global, so a spec
    may fire in whichever worker thread hits the site first — and
    exit/hang modes, which would kill or wedge the WHOLE process, are
    rejected there. Worker failures never raise out of here — they
    land in the per-worker ``WorkerOutcome.error`` so a partial gang
    still reports what the survivors produced.

    ``fanout`` > 0 (or ``TPUFLOW_ELASTIC_FANOUT``) switches the socket
    gang to tree aggregation (``aggregator.py``): ``tiers`` levels of
    mid-tier aggregators fold subtree pushes and forward one weighted
    partial each, each worker dials its leaf aggregator with the root
    as failover fallback, and the aggregators are stopped leaf-tier
    first so final pushes flush upward. ``delta``/``wire_dtype`` pick
    the push encoding (``wire.py``); ``opt_policy`` picks what happens
    to optimizer state on adoption (docs/elastic.md). ``on_gang_up``
    (tests/benchmarks) is called once every thread is running, with
    ``{"server", "aggregators", "coordinator", "stop"}`` — the seam
    kill drills reach the live tree through.

    ``stop_event`` (inprocess mode only) is the runtime supervisor's
    drain handle: setting it asks every worker to stop cooperatively at
    its next epoch boundary via ``train(stop_fn=...)`` — the stop is an
    outcome (``WorkerOutcome.stopped``), not an error, so a drained
    gang still averages whatever its workers last pushed and reports
    ``ok=True``. Supervised workers are separate processes; stopping
    them is the process supervisor's SIGTERM escalation, not an Event.
    """
    if n_workers < 1:
        raise ValueError(f"n_workers must be >= 1, got {n_workers}")
    if mode not in MODES:
        raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
    if stop_event is not None and mode != "inprocess":
        raise ValueError(
            "stop_event needs mode='inprocess' (threaded workers polling "
            "a shared Event); supervised workers are child processes — "
            "stop those with the supervisor's SIGTERM escalation"
        )
    if transport not in ("file", "socket"):
        raise ValueError(
            f"transport must be 'file' or 'socket', got {transport!r}"
        )
    from tpuflow.elastic.aggregator import (
        default_fanout,
        default_tiers,
        plan_tree,
    )

    fanout = default_fanout() if fanout is None else int(fanout)
    tiers = default_tiers() if tiers is None else int(tiers)
    tree_levels = []
    if fanout:
        if transport != "socket":
            raise ValueError(
                "tree aggregation (fanout > 0) needs transport="
                "'socket' — aggregators speak the TPFX wire protocol"
            )
        if async_push:
            raise ValueError(
                "tree aggregation folds per-round subtree barriers and "
                "async_push has no rounds to barrier on — use one or "
                "the other"
            )
        tree_levels = plan_tree(n_workers, fanout, tiers)
    if worker_faults and mode == "inprocess":
        from tpuflow.resilience import parse_fault_spec

        for wid, entries in worker_faults.items():
            for entry in entries:
                if parse_fault_spec(entry).mode in ("exit", "hang"):
                    raise ValueError(
                        f"worker_faults[{wid}]={entry!r}: mode="
                        f"{parse_fault_spec(entry).mode} under "
                        "mode='inprocess' would kill or wedge the whole "
                        "process (workers are threads); use "
                        "mode='supervised' for kill drills"
                    )
    storage = spec.get("storagePath") or spec.get("storage_path")
    if not storage:
        raise ValueError(
            "run_elastic needs storagePath in the spec — workers "
            "checkpoint under {storagePath}/workerN and restarts resume "
            "from there"
        )
    gang_dir = gang_dir or os.path.join(storage, "elastic")
    from tpuflow.storage import is_store_uri

    # A store-URI gang dir (fake://bucket/gang — see tpuflow/storage/)
    # rides StoreExchange: all gang state becomes objects, and the
    # coordinator's OBSERVABILITY files (state mirror, metrics trail,
    # forensics) land in a local sidecar dir under storagePath instead.
    store_gang = is_store_uri(gang_dir)
    meta_dir = (
        os.path.join(storage, "elastic-meta") if store_gang else gang_dir
    )
    if transport == "file" and not store_gang:
        # Socket gangs keep their state in the server's memory — a
        # stale DIRECTORY cannot confuse them, so only the file
        # transport needs the fresh-gang-dir refusal.
        _ensure_fresh_gang_dir(gang_dir)
    os.makedirs(meta_dir, exist_ok=True)
    server = None
    coord_backend = None
    if store_gang:
        if transport != "file":
            raise ValueError(
                f"a store-URI gang dir ({gang_dir!r}) carries the "
                "exchange itself; combine it with transport='file' "
                "(the default), not 'socket'"
            )
        from tpuflow.elastic import make_backend

        coord_backend = make_backend({"dir": gang_dir})
        if coord_backend.has_state():
            # The same silent catastrophe _ensure_fresh_gang_dir blocks
            # for directories: stale done-heartbeats end the gang
            # instantly and stale LATEST warm-starts orphaned rounds.
            raise ValueError(
                f"gang namespace {gang_dir!r} holds a previous gang's "
                "state — remove the old objects or pass a fresh prefix"
            )
    if transport == "socket":
        from tpuflow.elastic.transport import ExchangeServer, parse_addr

        # transport_addr pins the server's bind ("host:port"; port 0 =
        # ephemeral). The default loopback/ephemeral is right for
        # single-host gangs; a multi-host gang (or an external monitor)
        # needs a dialable address.
        host, port = parse_addr(transport_addr or "127.0.0.1:0")
        server = ExchangeServer(host=host, port=port).start()
        coord_backend = server.store
    overrides = {
        "heartbeat_interval": heartbeat_interval,
        "heartbeat_timeout": heartbeat_timeout,
        "pull_timeout": pull_timeout,
        "poll_interval": poll_interval,
        "transport": transport,
        "async_push": async_push,
        "max_staleness": max_staleness,
        "opt_policy": opt_policy,
    }
    if server is not None:
        overrides["addr"] = server.addr
        # Resolve the wire-encoding knobs HERE (explicit args, then the
        # TPUFLOW_ELASTIC_* env family, then the static defaults) so
        # workers and aggregators agree on one encoding — a worker
        # reading the env while its aggregator doesn't would split the
        # gang's wire format.
        from tpuflow.utils.env import env_choice, env_flag

        delta = (
            env_flag("TPUFLOW_ELASTIC_DELTA", False)
            if delta is None else bool(delta)
        )
        wire_dtype = (
            env_choice(
                "TPUFLOW_ELASTIC_WIRE_DTYPE", "f32", ("f32", "bf16")
            )
            if wire_dtype is None else wire_dtype
        )
        overrides["delta"] = delta
        overrides["wire_dtype"] = wire_dtype
    elif delta or (wire_dtype not in (None, "f32")):
        raise ValueError(
            "delta / wire_dtype are socket-transport wire encodings; "
            "the file backend exchanges full f32"
        )
    # Fail at submission, not N jax-import-heavy worker launches
    # later: a bad knob (sync_every=0, negative timeout) or a bad base
    # job (stream=True, typo'd model) must die HERE, in this process,
    # with the validator's message.
    from tpuflow.analysis import ensure_preflight
    from tpuflow.elastic import resolve_elastic
    from tpuflow.serve import spec_to_config

    try:
        resolve_elastic({
            "dir": gang_dir, "worker_id": 0, "n_workers": n_workers,
            "sync_every": sync_every, "round_timeout": round_timeout,
            **overrides,
        })
        if min_round_interval < 0:
            raise ValueError(
                f"min_round_interval must be >= 0 (seconds), got "
                f"{min_round_interval}"
            )
        ensure_preflight(
            spec_to_config(worker_spec(
                spec, gang_dir, 0, n_workers,
                sync_every=sync_every, elastic_overrides=overrides,
            )),
            passes=("spec",),
        )
    except BaseException:
        if server is not None:  # a rejected submission must not leak it
            server.stop()
        raise
    # The aggregation tree, parents first: a child's first forward must
    # find its upstream dialable. `aggregators` ends up top-tier-first,
    # so teardown iterates it REVERSED (leaf tier first) and every
    # flush lands in a live parent.
    aggregators: list = []
    agg_addr_for: dict[int, str] = {}
    if tree_levels:
        from tpuflow.elastic.aggregator import Aggregator

        try:
            addr_of: dict[int, str] = {}
            for level in reversed(tree_levels):
                for node in level:
                    agg = Aggregator(
                        node.agg_id,
                        addr_of[node.parent]
                        if node.parent is not None else server.addr,
                        expected_children=len(node.children),
                        wire_dtype=wire_dtype,
                        delta=delta,
                    ).start()
                    aggregators.append(agg)
                    addr_of[node.agg_id] = agg.addr
            for node in tree_levels[0]:
                for wid in node.children:
                    agg_addr_for[wid] = addr_of[node.agg_id]
        except BaseException:
            for agg in aggregators:
                agg.kill()
            server.stop()
            raise
    coordinator = Coordinator(
        meta_dir,
        heartbeat_timeout=heartbeat_timeout,
        heartbeat_interval=heartbeat_interval,
        round_timeout=round_timeout,
        min_round_interval=min_round_interval,
        poll_interval=poll_interval,
        expected_workers=n_workers,
        backend=coord_backend,
        async_push=async_push,
        max_staleness=max_staleness,
        verbose=verbose,
    )
    stop = threading.Event()
    coord_outcome: dict = {}

    def _coordinate():
        try:
            coord_outcome["state"] = coordinator.run(stop)
        except BaseException as e:  # surfaced in the result, not lost
            coord_outcome["error"] = f"{type(e).__name__}: {e}"

    outcomes = [WorkerOutcome(worker_id=i) for i in range(n_workers)]

    def _work(i: int):
        wover = overrides
        if i in agg_addr_for:
            # Tree mode: dial the leaf aggregator; the root is the
            # failover fallback — an aggregator kill re-parents this
            # worker's subtree to the root mid-round.
            wover = {
                **overrides,
                "addr": agg_addr_for[i],
                "fallback_addrs": [server.addr],
            }
        wspec = worker_spec(
            spec, gang_dir, i, n_workers,
            sync_every=sync_every, elastic_overrides=wover,
        )
        if worker_faults and i in worker_faults:
            wspec["faults"] = list(worker_faults[i])
        try:
            if mode == "supervised":
                from tpuflow.train.supervisor import supervise

                run = supervise(
                    wspec,
                    max_restarts=max_restarts,
                    stall_timeout=stall_timeout,
                    term_grace=term_grace,
                    backoff_base=backoff_base,
                    backoff_jitter=backoff_jitter,
                    verbose=verbose,
                )
                outcomes[i].report = run.report
                outcomes[i].attempts = run.attempts
                outcomes[i].failures = run.failures
            else:
                from tpuflow.api import train
                from tpuflow.serve import report_to_dict, spec_to_config
                from tpuflow.train.loop import TrainingInterrupted

                stop_fn = None
                if stop_event is not None:
                    def _stop_fn():
                        if stop_event.is_set():
                            return "runtime stop requested"
                        return None

                    stop_fn = _stop_fn
                try:
                    outcomes[i].report = report_to_dict(
                        train(spec_to_config(wspec), stop_fn=stop_fn)
                    )
                except TrainingInterrupted as e:
                    outcomes[i].stopped = str(e) or "stopped"
                outcomes[i].attempts = 1
        except BaseException as e:
            outcomes[i].error = f"{type(e).__name__}: {e}"
            # CrashLoopError / budget-exhaustion RuntimeError carry the
            # supervisor's attempt trail — keep it, or the summary
            # would show attempts=0 for the worker that burned the
            # whole restart budget.
            trail = getattr(e, "failures", None)
            if trail:
                outcomes[i].failures = list(trail)
                outcomes[i].attempts = len(trail)

    coord_thread = threading.Thread(
        target=_coordinate, name="tpuflow-elastic-coordinator", daemon=True
    )
    coord_thread.start()
    workers = [
        threading.Thread(
            target=_work, args=(i,), name=f"tpuflow-elastic-w{i}",
            daemon=True,
        )
        for i in range(n_workers)
    ]
    try:
        for t in workers:
            t.start()
        if on_gang_up is not None:
            on_gang_up({
                "server": server,
                "aggregators": list(aggregators),
                "coordinator": coordinator,
                "stop": stop,
            })
        for t in workers:
            t.join()
        for agg in reversed(aggregators):
            agg.stop()  # leaf tier first: finals flush up a live chain
        stop.set()
        coord_thread.join(timeout=30)
    finally:
        stop.set()
        for agg in reversed(aggregators):
            agg.kill()  # no-op after a clean stop(); kills on error
        if server is not None:
            server.stop()

    final_backend = (
        coord_backend if coord_backend is not None
        else exchange.FileExchange(gang_dir)
    )
    weighted = getattr(final_backend, "read_weighted_pushes", None)
    if weighted is not None:
        # Socket/tree gangs: a final push may be an aggregator's
        # weighted partial covering several workers — re-average by
        # weight and report the covered WORKER ids.
        recs = weighted(exchange.FINAL_ROUND)
        final_leaves, used_pushers = exchange.average_leaf_sets(
            [(wid, ls) for wid, ls, _w, _c in recs],
            weights=[w for _, _, w, _ in recs],
            context="for the final round ",
        )
        pushers = set(used_pushers)
        final_ids = sorted({
            c
            for wid, _ls, _w, cov in recs
            if wid in pushers
            for c in cov
        })
    else:
        final_leaves, final_ids = exchange.average_leaf_sets(
            final_backend.read_pushes(exchange.FINAL_ROUND),
            context="for the final round ",
        )
    final_path = None
    if final_leaves is not None:
        if store_gang:
            # The deliverable is an object too: avg/final.npz in the
            # store, reported as its URI.
            key = final_backend.write_final(final_leaves)
            scheme, _, rest = gang_dir.partition("://")
            bucket = rest.split("/", 1)[0]
            final_path = f"{scheme}://{bucket}/{key}"
        else:
            final_path = os.path.join(
                gang_dir, exchange.AVG_DIR, "final.npz"
            )
            exchange.write_leaves(final_path, final_leaves)
    coord_state = coord_outcome.get("state") or coordinator.state()
    if coord_thread.is_alive():
        # The join timed out: the coordinator is wedged (slow shared
        # FS, a stuck scan). A run whose rounds were never driven to
        # completion must not report ok=True.
        coord_outcome.setdefault(
            "error",
            "coordinator thread still running after the stop join "
            "timeout (wedged scan?)",
        )
    if "error" in coord_outcome:
        coord_state = {**coord_state, "error": coord_outcome["error"]}
    return ElasticRunResult(
        gang_dir=gang_dir,
        workers=outcomes,
        coordinator=coord_state,
        final_params=final_leaves,
        final_worker_ids=final_ids,
        final_path=final_path,
    )
