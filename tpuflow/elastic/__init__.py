"""Elastic data-parallel training: local SGD + coordinated averaging.

In the SparkNet/DeepSpark mold (PAPERS.md): N worker processes each
run the ordinary ``train()`` loop on a disjoint shard of the training
rows (each optionally data-parallel across its own local devices via
``tpuflow/parallel/compat.py`` — a fleet of meshes); a small
coordinator averages their parameters and rebroadcasts the mean —
synchronously per round, or asynchronously with a staleness bound
(``async_push``/``max_staleness``: push when ready, adopt the
freshest, down-weight stale pushes by ``1/(1+s)`` and reject past the
bound, so a straggler can't stall the gang). The exchange rides one of
two transports behind a single backend interface — the file reference
implementation (``exchange.py``: needs nothing but a shared directory)
or a coordinator-hosted TCP exchange server (``transport.py``: framed,
checksummed, retry-wrapped; no shared filesystem) — and either way
tolerates membership churn by construction:

- **Heartbeats + eviction** (``membership.py``): a worker whose
  heartbeat goes stale past the deadline is evicted; averaging proceeds
  over the live set.
- **Restart + rejoin** (``runner.py``): each worker runs under its own
  ``train/supervisor.py`` attempt loop — a SIGKILLed worker is
  relaunched with ``resume=True``, replays from its run checkpoint, and
  is readmitted the moment its heartbeat reappears.
- **Warm start** (``worker.py``): a late joiner with no checkpoint
  adopts the latest published average before its first epoch
  (``train/resume.py::apply_params``), so it starts from gang progress,
  not from init.

Drillable end to end through the resilience registry: the
``elastic.heartbeat`` / ``elastic.push`` / ``elastic.join`` sites plus
the transport chaos sites ``elastic.transport.send`` / ``.recv`` /
``.partition`` (docs/elastic.md has the recipes; a worker that loses
the coordinator degrades to local training and resyncs on reconnect).

A worker is configured by the spec-validated ``elastic`` block of
``TrainJobConfig`` (``analysis/spec.py`` rejects malformed blocks at
submission)::

    {"dir": "/shared/gang", "worker_id": 0, "n_workers": 3,
     "sync_every": 1, "heartbeat_interval": 0.25,
     "heartbeat_timeout": 30.0, "pull_timeout": 120.0,
     "warm_start": true}

``run_elastic`` (``runner.py``) builds those blocks, launches the
coordinator plus the per-worker supervisors, and averages the workers'
final pushes into the gang's deliverable.
"""

from __future__ import annotations

# Per-knob defaults and validation for the ``elastic`` config block.
# Kept import-light: the preflight spec pass reads these without pulling
# jax-heavy worker machinery.
ELASTIC_DEFAULTS: dict = {
    "sync_every": 1,           # epochs between averaging rounds
    "heartbeat_interval": 0.25,  # seconds between heartbeat writes
    "heartbeat_timeout": 30.0,  # stale-heartbeat eviction deadline
    "round_timeout": 60.0,     # coordinator wait per round
    "pull_timeout": 120.0,     # worker wait for a round's average
    "poll_interval": None,     # file-polling cadence (worker + coord);
    # None = derived from heartbeat_interval (derive_poll_interval) —
    # a fixed 20 Hz directory scan is needless metadata load on
    # NFS-class gang dirs when the gang only beats every few seconds.
    "warm_start": True,        # late joiners adopt the latest average
    # --- transport + async push (transport.py; docs/elastic.md) ---
    "transport": "file",       # "file" (shared dir — the reference/
    # drill implementation) or "socket" (coordinator-hosted TCP RPC —
    # no shared filesystem needed)
    "addr": None,              # "host:port" of the exchange server
    # (required when transport="socket"; the runner fills it in)
    "async_push": False,       # DeepSpark-style async: push when ready,
    # adopt the freshest average, no round barrier
    "max_staleness": 2,        # async only: pushes older than this many
    # rounds behind the coordinator are rejected from the average;
    # fresher-but-stale ones are down-weighted by 1/(1+staleness)
    # --- tree aggregation + wire encoding (aggregator.py, wire.py) ---
    "fallback_addrs": None,    # ordered "host:port" list tried when the
    # primary addr is dead (tree mode: the root behind each worker's
    # leaf aggregator — the re-parenting path); socket transport only
    "wire_dtype": "f32",       # push payload dtype on the wire: "f32"
    # (exact) or "bf16" (half the bytes; masters/folds stay f32) —
    # socket transport only
    "delta": False,            # push params minus the last-adopted
    # average instead of full params (composes with bf16; a receiver
    # missing the base answers stored=false and gets a full re-push) —
    # socket transport only
    "opt_policy": "carry",     # optimizer state across an adoption:
    # "carry" (keep local moments — the historical behavior), "reset"
    # (re-init moments for the adopted params; step counts survive), or
    # "average" (gang-average floating moments alongside the params)
}

# The env-knob family for the transport block (the TPUFLOW_RETRY_* /
# TPUFLOW_SERVE_* precedent): each supplies the default for its config
# key when the job spec leaves it unset, validated at read time through
# tpuflow/utils/env.py so a malformed value names the variable and the
# expected form. An explicit spec value always wins.
#   TPUFLOW_ELASTIC_TRANSPORT       "file" | "socket"
#   TPUFLOW_ELASTIC_ADDR            "host:port"
#   TPUFLOW_ELASTIC_ASYNC           boolean flag
#   TPUFLOW_ELASTIC_MAX_STALENESS   integer >= 0
#   TPUFLOW_ELASTIC_CONNECT_TIMEOUT positive seconds (read by
#                                   transport.connect_timeout)
#   TPUFLOW_ELASTIC_DELTA           boolean flag (delta-encoded pushes)
#   TPUFLOW_ELASTIC_WIRE_DTYPE      "f32" | "bf16"
#   TPUFLOW_ELASTIC_FANOUT          integer >= 0 (runner-level tree
#                                   fan-out; read by
#                                   aggregator.default_fanout)
#   TPUFLOW_ELASTIC_TIER            integer >= 1 (aggregator tiers;
#                                   read by aggregator.default_tiers)

# Polls per heartbeat interval when poll_interval is derived: a scan a
# few times per beat observes every membership/average transition within
# a fraction of a beat, and the scan rate falls automatically as the
# heartbeat cadence relaxes (production gangs on shared filesystems).
# The drill default (heartbeat_interval=0.25) derives the same 0.05 s
# the old hard-coded constant gave — and the drills mostly inject fake
# clocks/sleeps anyway, so they stay wall-clock-free regardless.
POLL_BEATS = 5


def derive_poll_interval(heartbeat_interval: float) -> float:
    """The file-poll cadence for a gang that beats every
    ``heartbeat_interval`` seconds (see ``POLL_BEATS``)."""
    return float(heartbeat_interval) / POLL_BEATS


_REQUIRED = ("dir", "worker_id", "n_workers")


def validate_elastic_block(block) -> list[str]:
    """Every problem with an ``elastic`` config block, as messages
    (empty = valid). Never raises — the preflight spec pass reports all
    findings at once; ``resolve_elastic`` turns them into the fail-loud
    raise for runtime callers."""
    if not isinstance(block, dict):
        return [
            f"elastic must be a dict config block, got "
            f"{type(block).__name__}"
        ]
    out = []
    known = set(_REQUIRED) | set(ELASTIC_DEFAULTS)
    unknown = sorted(set(block) - known)
    if unknown:
        out.append(
            f"unknown elastic keys {unknown}; known: {sorted(known)}"
        )
    for key in _REQUIRED:
        if key not in block:
            out.append(f"elastic.{key} is required")
    if not isinstance(block.get("dir", "x"), str) or block.get("dir") == "":
        out.append("elastic.dir must be a non-empty path string")
    wid, n = block.get("worker_id"), block.get("n_workers")
    if wid is not None and (not isinstance(wid, int) or wid < 0):
        out.append(f"elastic.worker_id must be an int >= 0, got {wid!r}")
    if n is not None and (not isinstance(n, int) or n < 1):
        out.append(f"elastic.n_workers must be an int >= 1, got {n!r}")
    if (
        isinstance(wid, int) and isinstance(n, int)
        and 0 <= wid and 1 <= n and wid >= n
    ):
        out.append(
            f"elastic.worker_id {wid} is outside the gang "
            f"(n_workers={n}; ids are 0-based)"
        )
    if not isinstance(block.get("sync_every", 1), int) or (
        block.get("sync_every", 1) < 1
    ):
        out.append(
            f"elastic.sync_every must be an int >= 1, got "
            f"{block.get('sync_every')!r}"
        )
    for key in (
        "heartbeat_interval", "heartbeat_timeout", "round_timeout",
        "pull_timeout", "poll_interval",
    ):
        value = block.get(key, 1.0)
        if key == "poll_interval" and value is None:
            continue  # None = derive from heartbeat_interval
        if not isinstance(value, (int, float)) or value <= 0:
            out.append(
                f"elastic.{key} must be a positive number (seconds), "
                f"got {value!r}"
            )
    if not isinstance(block.get("warm_start", True), bool):
        out.append(
            f"elastic.warm_start must be a bool, got "
            f"{block.get('warm_start')!r}"
        )
    transport = block.get("transport", "file")
    if transport not in ("file", "socket"):
        out.append(
            f"elastic.transport must be 'file' or 'socket', got "
            f"{transport!r}"
        )
    addr = block.get("addr")
    if addr is not None and not _valid_addr(addr):
        out.append(
            f"elastic.addr must be a 'host:port' string, got {addr!r}"
        )
    if transport == "socket" and "addr" in block and addr is None:
        out.append(
            "elastic.transport='socket' needs elastic.addr "
            "('host:port' of the exchange server)"
        )
    if not isinstance(block.get("async_push", False), bool):
        out.append(
            f"elastic.async_push must be a bool, got "
            f"{block.get('async_push')!r}"
        )
    staleness = block.get("max_staleness", 0)
    if not isinstance(staleness, int) or isinstance(staleness, bool) \
            or staleness < 0:
        out.append(
            f"elastic.max_staleness must be an int >= 0 (rounds), got "
            f"{staleness!r}"
        )
    fallbacks = block.get("fallback_addrs")
    if fallbacks is not None:
        if not isinstance(fallbacks, (list, tuple)) or not all(
            _valid_addr(a) for a in fallbacks
        ):
            out.append(
                f"elastic.fallback_addrs must be a list of 'host:port' "
                f"strings (or None), got {fallbacks!r}"
            )
        elif transport != "socket":
            out.append(
                "elastic.fallback_addrs needs elastic.transport="
                "'socket' (failover is a wire-transport concern)"
            )
    wire_dtype = block.get("wire_dtype", "f32")
    if wire_dtype not in ("f32", "bf16"):
        out.append(
            f"elastic.wire_dtype must be 'f32' or 'bf16', got "
            f"{wire_dtype!r}"
        )
    elif wire_dtype == "bf16" and transport != "socket":
        out.append(
            "elastic.wire_dtype='bf16' needs elastic.transport="
            "'socket' (quantization is a wire encoding; the file "
            "backend exchanges full f32)"
        )
    delta = block.get("delta", False)
    if not isinstance(delta, bool):
        out.append(f"elastic.delta must be a bool, got {delta!r}")
    elif delta and transport != "socket":
        out.append(
            "elastic.delta=true needs elastic.transport='socket' "
            "(delta encoding is a wire encoding; the file backend "
            "exchanges full f32)"
        )
    if block.get("opt_policy", "carry") not in (
        "carry", "reset", "average",
    ):
        out.append(
            f"elastic.opt_policy must be 'carry', 'reset', or "
            f"'average', got {block.get('opt_policy')!r}"
        )
    return out


def _valid_addr(addr) -> bool:
    if not isinstance(addr, str):
        return False
    host, sep, port = addr.rpartition(":")
    return bool(sep) and bool(host) and port.isdigit()


def resolve_elastic(block: dict) -> dict:
    """Defaults-merged, validated copy of an ``elastic`` block; raises
    ``ValueError`` listing every problem. An unset (or explicit None)
    ``poll_interval`` resolves to ``derive_poll_interval`` of the
    resolved heartbeat cadence. Transport keys the block leaves unset
    fall back to the ``TPUFLOW_ELASTIC_*`` env knobs (validated at read
    time through ``utils/env.py``) before the static defaults."""
    problems = validate_elastic_block(block)
    if problems:
        raise ValueError(
            "invalid elastic config block: " + "; ".join(problems)
        )
    out = {**ELASTIC_DEFAULTS, **block}
    _apply_env_defaults(block, out)
    if out["poll_interval"] is None:
        out["poll_interval"] = derive_poll_interval(
            out["heartbeat_interval"]
        )
    if out["transport"] == "socket" and not out["addr"]:
        raise ValueError(
            "invalid elastic config block: elastic.transport='socket' "
            "needs elastic.addr ('host:port' of the exchange server, "
            "or TPUFLOW_ELASTIC_ADDR)"
        )
    return out


def _apply_env_defaults(block: dict, out: dict) -> None:
    """Fill transport keys absent from the spec block from the
    ``TPUFLOW_ELASTIC_*`` env family (an explicit spec value wins;
    malformed env values raise naming the variable — the fail-loud
    contract every TPUFLOW_* knob family shares)."""
    import os

    from tpuflow.utils.env import env_choice, env_flag, env_num

    if "transport" not in block:
        out["transport"] = env_choice(
            "TPUFLOW_ELASTIC_TRANSPORT", out["transport"],
            ("file", "socket"),
        )
    if "addr" not in block:
        raw = os.environ.get("TPUFLOW_ELASTIC_ADDR")
        if raw is not None and raw.strip():
            if not _valid_addr(raw.strip()):
                raise ValueError(
                    f"invalid TPUFLOW_ELASTIC_ADDR={raw!r}: expected "
                    "a 'host:port' string"
                )
            out["addr"] = raw.strip()
    if "async_push" not in block:
        out["async_push"] = env_flag(
            "TPUFLOW_ELASTIC_ASYNC", out["async_push"]
        )
    if "max_staleness" not in block:
        out["max_staleness"] = env_num(
            "TPUFLOW_ELASTIC_MAX_STALENESS", out["max_staleness"], int,
            minimum=0, form="an integer round count >= 0",
        )
    # Wire-encoding knobs only make sense on the socket transport; the
    # env fallback must not flip them on for a file-backend gang (the
    # validator would have rejected the same combination in a spec).
    if out["transport"] == "socket":
        if "delta" not in block:
            out["delta"] = env_flag("TPUFLOW_ELASTIC_DELTA", out["delta"])
        if "wire_dtype" not in block:
            out["wire_dtype"] = env_choice(
                "TPUFLOW_ELASTIC_WIRE_DTYPE", out["wire_dtype"],
                ("f32", "bf16"),
            )


def make_backend(cfg: dict):
    """The exchange backend a resolved elastic block names:
    ``FileExchange`` over ``cfg['dir']``, ``StoreExchange`` when the
    dir is an object-store URI (``fake://bucket/gang`` — see
    ``tpuflow/storage/``), or ``SocketExchange`` dialing ``cfg['addr']``
    (all imported lazily — the file path must not pull the socket or
    store machinery, and this module stays import-light for the
    preflight spec pass)."""
    if cfg.get("transport", "file") == "socket":
        from tpuflow.elastic.transport import SocketExchange

        return SocketExchange(
            cfg["addr"],
            fallbacks=tuple(cfg.get("fallback_addrs") or ()),
            wire_dtype=cfg.get("wire_dtype", "f32"),
            delta=bool(cfg.get("delta", False)),
        )
    from tpuflow.storage import is_store_uri

    if is_store_uri(cfg["dir"]):
        from tpuflow.elastic.store_backend import StoreExchange
        from tpuflow.storage import resolve_store

        return StoreExchange(*resolve_store(cfg["dir"]))
    from tpuflow.elastic.exchange import FileExchange

    return FileExchange(cfg["dir"])
