"""Elastic data-parallel training: local SGD + coordinated averaging.

ROADMAP item 4, in the SparkNet/DeepSpark mold (PAPERS.md): N worker
processes each run the ordinary single-process ``train()`` loop on a
disjoint shard of the training rows; a small coordinator periodically
averages their parameters and rebroadcasts the mean. The exchange is
deliberately file-based (``exchange.py``) — it needs no collective
runtime at all (the in-worker device mesh, now alive again via
``tpuflow/parallel/compat.py``, is orthogonal) and, more importantly,
tolerates membership churn by construction:

- **Heartbeats + eviction** (``membership.py``): a worker whose
  heartbeat goes stale past the deadline is evicted; averaging proceeds
  over the live set.
- **Restart + rejoin** (``runner.py``): each worker runs under its own
  ``train/supervisor.py`` attempt loop — a SIGKILLed worker is
  relaunched with ``resume=True``, replays from its run checkpoint, and
  is readmitted the moment its heartbeat reappears.
- **Warm start** (``worker.py``): a late joiner with no checkpoint
  adopts the latest published average before its first epoch
  (``train/resume.py::apply_params``), so it starts from gang progress,
  not from init.

Drillable end to end through the resilience registry: the
``elastic.heartbeat`` / ``elastic.push`` / ``elastic.join`` fault sites
(docs/elastic.md has the recipes).

A worker is configured by the spec-validated ``elastic`` block of
``TrainJobConfig`` (``analysis/spec.py`` rejects malformed blocks at
submission)::

    {"dir": "/shared/gang", "worker_id": 0, "n_workers": 3,
     "sync_every": 1, "heartbeat_interval": 0.25,
     "heartbeat_timeout": 30.0, "pull_timeout": 120.0,
     "warm_start": true}

``run_elastic`` (``runner.py``) builds those blocks, launches the
coordinator plus the per-worker supervisors, and averages the workers'
final pushes into the gang's deliverable.
"""

from __future__ import annotations

# Per-knob defaults and validation for the ``elastic`` config block.
# Kept import-light: the preflight spec pass reads these without pulling
# jax-heavy worker machinery.
ELASTIC_DEFAULTS: dict = {
    "sync_every": 1,           # epochs between averaging rounds
    "heartbeat_interval": 0.25,  # seconds between heartbeat writes
    "heartbeat_timeout": 30.0,  # stale-heartbeat eviction deadline
    "round_timeout": 60.0,     # coordinator wait per round
    "pull_timeout": 120.0,     # worker wait for a round's average
    "poll_interval": None,     # file-polling cadence (worker + coord);
    # None = derived from heartbeat_interval (derive_poll_interval) —
    # a fixed 20 Hz directory scan is needless metadata load on
    # NFS-class gang dirs when the gang only beats every few seconds.
    "warm_start": True,        # late joiners adopt the latest average
}

# Polls per heartbeat interval when poll_interval is derived: a scan a
# few times per beat observes every membership/average transition within
# a fraction of a beat, and the scan rate falls automatically as the
# heartbeat cadence relaxes (production gangs on shared filesystems).
# The drill default (heartbeat_interval=0.25) derives the same 0.05 s
# the old hard-coded constant gave — and the drills mostly inject fake
# clocks/sleeps anyway, so they stay wall-clock-free regardless.
POLL_BEATS = 5


def derive_poll_interval(heartbeat_interval: float) -> float:
    """The file-poll cadence for a gang that beats every
    ``heartbeat_interval`` seconds (see ``POLL_BEATS``)."""
    return float(heartbeat_interval) / POLL_BEATS


_REQUIRED = ("dir", "worker_id", "n_workers")


def validate_elastic_block(block) -> list[str]:
    """Every problem with an ``elastic`` config block, as messages
    (empty = valid). Never raises — the preflight spec pass reports all
    findings at once; ``resolve_elastic`` turns them into the fail-loud
    raise for runtime callers."""
    if not isinstance(block, dict):
        return [
            f"elastic must be a dict config block, got "
            f"{type(block).__name__}"
        ]
    out = []
    known = set(_REQUIRED) | set(ELASTIC_DEFAULTS)
    unknown = sorted(set(block) - known)
    if unknown:
        out.append(
            f"unknown elastic keys {unknown}; known: {sorted(known)}"
        )
    for key in _REQUIRED:
        if key not in block:
            out.append(f"elastic.{key} is required")
    if not isinstance(block.get("dir", "x"), str) or block.get("dir") == "":
        out.append("elastic.dir must be a non-empty path string")
    wid, n = block.get("worker_id"), block.get("n_workers")
    if wid is not None and (not isinstance(wid, int) or wid < 0):
        out.append(f"elastic.worker_id must be an int >= 0, got {wid!r}")
    if n is not None and (not isinstance(n, int) or n < 1):
        out.append(f"elastic.n_workers must be an int >= 1, got {n!r}")
    if (
        isinstance(wid, int) and isinstance(n, int)
        and 0 <= wid and 1 <= n and wid >= n
    ):
        out.append(
            f"elastic.worker_id {wid} is outside the gang "
            f"(n_workers={n}; ids are 0-based)"
        )
    if not isinstance(block.get("sync_every", 1), int) or (
        block.get("sync_every", 1) < 1
    ):
        out.append(
            f"elastic.sync_every must be an int >= 1, got "
            f"{block.get('sync_every')!r}"
        )
    for key in (
        "heartbeat_interval", "heartbeat_timeout", "round_timeout",
        "pull_timeout", "poll_interval",
    ):
        value = block.get(key, 1.0)
        if key == "poll_interval" and value is None:
            continue  # None = derive from heartbeat_interval
        if not isinstance(value, (int, float)) or value <= 0:
            out.append(
                f"elastic.{key} must be a positive number (seconds), "
                f"got {value!r}"
            )
    if not isinstance(block.get("warm_start", True), bool):
        out.append(
            f"elastic.warm_start must be a bool, got "
            f"{block.get('warm_start')!r}"
        )
    return out


def resolve_elastic(block: dict) -> dict:
    """Defaults-merged, validated copy of an ``elastic`` block; raises
    ``ValueError`` listing every problem. An unset (or explicit None)
    ``poll_interval`` resolves to ``derive_poll_interval`` of the
    resolved heartbeat cadence."""
    problems = validate_elastic_block(block)
    if problems:
        raise ValueError(
            "invalid elastic config block: " + "; ".join(problems)
        )
    out = {**ELASTIC_DEFAULTS, **block}
    if out["poll_interval"] is None:
        out["poll_interval"] = derive_poll_interval(
            out["heartbeat_interval"]
        )
    return out
