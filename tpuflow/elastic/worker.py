"""Worker-side elastic client: heartbeats, push/adopt sync, warm start.

One instance lives inside each worker's ``train()`` call (wired by
``api/train_api.py`` when ``TrainJobConfig.elastic`` is set):

- ``join(state)`` — pre-fit: registers the worker (``elastic.join``
  fault site), warm-starts a late joiner from the latest published
  average (``train/resume.py::apply_params`` — a resumed restart's own
  run checkpoint, restored later inside ``fit``, takes precedence), and
  starts the heartbeat thread.
- ``sync(epoch, state)`` — the ``FitConfig.sync_fn`` hook, called after
  each epoch's bookkeeping. **Synchronous mode** (default): every
  ``sync_every``-th epoch it pushes the worker's params for round
  ``epoch // sync_every`` and blocks (bounded by ``pull_timeout``) for
  the coordinator's average, which it adopts. **Async mode**
  (``async_push``, the DeepSpark shape): it pushes and immediately
  adopts the FRESHEST published average if one newer than the last
  adoption exists — no round barrier, so a straggling sibling never
  stalls this worker; the coordinator's staleness bound keeps this
  worker's own late pushes from poisoning the average.
- ``finish(state)`` — post-fit: pushes the final params (the runner's
  end-of-gang average reads these), reports a terminal heartbeat
  status, and stops the thread.

All gang I/O goes through ONE exchange backend (``make_backend``): the
file transport or the socket transport (``transport.py``), chosen by
the ``elastic.transport`` config key. Over the socket backend the
worker **degrades instead of dying**: a transport error that survives
the retry policy (a partitioned coordinator, a dead server) marks the
worker degraded, training continues on local params, and the first
successful exchange op afterwards resyncs it — adoption of the newest
average rides the very next sync. Non-transport failures (an injected
``elastic.push`` drill, a structure mismatch) still propagate: those
are the worker's own problem, and hiding them would fake a pass.

A restarted worker needs no special rejoin path: ``resume=True``
restores its checkpoint, its next syncs replay *historic* rounds whose
averages already exist (adopted instantly — the catch-up fast path),
and its fresh heartbeats readmit it to the live set.
"""

from __future__ import annotations

import sys
import threading
import time

from tpuflow.elastic import make_backend, resolve_elastic
from tpuflow.resilience import fault_point

# The fault sites whose FaultInjected firings count as TRANSPORT
# failures (degrade, don't die). elastic.push / elastic.heartbeat /
# elastic.join firings are the worker's own kill drills and propagate.
_TRANSPORT_SITES = frozenset({
    "elastic.transport.send",
    "elastic.transport.recv",
    "elastic.transport.partition",
})


def shard_rows(ds, worker_id: int, n_workers: int):
    """This worker's disjoint row shard (round-robin by row index — the
    SparkNet partitioning, cheap and deterministic for any N)."""
    if n_workers == 1:
        return ds
    x, y = ds.x[worker_id::n_workers], ds.y[worker_id::n_workers]
    if len(x) == 0:
        raise ValueError(
            f"elastic worker {worker_id}/{n_workers} got an empty train "
            f"shard ({ds.n} rows round-robined {n_workers} ways) — "
            "fewer rows than workers"
        )
    return type(ds)(x, y)


class ElasticWorkerClient:
    """See the module docstring. ``clock``/``sleep`` injectable for
    drills; counters go to the process-wide registry."""

    def __init__(
        self, block: dict, *, resuming: bool = False,
        progress_path: str | None = None,
        clock=time.time, sleep=time.sleep,
    ):
        from tpuflow.obs import default_registry

        cfg = resolve_elastic(block)
        # A RESUMING worker keeps epoch-aligned rounds (its checkpoint
        # belongs to the gang's history; replaying old rounds against
        # the published averages is the catch-up fast path). A FRESH
        # late joiner instead offsets its rounds to the join point —
        # otherwise its epoch-1 sync would adopt the gang's ancient
        # round-1 average and clobber the warm start it just did.
        self.resuming = bool(resuming)
        self.round_offset = 0
        # The supervisor's stall watchdog reads the fit loop's progress
        # file, which never changes while this worker blocks in
        # _wait_for_average — so the wait itself pings it (same epoch,
        # changing wait-timestamp), or a coordinator slower than
        # stall_timeout would get healthy workers killed as stalled.
        self.progress_path = progress_path
        self.gang_dir = cfg["dir"]
        self.worker_id = int(cfg["worker_id"])
        self.n_workers = int(cfg["n_workers"])
        self.sync_every = int(cfg["sync_every"])
        self.heartbeat_interval = float(cfg["heartbeat_interval"])
        self.pull_timeout = float(cfg["pull_timeout"])
        self.poll_interval = float(cfg["poll_interval"])
        self.warm_start = bool(cfg["warm_start"])
        self.async_push = bool(cfg["async_push"])
        self.opt_policy = cfg["opt_policy"]
        self.backend = make_backend(cfg)
        self.degraded = False  # transport lost: training local-only
        self._adopted_round = -1  # newest average this worker runs on
        self.clock = clock
        self.sleep = sleep
        self.epoch = 0
        self.round = 0
        self._stop = threading.Event()
        self._terminal = False  # set before the goodbye beat: a laggy
        # heartbeat-thread write should not overwrite the terminal
        # status with a stale "running" record after finish() returns.
        # This narrows the window to a beat already INSIDE its blocked
        # write when finish() runs (no rename-level CAS exists to close
        # that); the residual overwrite costs one eviction deadline,
        # not correctness — the coordinator evicts the stale record.
        self._thread: threading.Thread | None = None
        reg = default_registry()
        self._pushes = reg.counter(
            "elastic_pushes_total", "parameter pushes to the coordinator"
        )
        self._adopts = reg.counter(
            "elastic_adopts_total", "averaged rebroadcasts adopted"
        )
        self._missed = reg.counter(
            "elastic_missed_rounds_total",
            "sync rounds skipped because no average appeared in time",
        )
        self._transport_errors = reg.counter(
            "elastic_transport_errors_total",
            "exchange ops lost to transport failure (post-retry)",
        )
        self._resyncs = reg.counter(
            "elastic_degraded_resyncs_total",
            "recoveries from degraded local-only training",
        )

    # ---- transport guard: degrade, don't die (network backends) ----

    @staticmethod
    def _is_transport_error(e: BaseException) -> bool:
        from tpuflow.resilience import FaultInjected

        if isinstance(e, FaultInjected):
            return getattr(e, "site", None) in _TRANSPORT_SITES
        # RuntimeError: TransportClient's op-level server error — the
        # peer is sick, not this worker.
        return isinstance(e, (OSError, RuntimeError))

    def _guard(self, what: str, fn, *args, **kwargs):
        """Run one exchange op. Returns ``(ok, value)``. On a network
        backend a transport-class failure flips the worker into
        degraded local-only training instead of raising; the first
        success afterwards flips it back (the resync — the caller's
        normal adopt path completes it). File backends pass through
        untouched: a shared-FS error keeps its existing
        supervisor-restart semantics."""
        if not getattr(self.backend, "network", False):
            return True, fn(*args, **kwargs)
        from tpuflow.obs import record_event

        try:
            value = fn(*args, **kwargs)
        except BaseException as e:
            if not self._is_transport_error(e):
                raise
            self._transport_errors.inc(op=what)
            if not self.degraded:
                self.degraded = True
                record_event(
                    "elastic_worker_degraded",
                    worker_id=self.worker_id, op=what,
                    error=f"{type(e).__name__}: {e}",
                )
                print(
                    f"elastic: worker {self.worker_id} lost the "
                    f"coordinator ({what}: {type(e).__name__}: {e}); "
                    "degrading to local training, will resync on "
                    "reconnect",
                    file=sys.stderr,
                )
            return False, None
        if self.degraded:
            self.degraded = False
            self._resyncs.inc()
            record_event(
                "elastic_worker_resynced", worker_id=self.worker_id,
            )
            print(
                f"elastic: worker {self.worker_id} reconnected to the "
                "coordinator; resyncing",
                file=sys.stderr,
            )
        return True, value

    # ---- lifecycle ----

    def join(self, state):
        """Register with the gang and warm-start (see module docstring);
        returns the state to train from."""
        fault_point("elastic.join")
        self._beat(status="joining")
        if self.resuming:
            # A restart must rejoin at the SAME offset its first
            # incarnation recorded (0 for an original member): an
            # in-memory-only offset would reset on restart and leave a
            # late joiner permanently misaligned with the gang's
            # rounds — adopting R-rounds-stale averages every sync.
            ok, got = self._guard(
                "offset", self.backend.get_offset, self.worker_id
            )
            self.round_offset, found = got if ok else (0, False)
            if not found:
                # Every first incarnation writes the record at join, so
                # a missing one means it died before then (or the
                # exchange is unreachable). An original member is fine
                # at 0; a warm-started late joiner is now misaligned —
                # say so rather than train solo silently.
                print(
                    f"elastic: worker {self.worker_id} resuming with no "
                    "recorded round offset (first incarnation died "
                    "before join completed); assuming 0 — a late "
                    "joiner's rounds may lag the gang",
                    file=sys.stderr,
                )
        elif self.warm_start:
            ok, latest = self._guard(
                "warm_start", self.backend.latest_average
            )
            if ok and latest is not None:
                round, leaves = latest
                state = self._adopt(state, leaves)
                self.round_offset = round
                self._adopted_round = round
                print(
                    f"elastic: worker {self.worker_id} warm-started from "
                    f"round {round}'s average",
                    file=sys.stderr,
                )
        if not self.resuming:
            self._guard(
                "offset", self.backend.set_offset,
                self.worker_id, self.round_offset,
            )
        self._beat(status="running")
        self._thread = threading.Thread(
            target=self._heartbeat_loop,
            name=f"tpuflow-elastic-hb-{self.worker_id}",
            daemon=True,
        )
        self._thread.start()
        return state

    def finish(self, state=None, failed: bool = False) -> None:
        """Terminal heartbeat + final push; idempotent, never raises
        into the caller's (possibly already failing) exit path."""
        self._stop.set()
        self._terminal = True
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            if state is not None and not failed:
                from tpuflow.elastic.exchange import FINAL_ROUND

                self._guard(
                    "final_push", self.backend.push,
                    FINAL_ROUND, self.worker_id, state.params,
                )
            self._beat(status="failed" if failed else "done")
        except BaseException as e:
            print(
                f"elastic: worker {self.worker_id} goodbye failed "
                f"({type(e).__name__}: {e}); the eviction deadline "
                "covers it",
                file=sys.stderr,
            )

    # ---- the FitConfig.sync_fn hook ----

    def sync(self, epoch: int, state):
        self.epoch = epoch
        if epoch % self.sync_every:
            self._beat()
            return state
        round = self.round_offset + epoch // self.sync_every
        self.round = round
        self._beat()
        if self.async_push:
            return self._sync_async(round, state)
        ok, published = self._guard(
            "pull", self.backend.read_average, round
        )
        if not ok:
            self._missed.inc()
            return state
        if published is not None:
            # Catch-up fast path: the round is already averaged and
            # rebroadcast (this worker is replaying history after a
            # restart) — pushing a full param payload nobody will ever
            # read wastes exchange bandwidth; just adopt and move on.
            return self._adopt(state, published, round=round)
        if self._gang_moved_past(round):
            # The round's average is gone (pruned history): nothing to
            # adopt — and nothing to push, since the round will never
            # be re-averaged.
            self._missed.inc()
            return state
        ok, _ = self._guard(
            "push", self.backend.push, round, self.worker_id,
            self._push_payload(state),
        )
        if not ok:
            self._missed.inc()
            return state
        self._pushes.inc()
        leaves = self._wait_for_average(round)
        if leaves is None:
            self._missed.inc()
            if not self._gang_moved_past(round):
                print(
                    f"elastic: worker {self.worker_id} saw no average "
                    f"for round {round} within {self.pull_timeout:g}s; "
                    "continuing on local params",
                    file=sys.stderr,
                )
            return state
        return self._adopt(state, leaves, round=round)

    def _sync_async(self, round: int, state):
        """The DeepSpark-shaped sync: push when ready, adopt the
        freshest average if one newer than the last adoption exists,
        never block on a round barrier. A straggling sibling costs this
        worker nothing; this worker's own late pushes are the
        coordinator's staleness bound's problem, not a barrier's."""
        ok, _ = self._guard(
            "push", self.backend.push, round, self.worker_id,
            self._push_payload(state),
        )
        if ok:
            self._pushes.inc()
        ok, latest = self._guard("pull", self.backend.latest_average)
        if not ok or latest is None:
            self._missed.inc()
            return state
        latest_round, leaves = latest
        if latest_round <= self._adopted_round:
            return state  # nothing fresher than what we already run on
        return self._adopt(state, leaves, round=latest_round)

    def _push_payload(self, state):
        """What a non-final push ships. Params, or — under
        ``opt_policy="average"`` — the combined tree
        ``{"m": [floating opt leaves], "p": params}``: dict keys sort
        ``"m" < "p"``, so the moment leaves flatten FIRST at every
        tier's fold and the adopt-side split is purely positional.
        Non-floating opt leaves (step counters) never ride the wire —
        averaging a count is meaningless and each worker keeps its own
        decay-schedule position."""
        if self.opt_policy != "average":
            return state.params
        import jax
        import jax.numpy as jnp

        moments = [
            leaf
            for leaf in jax.tree_util.tree_leaves(state.opt_state)
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
        ]
        return {"m": moments, "p": state.params}

    def _adopt(self, state, leaves, round: int | None = None):
        """Replace the live params with a rebroadcast's leaves — THE
        one adoption path (warm start, catch-up, per-round sync, and
        async freshest-adopt all ride it), structure-checked by
        ``apply_params``. ``opt_policy`` decides what happens to the
        optimizer state alongside (docs/elastic.md): carry it (the
        historical behavior), reset its momenta for the new params, or
        — when the gang ships combined moments+params payloads — adopt
        the averaged moments too."""
        import jax

        from tpuflow.elastic.exchange import unflatten_like
        from tpuflow.train.resume import apply_params

        n_params = len(jax.tree_util.tree_leaves(state.params))
        if self.opt_policy == "average" and len(leaves) > n_params:
            state = self._adopt_with_moments(state, leaves, n_params)
        else:
            state = apply_params(
                state, unflatten_like(state.params, leaves)
            )
            if self.opt_policy == "reset":
                from tpuflow.train.optim import reset_opt_state

                state = reset_opt_state(state)
        self._adopts.inc()
        if round is not None:
            self._adopted_round = max(self._adopted_round, round)
            # Delta-encoding bookkeeping (socket transport): the newly
            # adopted average is the base the next push is encoded
            # against — both ends hold the same f32 leaves.
            note = getattr(self.backend, "note_adopted", None)
            if note is not None:
                note(round, leaves)
        return state

    def _adopt_with_moments(self, state, leaves, n_params: int):
        """Split a combined average (``_push_payload``'s layout: moment
        leaves first, then params) and adopt both halves — params via
        the structure-checked ``apply_params``, moments merged back
        into the floating slots of this worker's optimizer state
        (counters stay local), cast to each slot's dtype."""
        import jax
        import jax.numpy as jnp

        from tpuflow.elastic.exchange import unflatten_like
        from tpuflow.train.resume import apply_params

        opt_leaves, opt_def = jax.tree_util.tree_flatten(state.opt_state)
        float_slots = [
            i for i, leaf in enumerate(opt_leaves)
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
        ]
        n_moments = len(leaves) - n_params
        if n_moments != len(float_slots):
            raise ValueError(
                f"averaged payload carries {n_moments} moment leaves "
                f"but this worker's optimizer state has "
                f"{len(float_slots)} floating leaves — mixed "
                "opt_policy or optimizer config across the gang?"
            )
        state = apply_params(
            state,
            unflatten_like(state.params, list(leaves[n_moments:])),
        )
        merged = list(opt_leaves)
        for slot, leaf in zip(float_slots, leaves[:n_moments]):
            old = jnp.asarray(opt_leaves[slot])
            got = jnp.asarray(leaf)
            if got.shape != old.shape:
                raise ValueError(
                    f"averaged moment leaf {slot} has shape "
                    f"{tuple(got.shape)} but this worker's is "
                    f"{tuple(old.shape)} — mixed optimizer config "
                    "across the gang?"
                )
            merged[slot] = got.astype(old.dtype)
        return state.replace(
            opt_state=jax.tree_util.tree_unflatten(opt_def, merged)
        )

    def _gang_moved_past(self, round: int) -> bool:
        """True when the gang's newest published round is beyond
        ``round`` while ``round``'s own average is absent — i.e. the
        history this worker is replaying was pruned."""
        ok, latest = self._guard("pull", self.backend.latest_round)
        return ok and latest is not None and latest > round

    def _wait_for_average(self, round: int):
        deadline = self.clock() + self.pull_timeout
        last_ping = self.clock()
        while True:
            ok, leaves = self._guard(
                "pull", self.backend.read_average, round
            )
            if ok and leaves is not None:
                return leaves
            # A transport outage inside the wait keeps polling until
            # pull_timeout: a partition shorter than the window costs
            # nothing, a longer one degrades this round to local
            # training (the same miss a slow coordinator causes).
            if ok and self._gang_moved_past(round):
                # Skipping a pruned historic round immediately beats
                # burning pull_timeout on a file that cannot appear.
                return None
            if self.clock() > deadline:
                return None
            if (
                self.progress_path is not None
                and self.clock() - last_ping >= 1.0
            ):
                self._ping_progress(round)
                last_ping = self.clock()
            self.sleep(self.poll_interval)

    def _ping_progress(self, round: int) -> None:
        """Touch the supervisor's progress file during a sync wait —
        same completed-epoch number (the wait runs BEFORE this epoch's
        run checkpoint, so epoch-1 is the last durable one), changing
        timestamp, so the stall watchdog sees liveness. Delegates to
        the fit loop's one progress writer; single-threaded with it by
        construction (sync runs inside the fit loop's own thread)."""
        from tpuflow.train.loop import _write_progress

        _write_progress(
            self.progress_path, max(self.epoch - 1, 0),
            elastic_wait_round=round,
        )

    # ---- heartbeats ----

    def _beat(self, status: str = "running") -> None:
        """One guarded heartbeat. Transport loss degrades (the beats
        simply stop ARRIVING — which is exactly what the coordinator's
        eviction deadline measures); a non-transport failure (the
        ``elastic.heartbeat`` drill site) propagates to the caller —
        main-thread beats kill the attempt, the daemon loop's die."""
        self._guard(
            "heartbeat", self.backend.write_heartbeat, self.worker_id,
            epoch=self.epoch, round=self.round, status=status,
            clock=self.clock,
        )

    def _heartbeat_loop(self) -> None:
        # Covers liveness through long compiles and slow epochs. A
        # TRANSPORT failure is absorbed by the guard (beats resume when
        # the partition heals — the degrade/resync story); an injected
        # elastic.heartbeat fault (or a genuinely dead filesystem)
        # kills the thread — which IS the eviction drill — rather than
        # crashing the training thread.
        while not self._stop.wait(self.heartbeat_interval):
            if self._terminal:
                return  # never overwrite the goodbye with "running"
            try:
                self._beat()
            except BaseException as e:
                print(
                    f"elastic: worker {self.worker_id} heartbeat thread "
                    f"dying ({type(e).__name__}: {e}); the worker will "
                    "be evicted on the stale-heartbeat deadline",
                    file=sys.stderr,
                )
                return
