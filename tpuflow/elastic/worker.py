"""Worker-side elastic client: heartbeats, push/adopt sync, warm start.

One instance lives inside each worker's ``train()`` call (wired by
``api/train_api.py`` when ``TrainJobConfig.elastic`` is set):

- ``join(state)`` — pre-fit: registers the worker (``elastic.join``
  fault site), warm-starts a late joiner from the latest published
  average (``train/resume.py::apply_params`` — a resumed restart's own
  run checkpoint, restored later inside ``fit``, takes precedence), and
  starts the heartbeat thread.
- ``sync(epoch, state)`` — the ``FitConfig.sync_fn`` hook, called after
  each epoch's bookkeeping: every ``sync_every``-th epoch it pushes the
  worker's params for round ``epoch // sync_every`` and blocks (bounded
  by ``pull_timeout``) for the coordinator's average, which it adopts.
  A round whose average never appears is *skipped*, not fatal — the
  worker continues on local params and re-syncs next round, so a slow
  or briefly-absent coordinator degrades cadence, never the run.
- ``finish(state)`` — post-fit: pushes the final params (the runner's
  end-of-gang average reads these), reports a terminal heartbeat
  status, and stops the thread.

A restarted worker needs no special rejoin path: ``resume=True``
restores its checkpoint, its next syncs replay *historic* rounds whose
averages already exist (adopted instantly — the catch-up fast path),
and its fresh heartbeats readmit it to the live set.
"""

from __future__ import annotations

import sys
import threading
import time

from tpuflow.elastic import exchange, resolve_elastic
from tpuflow.elastic.membership import write_heartbeat
from tpuflow.resilience import fault_point


def shard_rows(ds, worker_id: int, n_workers: int):
    """This worker's disjoint row shard (round-robin by row index — the
    SparkNet partitioning, cheap and deterministic for any N)."""
    if n_workers == 1:
        return ds
    x, y = ds.x[worker_id::n_workers], ds.y[worker_id::n_workers]
    if len(x) == 0:
        raise ValueError(
            f"elastic worker {worker_id}/{n_workers} got an empty train "
            f"shard ({ds.n} rows round-robined {n_workers} ways) — "
            "fewer rows than workers"
        )
    return type(ds)(x, y)


class ElasticWorkerClient:
    """See the module docstring. ``clock``/``sleep`` injectable for
    drills; counters go to the process-wide registry."""

    def __init__(
        self, block: dict, *, resuming: bool = False,
        progress_path: str | None = None,
        clock=time.time, sleep=time.sleep,
    ):
        from tpuflow.obs import default_registry

        cfg = resolve_elastic(block)
        # A RESUMING worker keeps epoch-aligned rounds (its checkpoint
        # belongs to the gang's history; replaying old rounds against
        # the published averages is the catch-up fast path). A FRESH
        # late joiner instead offsets its rounds to the join point —
        # otherwise its epoch-1 sync would adopt the gang's ancient
        # round-1 average and clobber the warm start it just did.
        self.resuming = bool(resuming)
        self.round_offset = 0
        # The supervisor's stall watchdog reads the fit loop's progress
        # file, which never changes while this worker blocks in
        # _wait_for_average — so the wait itself pings it (same epoch,
        # changing wait-timestamp), or a coordinator slower than
        # stall_timeout would get healthy workers killed as stalled.
        self.progress_path = progress_path
        self.gang_dir = cfg["dir"]
        self.worker_id = int(cfg["worker_id"])
        self.n_workers = int(cfg["n_workers"])
        self.sync_every = int(cfg["sync_every"])
        self.heartbeat_interval = float(cfg["heartbeat_interval"])
        self.pull_timeout = float(cfg["pull_timeout"])
        self.poll_interval = float(cfg["poll_interval"])
        self.warm_start = bool(cfg["warm_start"])
        self.clock = clock
        self.sleep = sleep
        self.epoch = 0
        self.round = 0
        self._stop = threading.Event()
        self._terminal = False  # set before the goodbye beat: a laggy
        # heartbeat-thread write should not overwrite the terminal
        # status with a stale "running" record after finish() returns.
        # This narrows the window to a beat already INSIDE its blocked
        # write when finish() runs (no rename-level CAS exists to close
        # that); the residual overwrite costs one eviction deadline,
        # not correctness — the coordinator evicts the stale record.
        self._thread: threading.Thread | None = None
        reg = default_registry()
        self._pushes = reg.counter(
            "elastic_pushes_total", "parameter pushes to the coordinator"
        )
        self._adopts = reg.counter(
            "elastic_adopts_total", "averaged rebroadcasts adopted"
        )
        self._missed = reg.counter(
            "elastic_missed_rounds_total",
            "sync rounds skipped because no average appeared in time",
        )

    # ---- lifecycle ----

    def join(self, state):
        """Register with the gang and warm-start (see module docstring);
        returns the state to train from."""
        fault_point("elastic.join")
        self._beat(status="joining")
        if self.resuming:
            # A restart must rejoin at the SAME offset its first
            # incarnation recorded (0 for an original member): an
            # in-memory-only offset would reset on restart and leave a
            # late joiner permanently misaligned with the gang's
            # rounds — adopting R-rounds-stale averages every sync.
            self.round_offset, found = self._read_offset()
            if not found:
                # Every first incarnation writes the file at join, so a
                # missing one means it died before then. An original
                # member is fine at 0; a warm-started late joiner is
                # now misaligned — say so rather than train solo
                # silently.
                print(
                    f"elastic: worker {self.worker_id} resuming with no "
                    "recorded round offset (first incarnation died "
                    "before join completed); assuming 0 — a late "
                    "joiner's rounds may lag the gang",
                    file=sys.stderr,
                )
        elif self.warm_start:
            latest = exchange.latest_average(self.gang_dir)
            if latest is not None:
                round, leaves = latest
                state = self._adopt(state, leaves)
                self.round_offset = round
                print(
                    f"elastic: worker {self.worker_id} warm-started from "
                    f"round {round}'s average",
                    file=sys.stderr,
                )
        if not self.resuming:
            self._write_offset()
        self._beat(status="running")
        self._thread = threading.Thread(
            target=self._heartbeat_loop,
            name=f"tpuflow-elastic-hb-{self.worker_id}",
            daemon=True,
        )
        self._thread.start()
        return state

    def finish(self, state=None, failed: bool = False) -> None:
        """Terminal heartbeat + final push; idempotent, never raises
        into the caller's (possibly already failing) exit path."""
        self._stop.set()
        self._terminal = True
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            if state is not None and not failed:
                exchange.push_params(
                    self.gang_dir, exchange.FINAL_ROUND, self.worker_id,
                    state.params,
                )
            self._beat(status="failed" if failed else "done")
        except BaseException as e:
            print(
                f"elastic: worker {self.worker_id} goodbye failed "
                f"({type(e).__name__}: {e}); the eviction deadline "
                "covers it",
                file=sys.stderr,
            )

    # ---- the FitConfig.sync_fn hook ----

    def sync(self, epoch: int, state):
        self.epoch = epoch
        if epoch % self.sync_every:
            self._beat()
            return state
        round = self.round_offset + epoch // self.sync_every
        self.round = round
        self._beat()
        published = exchange.read_average(self.gang_dir, round)
        if published is not None:
            # Catch-up fast path: the round is already averaged and
            # rebroadcast (this worker is replaying history after a
            # restart) — pushing a full param file nobody will ever
            # read wastes shared-FS I/O; just adopt and move on.
            return self._adopt(state, published)
        if self._gang_moved_past(round):
            # The round's average is gone (pruned history): nothing to
            # adopt — and nothing to push, since the round will never
            # be re-averaged.
            self._missed.inc()
            return state
        exchange.push_params(self.gang_dir, round, self.worker_id, state.params)
        self._pushes.inc()
        leaves = self._wait_for_average(round)
        if leaves is None:
            self._missed.inc()
            if not self._gang_moved_past(round):
                print(
                    f"elastic: worker {self.worker_id} saw no average "
                    f"for round {round} within {self.pull_timeout:g}s; "
                    "continuing on local params",
                    file=sys.stderr,
                )
            return state
        return self._adopt(state, leaves)

    def _adopt(self, state, leaves):
        """Replace the live params with a rebroadcast's leaves — THE
        one adoption path (warm start, catch-up, and per-round sync all
        ride it), structure-checked by ``apply_params``."""
        from tpuflow.train.resume import apply_params

        state = apply_params(
            state, exchange.unflatten_like(state.params, leaves)
        )
        self._adopts.inc()
        return state

    def _gang_moved_past(self, round: int) -> bool:
        """True when the gang's newest published round is beyond
        ``round`` while ``round``'s own average is absent — i.e. the
        history this worker is replaying was pruned."""
        latest = exchange.latest_round(self.gang_dir)
        return latest is not None and latest > round

    def _wait_for_average(self, round: int):
        deadline = self.clock() + self.pull_timeout
        last_ping = self.clock()
        while True:
            leaves = exchange.read_average(self.gang_dir, round)
            if leaves is not None:
                return leaves
            if self._gang_moved_past(round):
                # Skipping a pruned historic round immediately beats
                # burning pull_timeout on a file that cannot appear.
                return None
            if self.clock() > deadline:
                return None
            if (
                self.progress_path is not None
                and self.clock() - last_ping >= 1.0
            ):
                self._ping_progress(round)
                last_ping = self.clock()
            self.sleep(self.poll_interval)

    def _ping_progress(self, round: int) -> None:
        """Touch the supervisor's progress file during a sync wait —
        same completed-epoch number (the wait runs BEFORE this epoch's
        run checkpoint, so epoch-1 is the last durable one), changing
        timestamp, so the stall watchdog sees liveness. Delegates to
        the fit loop's one progress writer; single-threaded with it by
        construction (sync runs inside the fit loop's own thread)."""
        from tpuflow.train.loop import _write_progress

        _write_progress(
            self.progress_path, max(self.epoch - 1, 0),
            elastic_wait_round=round,
        )

    # ---- the persisted round offset (survives restarts) ----

    def _offset_path(self) -> str:
        # Deliberately NOT *.json: the membership scanner globs
        # members/*.json and this file is not a heartbeat.
        import os

        return os.path.join(
            self.gang_dir, "members", f"{self.worker_id}.offset"
        )

    def _write_offset(self) -> None:
        import os

        from tpuflow.utils.paths import atomic_write_json

        path = self._offset_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic_write_json(path, {"round_offset": self.round_offset})

    def _read_offset(self) -> tuple[int, bool]:
        """``(offset, found)`` — found=False means no readable record
        (the caller decides whether the 0 fallback is benign)."""
        import json

        try:
            with open(self._offset_path(), encoding="utf-8") as f:
                return int(json.load(f)["round_offset"]), True
        except (OSError, ValueError, TypeError, KeyError,
                json.JSONDecodeError):
            return 0, False

    # ---- heartbeats ----

    def _beat(self, status: str = "running") -> None:
        write_heartbeat(
            self.gang_dir, self.worker_id,
            epoch=self.epoch, round=self.round, status=status,
            clock=self.clock,
        )

    def _heartbeat_loop(self) -> None:
        # Covers liveness through long compiles and slow epochs; an
        # injected elastic.heartbeat fault (or a genuinely dead
        # filesystem) stops the beats — which IS the eviction drill —
        # rather than crashing the training thread.
        while not self._stop.wait(self.heartbeat_interval):
            if self._terminal:
                return  # never overwrite the goodbye with "running"
            try:
                self._beat()
            except BaseException as e:
                print(
                    f"elastic: worker {self.worker_id} heartbeat thread "
                    f"dying ({type(e).__name__}: {e}); the worker will "
                    "be evicted on the stale-heartbeat deadline",
                    file=sys.stderr,
                )
                return
