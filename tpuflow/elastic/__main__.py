"""Shell entry for an elastic gang::

    python -m tpuflow.elastic spec.json --workers 3 --sync-every 1

The spec is the same JSON job spec ``POST /jobs`` and ``supervise()``
accept (it must set ``storagePath``); the runner adds the per-worker
``elastic`` blocks and checkpoint trees itself.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpuflow.elastic.runner import MODES, run_elastic


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpuflow.elastic",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("spec", help="JSON job-spec file (serve.py contract)")
    ap.add_argument("--workers", type=int, default=2, metavar="N",
                    help="gang size (worker processes)")
    ap.add_argument("--mode", choices=MODES, default="supervised",
                    help="supervised: child processes under the restart "
                    "loop (default); inprocess: threads, no restarts")
    ap.add_argument("--gang-dir", default=None,
                    help="shared coordination dir "
                    "(default {storagePath}/elastic)")
    ap.add_argument("--sync-every", type=int, default=1,
                    help="epochs between averaging rounds")
    ap.add_argument("--transport", choices=("file", "socket"),
                    default=None,
                    help="exchange transport: shared gang dir (file; "
                    "the default) or a coordinator-hosted TCP exchange "
                    "server (socket; implied by --fanout)")
    ap.add_argument("--fanout", type=int, default=None, metavar="K",
                    help="tree aggregation: fold pushes through "
                    "mid-tier aggregators with this subtree fan-out "
                    "(0 = star hub; implies --transport socket; "
                    "default TPUFLOW_ELASTIC_FANOUT or 0)")
    ap.add_argument("--tiers", type=int, default=None,
                    help="aggregator tier count for --fanout "
                    "(default TPUFLOW_ELASTIC_TIER or 1)")
    ap.add_argument("--delta", action="store_true", default=None,
                    help="delta-encode pushes against the last adopted "
                    "average (socket transport)")
    ap.add_argument("--wire-dtype", choices=("f32", "bf16"),
                    default=None,
                    help="push payload dtype on the wire (socket "
                    "transport; masters and folds stay f32)")
    ap.add_argument("--opt-policy",
                    choices=("carry", "reset", "average"),
                    default="carry",
                    help="optimizer state across an adoption: keep "
                    "local moments, re-init them, or gang-average "
                    "floating moments alongside the params")
    ap.add_argument("--async-push", action="store_true",
                    help="asynchronous push with a staleness bound "
                    "(DeepSpark style): no round barrier")
    ap.add_argument("--max-staleness", type=int, default=2,
                    help="async only: reject pushes more than this many "
                    "rounds behind the gang's frontier")
    ap.add_argument("--heartbeat-timeout", type=float, default=30.0,
                    help="stale-heartbeat eviction deadline, seconds")
    ap.add_argument("--round-timeout", type=float, default=60.0,
                    help="coordinator wait per averaging round, seconds")
    ap.add_argument("--max-restarts", type=int, default=2,
                    help="per-worker supervisor restart budget")
    ap.add_argument("--stall-timeout", type=float, default=None,
                    help="per-worker progress watchdog, seconds")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    from tpuflow.storage import read_json

    spec = read_json(args.spec)
    # --fanout implies the socket transport (the tree IS a wire
    # topology); an explicit --transport still wins, so the
    # fanout-over-file mistake dies with the runner's message.
    transport = args.transport or (
        "socket" if args.fanout else "file"
    )
    try:
        result = run_elastic(
            spec,
            args.workers,
            gang_dir=args.gang_dir,
            mode=args.mode,
            transport=transport,
            async_push=args.async_push,
            max_staleness=args.max_staleness,
            fanout=args.fanout,
            tiers=args.tiers,
            delta=args.delta,
            wire_dtype=args.wire_dtype,
            opt_policy=args.opt_policy,
            sync_every=args.sync_every,
            heartbeat_timeout=args.heartbeat_timeout,
            round_timeout=args.round_timeout,
            max_restarts=args.max_restarts,
            stall_timeout=args.stall_timeout,
            verbose=not args.quiet,
        )
    except ValueError as e:
        # e.g. a stale gang dir from a previous run under the same
        # storagePath: a submission error, not a traceback — the same
        # UX as cli.py --elastic.
        print(f"tpuflow.elastic: {e}", file=sys.stderr)
        return 2
    print(json.dumps(result.summary()))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
