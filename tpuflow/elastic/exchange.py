"""File-based parameter exchange: worker pushes, averaged rebroadcasts.

SparkNet's training strategy (PAPERS.md, arXiv:1511.06051) is local SGD
with driver-coordinated parameter averaging: workers train independently
for a fixed number of steps, the driver averages their parameters and
broadcasts the average back. The transport here is deliberately the
filesystem — NOT ``jax.distributed`` collectives — for two load-bearing
reasons:

- **It works today.** The installed jax's mesh construction is broken
  (ROADMAP item 1); a collective-based exchange would be dead on
  arrival. Files need nothing but a shared directory.
- **It tolerates churn by construction.** A collective has a fixed
  communicator: one dead rank wedges everyone. A directory of
  ``push/r000007/{worker_id}.npz`` files has no membership baked in —
  the coordinator averages whichever files the live set produced, and a
  worker that died mid-push left only an invisible temp file.

Layout under the gang dir::

    push/r{round:06d}/{worker_id}.npz   one worker's params for a round
    push/final/{worker_id}.npz          a finished worker's last params
    avg/r{round:06d}.npz                the averaged rebroadcast
    avg/LATEST                          JSON {round, path, time}

Params ride as their flattened pytree leaves (``arr_0..arr_{n-1}`` in
tree-flatten order) plus a leaf count and a CRC32 over every leaf's
shape/dtype/bytes; the reader restores against the live state's own
treedef, so structure mismatches fail loudly instead of silently
mis-zipping leaves, and a payload whose checksum disagrees reads as
*unreadable* (a ``ValueError``), not as trusted data — ``np.load``
alone would happily hand a truncated socket read or a torn NFS page to
the averaging math. Every write is atomic (tmp + rename): a reader
never sees a torn file, only a missing one — "not pushed yet" and
"crashed mid-push" are deliberately the same observation.

This module also defines :class:`FileExchange` — the file transport
packaged behind the backend interface that ``SocketExchange``
(``transport.py``) implements over TCP, so the worker and coordinator
speak to ONE contract whatever carries the bytes. The file backend
stays the drill/reference implementation.

The ``elastic.push`` fault site fires inside every push (index = round,
so ``at=K`` drills "the worker that dies pushing round K").
"""

from __future__ import annotations

import io
import json
import os
import time
import zlib

import numpy as np

from tpuflow.resilience import fault_point

PUSH_DIR = "push"
AVG_DIR = "avg"
FINAL_ROUND = "final"
LATEST = "LATEST"


def _round_name(round) -> str:
    return round if round == FINAL_ROUND else f"r{int(round):06d}"


def push_dir(gang_dir: str, round) -> str:
    return os.path.join(gang_dir, PUSH_DIR, _round_name(round))


def avg_path(gang_dir: str, round: int) -> str:
    return os.path.join(gang_dir, AVG_DIR, _round_name(round) + ".npz")


def flatten_params(params) -> list[np.ndarray]:
    """Params pytree -> host numpy leaves in tree-flatten order."""
    import jax

    leaves = jax.tree_util.tree_leaves(params)
    return [np.asarray(leaf) for leaf in leaves]


def unflatten_like(params, leaves: list[np.ndarray]):
    """Leaves (tree-flatten order) -> a pytree shaped like ``params``.

    Leaf count and per-leaf shapes are checked against the template — a
    file from a differently-configured model must fail loudly, not
    silently mis-assign weights.
    """
    import jax

    template_leaves, treedef = jax.tree_util.tree_flatten(params)
    if len(leaves) != len(template_leaves):
        raise ValueError(
            f"param exchange file carries {len(leaves)} leaves; this "
            f"model has {len(template_leaves)} — different model/config?"
        )
    cast = []
    for i, (got, want) in enumerate(zip(leaves, template_leaves)):
        want_shape = tuple(np.shape(want))
        if tuple(got.shape) != want_shape:
            raise ValueError(
                f"param exchange leaf {i} has shape {tuple(got.shape)}; "
                f"this model expects {want_shape} — different "
                "model/config?"
            )
        # .dtype, not np.asarray(want).dtype: the template leaves are
        # the LIVE device params, and asarray would pull every one of
        # them to host just to read a dtype — doubling host transfer
        # per adopt.
        cast.append(np.asarray(got, dtype=getattr(want, "dtype", None)))
    return jax.tree_util.tree_unflatten(treedef, cast)


def leaves_crc32(leaves: list[np.ndarray]) -> int:
    """CRC32 over every leaf's shape, dtype, and raw bytes — the
    integrity stamp both transports carry (npz field / frame header)."""
    crc = 0
    for leaf in leaves:
        a = np.ascontiguousarray(leaf)
        crc = zlib.crc32(repr((a.shape, a.dtype.str)).encode(), crc)
        crc = zlib.crc32(a.tobytes(), crc)
    return crc & 0xFFFFFFFF


def _savez(f, leaves: list[np.ndarray]) -> None:
    np.savez(f, n_leaves=np.int64(len(leaves)),
             crc32=np.uint64(leaves_crc32(leaves)),
             **{f"arr_{i}": leaf for i, leaf in enumerate(leaves)})


def _loadz(f) -> list[np.ndarray]:
    with np.load(f) as z:
        n = int(z["n_leaves"])
        leaves = [z[f"arr_{i}"] for i in range(n)]
        if "crc32" in z.files:  # pre-checksum files stay readable
            want = int(z["crc32"])
            got = leaves_crc32(leaves)
            if got != want:
                raise ValueError(
                    f"param payload checksum mismatch (crc32 {got:#010x}"
                    f" != recorded {want:#010x}) — torn file or "
                    "truncated read; refusing to trust np.load's bytes"
                )
    return leaves


def encode_leaves(leaves: list[np.ndarray]) -> bytes:
    """Leaves -> checksummed npz bytes (the socket transport's payload
    encoding — the SAME format the file backend writes to disk)."""
    buf = io.BytesIO()
    _savez(buf, leaves)
    return buf.getvalue()


def decode_leaves(data: bytes) -> list[np.ndarray]:
    """Checksummed npz bytes -> leaves; raises ``ValueError`` on a
    corrupt or truncated payload."""
    return _loadz(io.BytesIO(data))


def _write_npz(path: str, leaves: list[np.ndarray]) -> None:
    import threading

    os.makedirs(os.path.dirname(path), exist_ok=True)
    # (pid, thread)-unique like utils.paths.atomic_write_json: the
    # in-process runner mode runs workers as threads of one pid.
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "wb") as f:
        _savez(f, leaves)
        f.flush()
        os.fsync(f.fileno())  # data before name: no torn-write publish
    os.replace(tmp, path)


def _read_npz(path: str) -> list[np.ndarray]:
    with open(path, "rb") as f:
        return _loadz(f)


def write_leaves(path: str, leaves: list[np.ndarray]) -> str:
    """Atomically write a leaves file outside the push/avg layout (the
    runner's final-average deliverable)."""
    _write_npz(path, leaves)
    return path


def push_params(gang_dir: str, round, worker_id: int, params) -> str:
    """Write this worker's params for ``round`` (atomic); returns the
    path. ``round`` may be the string ``"final"`` for the end-of-run
    push the runner's final average reads."""
    index = None if round == FINAL_ROUND else int(round)
    fault_point("elastic.push", index=index)
    path = os.path.join(push_dir(gang_dir, round), f"{worker_id}.npz")
    _write_npz(path, flatten_params(params))
    return path


def pushed_ids(gang_dir: str, round) -> set[int]:
    """Worker IDs that have completed a push for ``round``."""
    try:
        names = os.listdir(push_dir(gang_dir, round))
    except OSError:
        return set()
    out = set()
    for name in names:
        stem, ext = os.path.splitext(name)
        if ext == ".npz" and stem.isdigit():
            out.add(int(stem))
    return out


def average_leaf_sets(
    pairs: list[tuple[int, list[np.ndarray]]],
    *,
    weights: list[float] | None = None,
    context: str = "",
) -> tuple[list[np.ndarray] | None, list[int]]:
    """Mean (optionally weighted — the async staleness down-weighting)
    of several workers' leaf sets. THE averaging math, shared by every
    backend: ``pairs`` is ``[(worker_id, leaves), ...]``; returns
    ``(leaves, worker_ids_averaged)`` with leaves None when ``pairs``
    is empty. Leaf counts and shapes are cross-checked — same depth +
    different widths would otherwise either crash with a bare numpy
    broadcast error or, worse, broadcast INTO the accumulator and
    publish a silently wrong average for every worker to adopt."""
    acc: list[np.ndarray] | None = None
    used: list[int] = []
    total_w = 0.0
    for k, (wid, leaves) in enumerate(pairs):
        w = 1.0 if weights is None else float(weights[k])
        if w <= 0.0:
            continue
        if acc is None:
            acc = [np.asarray(leaf, np.float64) * w for leaf in leaves]
        else:
            if len(leaves) != len(acc):
                raise ValueError(
                    f"worker {wid}'s push {context}has "
                    f"{len(leaves)} leaves; others pushed {len(acc)} — "
                    "mixed model configs in one gang"
                )
            for i, (a, leaf) in enumerate(zip(acc, leaves)):
                if tuple(np.shape(leaf)) != tuple(a.shape):
                    raise ValueError(
                        f"worker {wid}'s push {context}leaf "
                        f"{i} has shape {tuple(np.shape(leaf))}; others "
                        f"pushed {tuple(a.shape)} — mixed model configs "
                        "in one gang"
                    )
                a += w * leaf
        total_w += w
        used.append(wid)
    if acc is None:
        return None, []
    return [np.asarray(a / total_w, np.float32) for a in acc], used


def dedupe_weighted_records(
    recs: list[tuple[int, list[np.ndarray], float, tuple[int, ...]]],
) -> list[tuple[int, list[np.ndarray], float, tuple[int, ...]]]:
    """Drop direct worker pushes already covered by another pusher's
    partial average; ``recs`` is ``[(pusher_id, leaves, weight,
    covers), ...]`` and the survivors come back in input order.

    A lost response makes a worker's FailoverClient re-send a push the
    first receiver actually stored (``transport.py``): the round's fold
    then sees that worker twice — once inside its aggregator's weighted
    partial, once as a direct weight-1 record — and the weighted mean
    is biased toward it. A direct record is recognizable (``covers`` is
    exactly its own pusher id); when any OTHER record's covers already
    include that worker, the direct record is redundant and dropped
    before the fold. Partial-vs-partial overlap (two aggregators each
    folding the same worker after a sibling re-parent) cannot be
    subtracted back out of an already-folded average and is accepted as
    a bounded down-round bias instead."""
    out = []
    for k, (pid, leaves, w, cov) in enumerate(recs):
        if tuple(cov) == (pid,) and any(
            pid in other
            for j, (_p, _l, _w, other) in enumerate(recs) if j != k
        ):
            continue
        out.append((pid, leaves, w, cov))
    return out


def average_pushes(
    gang_dir: str, round, include: set[int] | None = None
) -> tuple[list[np.ndarray] | None, list[int]]:
    """Mean of the pushed leaves for ``round`` over ``include`` (None =
    every completed push). Returns ``(leaves, worker_ids_averaged)``;
    leaves is None when nothing (readable) was pushed. A torn/corrupt
    file (checksum mismatch included) is skipped — the push side is
    atomic, so unreadable means a concurrent replace or a damaged
    payload, and averaging must proceed over the live set rather than
    wedge the round or trust poisoned bytes."""
    ids = sorted(pushed_ids(gang_dir, round))
    if include is not None:
        ids = [i for i in ids if i in include]
    pairs: list[tuple[int, list[np.ndarray]]] = []
    for wid in ids:
        path = os.path.join(push_dir(gang_dir, round), f"{wid}.npz")
        try:
            pairs.append((wid, _read_npz(path)))
        except (OSError, ValueError, KeyError):
            continue
    return average_leaf_sets(pairs, context=f"for round {round} ")


def publish_average(
    gang_dir: str, round: int, leaves: list[np.ndarray],
    clock=time.time,
) -> str:
    """Write the averaged params for ``round`` and repoint LATEST
    (average first, pointer second — a crash in between leaves the old
    pointer valid)."""
    path = avg_path(gang_dir, round)
    _write_npz(path, leaves)
    from tpuflow.utils.paths import atomic_write_json

    # The pointer is gang_dir-RELATIVE: workers on other hosts may see
    # the same share under a different mount point, and an absolute
    # coordinator-side path would silently break their warm start.
    atomic_write_json(
        os.path.join(gang_dir, AVG_DIR, LATEST),
        {
            "round": int(round),
            "path": os.path.join(AVG_DIR, _round_name(round) + ".npz"),
            "time": clock(),
        },
    )
    return path


def read_average(gang_dir: str, round: int) -> list[np.ndarray] | None:
    """The averaged leaves for ``round``, or None if not published yet."""
    try:
        return _read_npz(avg_path(gang_dir, round))
    except (OSError, ValueError, KeyError):
        return None


def latest_round(gang_dir: str) -> int | None:
    """The newest published round NUMBER (pointer read only, no array
    load) — the cheap check catch-up workers poll with."""
    try:
        with open(
            os.path.join(gang_dir, AVG_DIR, LATEST), encoding="utf-8"
        ) as f:
            return int(json.load(f)["round"])
    except (OSError, ValueError, TypeError, KeyError,
            json.JSONDecodeError):
        return None


def _parse_round(name: str) -> int | None:
    if (
        len(name) == 7 and name.startswith("r") and name[1:].isdigit()
    ):
        return int(name[1:])
    return None


def prune_rounds(gang_dir: str, below: int) -> int:
    """Best-effort delete of push dirs and averaged files for rounds
    < ``below`` (never ``final`` or ``LATEST``). Without pruning a long
    gang writes one full copy of the params per worker per round
    forever; the coordinator calls this behind the slowest live
    member's round, and a catch-up worker that finds a historic round
    pruned just skips it (``latest_round`` is newer — see
    worker._wait_for_average)."""
    import shutil

    removed = 0
    push_root = os.path.join(gang_dir, PUSH_DIR)
    try:
        names = os.listdir(push_root)
    except OSError:
        names = []
    for name in names:
        r = _parse_round(name)
        if r is not None and r < below:
            shutil.rmtree(os.path.join(push_root, name), ignore_errors=True)
            removed += 1
    avg_root = os.path.join(gang_dir, AVG_DIR)
    try:
        names = os.listdir(avg_root)
    except OSError:
        names = []
    for name in names:
        if not name.endswith(".npz"):
            continue
        r = _parse_round(name[: -len(".npz")])
        if r is not None and r < below:
            try:
                os.remove(os.path.join(avg_root, name))
                removed += 1
            except OSError:
                pass
    return removed


def latest_average(gang_dir: str) -> tuple[int, list[np.ndarray]] | None:
    """The newest published average as ``(round, leaves)``, or None when
    no round has ever been published — the late joiner's warm-start
    source."""
    latest = os.path.join(gang_dir, AVG_DIR, LATEST)
    try:
        with open(latest, encoding="utf-8") as f:
            rec = json.load(f)
        path = os.path.join(gang_dir, rec["path"])  # pointer is relative
        return int(rec["round"]), _read_npz(path)
    except (OSError, ValueError, TypeError, KeyError,
            json.JSONDecodeError):
        return None


# ---------------------------------------------------------------------
# the backend interface: one contract, two transports
# ---------------------------------------------------------------------


class FileExchange:
    """The file transport behind the backend interface.

    Every method is a thin delegation to the module functions above —
    this class exists so the worker and coordinator are written against
    ONE contract that ``SocketExchange`` (``transport.py``) also
    implements over TCP. ``network`` tells the worker whether transport
    errors are a peer problem to degrade through (socket) or a local
    storage problem to fail on (file — the existing supervisor-restart
    semantics)."""

    network = False

    def __init__(self, gang_dir: str):
        self.gang_dir = gang_dir

    # --- params ---

    def push(self, round, worker_id: int, params) -> None:
        push_params(self.gang_dir, round, worker_id, params)

    def pushed_ids(self, round) -> set[int]:
        return pushed_ids(self.gang_dir, round)

    def read_pushes(
        self, round, include: set[int] | None = None
    ) -> list[tuple[int, list[np.ndarray]]]:
        """Every readable push for ``round`` as ``(worker_id, leaves)``
        pairs (corrupt/torn payloads skipped)."""
        ids = sorted(pushed_ids(self.gang_dir, round))
        if include is not None:
            ids = [i for i in ids if i in include]
        out = []
        for wid in ids:
            path = os.path.join(
                push_dir(self.gang_dir, round), f"{wid}.npz"
            )
            try:
                out.append((wid, _read_npz(path)))
            except (OSError, ValueError, KeyError):
                continue
        return out

    def _newest_push_rounds(self, min_round: int) -> dict[int, int]:
        push_root = os.path.join(self.gang_dir, PUSH_DIR)
        try:
            names = os.listdir(push_root)
        except OSError:
            return {}
        newest: dict[int, int] = {}
        for name in names:
            r = _parse_round(name)
            if r is None or r < min_round:
                continue
            for wid in pushed_ids(self.gang_dir, r):
                if newest.get(wid, -1) < r:
                    newest[wid] = r
        return newest

    def latest_push_rounds(
        self, min_round: int
    ) -> list[tuple[int, int]]:
        """Each worker's newest push ROUND with round >= ``min_round``,
        as ``(worker_id, round)`` — metadata only (directory listings,
        no payload reads): the async coordinator's every-poll scan."""
        newest = self._newest_push_rounds(min_round)
        return [(wid, newest[wid]) for wid in sorted(newest)]

    def latest_pushes(
        self, min_round: int
    ) -> list[tuple[int, int, list[np.ndarray]]]:
        """Each worker's NEWEST push with round >= ``min_round``, as
        ``(worker_id, round, leaves)`` — the payload read the async
        coordinator pays only when a publication actually happens
        (anything older than the staleness horizon is not even read)."""
        newest = self._newest_push_rounds(min_round)
        out = []
        for wid in sorted(newest):
            r = newest[wid]
            path = os.path.join(push_dir(self.gang_dir, r), f"{wid}.npz")
            try:
                out.append((wid, r, _read_npz(path)))
            except (OSError, ValueError, KeyError):
                continue
        return out

    def publish(self, round: int, leaves, clock=time.time) -> None:
        publish_average(self.gang_dir, round, leaves, clock=clock)

    def read_average(self, round: int):
        return read_average(self.gang_dir, round)

    def latest_round(self) -> int | None:
        return latest_round(self.gang_dir)

    def latest_average(self):
        return latest_average(self.gang_dir)

    def prune(self, below: int) -> int:
        return prune_rounds(self.gang_dir, below)

    # --- membership ---

    def write_heartbeat(
        self, worker_id: int, *, epoch: int = 0, round: int = 0,
        status: str = "running", clock=time.time,
    ) -> bool:
        from tpuflow.elastic.membership import write_heartbeat

        return write_heartbeat(
            self.gang_dir, worker_id,
            epoch=epoch, round=round, status=status, clock=clock,
        )

    def read_members(self) -> list:
        from tpuflow.elastic.membership import read_members

        return read_members(self.gang_dir)

    # --- the persisted round offset (survives restarts) ---

    def _offset_path(self, worker_id: int) -> str:
        # Deliberately NOT *.json: the membership scanner globs
        # members/*.json and this file is not a heartbeat.
        return os.path.join(
            self.gang_dir, "members", f"{worker_id}.offset"
        )

    def set_offset(self, worker_id: int, offset: int) -> None:
        from tpuflow.utils.paths import atomic_write_json

        path = self._offset_path(worker_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic_write_json(path, {"round_offset": int(offset)})

    def get_offset(self, worker_id: int) -> tuple[int, bool]:
        """``(offset, found)`` — found=False means no readable record
        (the caller decides whether the 0 fallback is benign)."""
        try:
            with open(
                self._offset_path(worker_id), encoding="utf-8"
            ) as f:
                return int(json.load(f)["round_offset"]), True
        except (OSError, ValueError, TypeError, KeyError,
                json.JSONDecodeError):
            return 0, False
