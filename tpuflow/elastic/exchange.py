"""File-based parameter exchange: worker pushes, averaged rebroadcasts.

SparkNet's training strategy (PAPERS.md, arXiv:1511.06051) is local SGD
with driver-coordinated parameter averaging: workers train independently
for a fixed number of steps, the driver averages their parameters and
broadcasts the average back. The transport here is deliberately the
filesystem — NOT ``jax.distributed`` collectives — for two load-bearing
reasons:

- **It works today.** The installed jax's mesh construction is broken
  (ROADMAP item 1); a collective-based exchange would be dead on
  arrival. Files need nothing but a shared directory.
- **It tolerates churn by construction.** A collective has a fixed
  communicator: one dead rank wedges everyone. A directory of
  ``push/r000007/{worker_id}.npz`` files has no membership baked in —
  the coordinator averages whichever files the live set produced, and a
  worker that died mid-push left only an invisible temp file.

Layout under the gang dir::

    push/r{round:06d}/{worker_id}.npz   one worker's params for a round
    push/final/{worker_id}.npz          a finished worker's last params
    avg/r{round:06d}.npz                the averaged rebroadcast
    avg/LATEST                          JSON {round, path, time}

Params ride as their flattened pytree leaves (``arr_0..arr_{n-1}`` in
tree-flatten order) plus a leaf count; the reader restores against the
live state's own treedef, so structure mismatches fail loudly instead of
silently mis-zipping leaves. Every write is atomic (tmp + rename): a
reader never sees a torn file, only a missing one — "not pushed yet" and
"crashed mid-push" are deliberately the same observation.

The ``elastic.push`` fault site fires inside every push (index = round,
so ``at=K`` drills "the worker that dies pushing round K").
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from tpuflow.resilience import fault_point

PUSH_DIR = "push"
AVG_DIR = "avg"
FINAL_ROUND = "final"
LATEST = "LATEST"


def _round_name(round) -> str:
    return round if round == FINAL_ROUND else f"r{int(round):06d}"


def push_dir(gang_dir: str, round) -> str:
    return os.path.join(gang_dir, PUSH_DIR, _round_name(round))


def avg_path(gang_dir: str, round: int) -> str:
    return os.path.join(gang_dir, AVG_DIR, _round_name(round) + ".npz")


def flatten_params(params) -> list[np.ndarray]:
    """Params pytree -> host numpy leaves in tree-flatten order."""
    import jax

    leaves = jax.tree_util.tree_leaves(params)
    return [np.asarray(leaf) for leaf in leaves]


def unflatten_like(params, leaves: list[np.ndarray]):
    """Leaves (tree-flatten order) -> a pytree shaped like ``params``.

    Leaf count and per-leaf shapes are checked against the template — a
    file from a differently-configured model must fail loudly, not
    silently mis-assign weights.
    """
    import jax

    template_leaves, treedef = jax.tree_util.tree_flatten(params)
    if len(leaves) != len(template_leaves):
        raise ValueError(
            f"param exchange file carries {len(leaves)} leaves; this "
            f"model has {len(template_leaves)} — different model/config?"
        )
    cast = []
    for i, (got, want) in enumerate(zip(leaves, template_leaves)):
        want_shape = tuple(np.shape(want))
        if tuple(got.shape) != want_shape:
            raise ValueError(
                f"param exchange leaf {i} has shape {tuple(got.shape)}; "
                f"this model expects {want_shape} — different "
                "model/config?"
            )
        # .dtype, not np.asarray(want).dtype: the template leaves are
        # the LIVE device params, and asarray would pull every one of
        # them to host just to read a dtype — doubling host transfer
        # per adopt.
        cast.append(np.asarray(got, dtype=getattr(want, "dtype", None)))
    return jax.tree_util.tree_unflatten(treedef, cast)


def _write_npz(path: str, leaves: list[np.ndarray]) -> None:
    import threading

    os.makedirs(os.path.dirname(path), exist_ok=True)
    # (pid, thread)-unique like utils.paths.atomic_write_json: the
    # in-process runner mode runs workers as threads of one pid.
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "wb") as f:
        np.savez(f, n_leaves=np.int64(len(leaves)),
                 **{f"arr_{i}": leaf for i, leaf in enumerate(leaves)})
    os.replace(tmp, path)


def _read_npz(path: str) -> list[np.ndarray]:
    with np.load(path) as z:
        n = int(z["n_leaves"])
        return [z[f"arr_{i}"] for i in range(n)]


def write_leaves(path: str, leaves: list[np.ndarray]) -> str:
    """Atomically write a leaves file outside the push/avg layout (the
    runner's final-average deliverable)."""
    _write_npz(path, leaves)
    return path


def push_params(gang_dir: str, round, worker_id: int, params) -> str:
    """Write this worker's params for ``round`` (atomic); returns the
    path. ``round`` may be the string ``"final"`` for the end-of-run
    push the runner's final average reads."""
    index = None if round == FINAL_ROUND else int(round)
    fault_point("elastic.push", index=index)
    path = os.path.join(push_dir(gang_dir, round), f"{worker_id}.npz")
    _write_npz(path, flatten_params(params))
    return path


def pushed_ids(gang_dir: str, round) -> set[int]:
    """Worker IDs that have completed a push for ``round``."""
    try:
        names = os.listdir(push_dir(gang_dir, round))
    except OSError:
        return set()
    out = set()
    for name in names:
        stem, ext = os.path.splitext(name)
        if ext == ".npz" and stem.isdigit():
            out.add(int(stem))
    return out


def average_pushes(
    gang_dir: str, round, include: set[int] | None = None
) -> tuple[list[np.ndarray] | None, list[int]]:
    """Mean of the pushed leaves for ``round`` over ``include`` (None =
    every completed push). Returns ``(leaves, worker_ids_averaged)``;
    leaves is None when nothing (readable) was pushed. A torn/corrupt
    file is skipped — the push side is atomic, so unreadable means a
    concurrent replace, and averaging must proceed over the live set
    rather than wedge the round."""
    ids = sorted(pushed_ids(gang_dir, round))
    if include is not None:
        ids = [i for i in ids if i in include]
    acc: list[np.ndarray] | None = None
    used: list[int] = []
    for wid in ids:
        path = os.path.join(push_dir(gang_dir, round), f"{wid}.npz")
        try:
            leaves = _read_npz(path)
        except (OSError, ValueError, KeyError):
            continue
        if acc is None:
            acc = [np.asarray(leaf, np.float64) for leaf in leaves]
        else:
            if len(leaves) != len(acc):
                raise ValueError(
                    f"worker {wid}'s push for round {round} has "
                    f"{len(leaves)} leaves; others pushed {len(acc)} — "
                    "mixed model configs in one gang"
                )
            for i, (a, leaf) in enumerate(zip(acc, leaves)):
                # Shape-checked like the adopt side (unflatten_like):
                # same depth + different widths would otherwise either
                # crash with a bare numpy broadcast error or — worse —
                # broadcast INTO the accumulator and publish a silently
                # wrong average for every worker to adopt.
                if tuple(np.shape(leaf)) != tuple(a.shape):
                    raise ValueError(
                        f"worker {wid}'s push for round {round} leaf "
                        f"{i} has shape {tuple(np.shape(leaf))}; others "
                        f"pushed {tuple(a.shape)} — mixed model configs "
                        "in one gang"
                    )
                a += leaf
        used.append(wid)
    if acc is None:
        return None, []
    return [np.asarray(a / len(used), np.float32) for a in acc], used


def publish_average(
    gang_dir: str, round: int, leaves: list[np.ndarray],
    clock=time.time,
) -> str:
    """Write the averaged params for ``round`` and repoint LATEST
    (average first, pointer second — a crash in between leaves the old
    pointer valid)."""
    path = avg_path(gang_dir, round)
    _write_npz(path, leaves)
    from tpuflow.utils.paths import atomic_write_json

    # The pointer is gang_dir-RELATIVE: workers on other hosts may see
    # the same share under a different mount point, and an absolute
    # coordinator-side path would silently break their warm start.
    atomic_write_json(
        os.path.join(gang_dir, AVG_DIR, LATEST),
        {
            "round": int(round),
            "path": os.path.join(AVG_DIR, _round_name(round) + ".npz"),
            "time": clock(),
        },
    )
    return path


def read_average(gang_dir: str, round: int) -> list[np.ndarray] | None:
    """The averaged leaves for ``round``, or None if not published yet."""
    try:
        return _read_npz(avg_path(gang_dir, round))
    except (OSError, ValueError, KeyError):
        return None


def latest_round(gang_dir: str) -> int | None:
    """The newest published round NUMBER (pointer read only, no array
    load) — the cheap check catch-up workers poll with."""
    try:
        with open(
            os.path.join(gang_dir, AVG_DIR, LATEST), encoding="utf-8"
        ) as f:
            return int(json.load(f)["round"])
    except (OSError, ValueError, TypeError, KeyError,
            json.JSONDecodeError):
        return None


def _parse_round(name: str) -> int | None:
    if (
        len(name) == 7 and name.startswith("r") and name[1:].isdigit()
    ):
        return int(name[1:])
    return None


def prune_rounds(gang_dir: str, below: int) -> int:
    """Best-effort delete of push dirs and averaged files for rounds
    < ``below`` (never ``final`` or ``LATEST``). Without pruning a long
    gang writes one full copy of the params per worker per round
    forever; the coordinator calls this behind the slowest live
    member's round, and a catch-up worker that finds a historic round
    pruned just skips it (``latest_round`` is newer — see
    worker._wait_for_average)."""
    import shutil

    removed = 0
    push_root = os.path.join(gang_dir, PUSH_DIR)
    try:
        names = os.listdir(push_root)
    except OSError:
        names = []
    for name in names:
        r = _parse_round(name)
        if r is not None and r < below:
            shutil.rmtree(os.path.join(push_root, name), ignore_errors=True)
            removed += 1
    avg_root = os.path.join(gang_dir, AVG_DIR)
    try:
        names = os.listdir(avg_root)
    except OSError:
        names = []
    for name in names:
        if not name.endswith(".npz"):
            continue
        r = _parse_round(name[: -len(".npz")])
        if r is not None and r < below:
            try:
                os.remove(os.path.join(avg_root, name))
                removed += 1
            except OSError:
                pass
    return removed


def latest_average(gang_dir: str) -> tuple[int, list[np.ndarray]] | None:
    """The newest published average as ``(round, leaves)``, or None when
    no round has ever been published — the late joiner's warm-start
    source."""
    latest = os.path.join(gang_dir, AVG_DIR, LATEST)
    try:
        with open(latest, encoding="utf-8") as f:
            rec = json.load(f)
        path = os.path.join(gang_dir, rec["path"])  # pointer is relative
        return int(rec["round"]), _read_npz(path)
    except (OSError, ValueError, TypeError, KeyError,
            json.JSONDecodeError):
        return None
