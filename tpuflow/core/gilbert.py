"""Gilbert's-equation physical choke-flow model (pure JAX).

The reference system uses "a physical model (using the Gilbert's equation)"
as the closed-form accuracy baseline for all learned flow regressors
(reference Readme.md:7-8; SURVEY.md C16 — the script itself is absent from
the reference snapshot, so this module implements the documented intent).

Gilbert's (1954) empirical correlation for two-phase flow through a wellhead
choke relates wellhead pressure, gas-liquid ratio, gross liquid rate, and
choke size:

    P_wh = A * GLR^B * q / S^C

with Gilbert's original coefficients A=10.0, B=0.546, C=1.89 when
P_wh is in psig, GLR in Mscf/stb, q in stb/day and S in 64ths of an inch.
Solved for the liquid rate, the *flow prediction* used as the eval baseline:

    q = P_wh * S^C / (A * GLR^B)

The same functional form with different (A, B, C) gives the classic
Ros / Baxendell / Achong correlations, exposed here as a coefficient family
so the physical baseline is configurable per field.

Everything is pure ``jax.numpy`` — differentiable, jittable, vmappable —
so the physical model composes with learned models (e.g. residual learning
on top of the Gilbert prediction) and runs on TPU like any other op.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class ChokeCoefficients(NamedTuple):
    """Coefficients (A, B, C) of the Gilbert-form choke correlation.

    Float-only on purpose: instances are valid pytrees whose leaves are all
    numeric, so a coefficient set can be passed straight through ``jax.jit``
    / ``jax.vmap`` boundaries (a name string would fail tracing).
    """

    a: float
    b: float
    c: float


# Classic published coefficient sets for P_wh = a * GLR^b * q / S^c.
GILBERT = ChokeCoefficients(10.0, 0.546, 1.89)
ROS = ChokeCoefficients(17.4, 0.5, 2.0)
BAXENDELL = ChokeCoefficients(9.56, 0.546, 1.93)
ACHONG = ChokeCoefficients(3.82, 0.65, 1.88)

COEFFICIENTS = {
    "gilbert": GILBERT,
    "ros": ROS,
    "baxendell": BAXENDELL,
    "achong": ACHONG,
}

_EPS = 1e-6


def gilbert_flow(
    wellhead_pressure: jnp.ndarray,
    choke_size: jnp.ndarray,
    glr: jnp.ndarray,
    coeffs: ChokeCoefficients = GILBERT,
) -> jnp.ndarray:
    """Closed-form gross liquid rate q [stb/day] through the choke.

    q = P_wh * S^c / (a * GLR^b)

    Args:
      wellhead_pressure: P_wh [psig].
      choke_size: S [64ths of an inch].
      glr: gas-liquid ratio [Mscf/stb]; clamped away from zero.
      coeffs: correlation coefficients (Gilbert by default).
    """
    glr = jnp.maximum(glr, _EPS)
    choke_size = jnp.maximum(choke_size, _EPS)
    return (
        wellhead_pressure
        * jnp.power(choke_size, coeffs.c)
        / (coeffs.a * jnp.power(glr, coeffs.b))
    )


def gilbert_wellhead_pressure(
    flow_rate: jnp.ndarray,
    choke_size: jnp.ndarray,
    glr: jnp.ndarray,
    coeffs: ChokeCoefficients = GILBERT,
) -> jnp.ndarray:
    """Forward form of the correlation: P_wh = a * GLR^b * q / S^c."""
    choke_size = jnp.maximum(choke_size, _EPS)
    glr = jnp.maximum(glr, _EPS)
    return (
        coeffs.a
        * jnp.power(glr, coeffs.b)
        * flow_rate
        / jnp.power(choke_size, coeffs.c)
    )
