"""Gilbert's-equation physical choke-flow model (pure JAX).

The reference system uses "a physical model (using the Gilbert's equation)"
as the closed-form accuracy baseline for all learned flow regressors
(reference Readme.md:7-8; SURVEY.md C16 — the script itself is absent from
the reference snapshot, so this module implements the documented intent).

Gilbert's (1954) empirical correlation for two-phase flow through a wellhead
choke relates wellhead pressure, gas-liquid ratio, gross liquid rate, and
choke size:

    P_wh = A * GLR^B * q / S^C

with Gilbert's original coefficients A=10.0, B=0.546, C=1.89 when
P_wh is in psig, GLR in Mscf/stb, q in stb/day and S in 64ths of an inch.
Solved for the liquid rate, the *flow prediction* used as the eval baseline:

    q = P_wh * S^C / (A * GLR^B)

The same functional form with different (A, B, C) gives the classic
Ros / Baxendell / Achong correlations, exposed here as a coefficient family
so the physical baseline is configurable per field.

Everything is pure ``jax.numpy`` — differentiable, jittable, vmappable —
so the physical model composes with learned models (e.g. residual learning
on top of the Gilbert prediction) and runs on TPU like any other op.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class ChokeCoefficients(NamedTuple):
    """Coefficients (A, B, C) of the Gilbert-form choke correlation.

    Float-only on purpose: instances are valid pytrees whose leaves are all
    numeric, so a coefficient set can be passed straight through ``jax.jit``
    / ``jax.vmap`` boundaries (a name string would fail tracing).
    """

    a: float
    b: float
    c: float


# Classic published coefficient sets for P_wh = a * GLR^b * q / S^c.
GILBERT = ChokeCoefficients(10.0, 0.546, 1.89)
ROS = ChokeCoefficients(17.4, 0.5, 2.0)
BAXENDELL = ChokeCoefficients(9.56, 0.546, 1.93)
ACHONG = ChokeCoefficients(3.82, 0.65, 1.88)

COEFFICIENTS = {
    "gilbert": GILBERT,
    "ros": ROS,
    "baxendell": BAXENDELL,
    "achong": ACHONG,
}

_EPS = 1e-6


def gilbert_flow(
    wellhead_pressure: jnp.ndarray,
    choke_size: jnp.ndarray,
    glr: jnp.ndarray,
    coeffs: ChokeCoefficients = GILBERT,
) -> jnp.ndarray:
    """Closed-form gross liquid rate q [stb/day] through the choke.

    q = P_wh * S^c / (a * GLR^b)

    Args:
      wellhead_pressure: P_wh [psig].
      choke_size: S [64ths of an inch].
      glr: gas-liquid ratio [Mscf/stb]; clamped away from zero.
      coeffs: correlation coefficients (Gilbert by default).
    """
    glr = jnp.maximum(glr, _EPS)
    choke_size = jnp.maximum(choke_size, _EPS)
    return (
        wellhead_pressure
        * jnp.power(choke_size, coeffs.c)
        / (coeffs.a * jnp.power(glr, coeffs.b))
    )


def fit_coefficients(
    wellhead_pressure: jnp.ndarray,
    choke_size: jnp.ndarray,
    glr: jnp.ndarray,
    flow_rate: jnp.ndarray,
) -> ChokeCoefficients:
    """Calibrate (a, b, c) to field data by least squares in log space.

    The correlation is log-linear: ``log q = log P − log a − b·log GLR +
    c·log S``, so the residual ``log q − log P`` is linear in
    ``(−log a, −b, c)`` — one ``lstsq`` solve, no iteration. This is how a
    per-field physical baseline is tuned before comparing learned models
    against it (the reference fixes Gilbert's published 1954 constants;
    calibration makes the baseline honest on a specific field's wells).
    """
    P = jnp.maximum(jnp.asarray(wellhead_pressure, jnp.float32), _EPS)
    S = jnp.maximum(jnp.asarray(choke_size, jnp.float32), _EPS)
    G = jnp.maximum(jnp.asarray(glr, jnp.float32), _EPS)
    q = jnp.maximum(jnp.asarray(flow_rate, jnp.float32), _EPS)
    y = jnp.log(q) - jnp.log(P)
    X = jnp.stack(
        [jnp.ones_like(y), jnp.log(G), jnp.log(S)], axis=1
    )  # [N, 3] @ (−log a, −b, c)
    theta, *_ = jnp.linalg.lstsq(X, y)
    return ChokeCoefficients(
        a=float(jnp.exp(-theta[0])), b=float(-theta[1]), c=float(theta[2])
    )


def append_gilbert_column(features, columns, coeffs: ChokeCoefficients = GILBERT):
    """Append the RAW Gilbert flow prediction as the last feature column.

    The single source of the ``GilbertResidualMLP`` input contract, shared
    by the training pipeline and the serving path so the appended column
    can never drift between them. ``features`` is the assembled [N, F]
    matrix; ``columns`` the raw per-name arrays.
    """
    import numpy as np

    q = np.asarray(
        gilbert_flow(columns["pressure"], columns["choke"], columns["glr"], coeffs),
        dtype=np.float32,
    )
    return np.concatenate([np.asarray(features), q[:, None]], axis=1)


def append_gilbert_channel(
    series, feature_names, coeffs: ChokeCoefficients = GILBERT
):
    """Append the RAW per-timestep Gilbert prediction as the LAST channel.

    The sequence-model counterpart of ``append_gilbert_column`` and the
    single source of the ``GilbertResidualLSTM`` input contract, shared by
    the windowed training pipeline and the serving path so the appended
    channel can never drift between them. ``series`` is a [T, F] per-step
    feature matrix whose columns are named by ``feature_names``.
    """
    import numpy as np

    missing = {"pressure", "choke", "glr"} - set(feature_names)
    if missing:
        raise ValueError(
            f"append_gilbert needs pressure/choke/glr channels; "
            f"missing {sorted(missing)}"
        )
    ip = feature_names.index("pressure")
    ic = feature_names.index("choke")
    ig = feature_names.index("glr")
    q = np.asarray(
        gilbert_flow(series[:, ip], series[:, ic], series[:, ig], coeffs),
        dtype=np.float32,
    )
    return np.concatenate([np.asarray(series), q[:, None]], axis=1)


def gilbert_wellhead_pressure(
    flow_rate: jnp.ndarray,
    choke_size: jnp.ndarray,
    glr: jnp.ndarray,
    coeffs: ChokeCoefficients = GILBERT,
) -> jnp.ndarray:
    """Forward form of the correlation: P_wh = a * GLR^b * q / S^c."""
    choke_size = jnp.maximum(choke_size, _EPS)
    glr = jnp.maximum(glr, _EPS)
    return (
        coeffs.a
        * jnp.power(glr, coeffs.b)
        * flow_rate
        / jnp.power(choke_size, coeffs.c)
    )
