"""Pure-function core: physical models, losses, metrics."""

from tpuflow.core.gilbert import (  # noqa: F401
    ChokeCoefficients,
    GILBERT,
    ROS,
    BAXENDELL,
    ACHONG,
    gilbert_flow,
    gilbert_wellhead_pressure,
)
from tpuflow.core.losses import mae_clip, mae, mse, huber  # noqa: F401
from tpuflow.core.metrics import rmse, r2_score, mae_vs_baseline  # noqa: F401
