"""Training losses (pure JAX).

The reference's single custom loss is a clipped mean-absolute-error written
in Theano tensor ops with CLIP_VALUE = 6 (reference cnn.py:29-32, 37):

    mae_clip(y_true, y_pred) = mean(clip(|y_true - y_pred|, 0, 6))

i.e. an outlier-resistant regression loss whose per-sample contribution
saturates at 6 flow units. Reproduced here with identical semantics in
``jax.numpy`` (golden-value tested in tests/test_losses.py), plus the
standard losses the wider model family needs.
"""

from __future__ import annotations

import jax.numpy as jnp

CLIP_VALUE = 6.0


def mae_clip(
    y_true: jnp.ndarray, y_pred: jnp.ndarray, clip_value: float = CLIP_VALUE
) -> jnp.ndarray:
    """Clipped MAE: mean(clip(|y_true - y_pred|, 0, clip_value))."""
    return jnp.mean(jnp.clip(jnp.abs(y_true - y_pred), 0.0, clip_value))


def mae(y_true: jnp.ndarray, y_pred: jnp.ndarray) -> jnp.ndarray:
    """Mean absolute error."""
    return jnp.mean(jnp.abs(y_true - y_pred))


def mse(y_true: jnp.ndarray, y_pred: jnp.ndarray) -> jnp.ndarray:
    """Mean squared error."""
    return jnp.mean(jnp.square(y_true - y_pred))


def huber(
    y_true: jnp.ndarray, y_pred: jnp.ndarray, delta: float = 1.0
) -> jnp.ndarray:
    """Huber loss: quadratic within ``delta``, linear outside."""
    err = jnp.abs(y_true - y_pred)
    quad = jnp.minimum(err, delta)
    return jnp.mean(0.5 * quad**2 + delta * (err - quad))


def _mae_clip_pallas(y_true: jnp.ndarray, y_pred: jnp.ndarray) -> jnp.ndarray:
    """The fused Pallas kernel variant of ``mae_clip`` (same semantics,
    parity-tested) — selectable per job via ``TrainJobConfig.loss``.
    Lazy import: ``tpuflow.kernels`` imports this module for CLIP_VALUE.
    """
    from tpuflow.kernels import mae_clip_pallas

    return mae_clip_pallas(y_true, y_pred)


LOSSES = {
    "mae_clip": mae_clip,
    "mae": mae,
    "mse": mse,
    "huber": huber,
    "mae_clip_pallas": _mae_clip_pallas,
}
