"""Evaluation metrics (pure JAX).

The reference's only quality gate is a printed test-set loss
(reference cnn.py:132-134); the system-level accuracy yardstick is
"well-flow MAE vs Gilbert-eq baseline" (BASELINE.json). These helpers make
both first-class.
"""

from __future__ import annotations

import jax.numpy as jnp


def rmse(y_true: jnp.ndarray, y_pred: jnp.ndarray) -> jnp.ndarray:
    return jnp.sqrt(jnp.mean(jnp.square(y_true - y_pred)))


def r2_score(y_true: jnp.ndarray, y_pred: jnp.ndarray) -> jnp.ndarray:
    """Coefficient of determination."""
    ss_res = jnp.sum(jnp.square(y_true - y_pred))
    ss_tot = jnp.sum(jnp.square(y_true - jnp.mean(y_true)))
    return 1.0 - ss_res / jnp.maximum(ss_tot, 1e-12)


def mae_vs_baseline(
    y_true: jnp.ndarray,
    y_pred: jnp.ndarray,
    y_baseline: jnp.ndarray,
) -> dict:
    """Model MAE next to a physical-baseline MAE (the BASELINE.json metric).

    Returns model MAE, baseline MAE, and their ratio (<1 means the learned
    model beats the physical model).
    """
    from tpuflow.core.losses import mae

    model_mae = mae(y_true, y_pred)
    base_mae = mae(y_true, y_baseline)
    return {
        "mae": model_mae,
        "baseline_mae": base_mae,
        "mae_ratio": model_mae / jnp.maximum(base_mae, 1e-12),
    }
