"""Job-runner service: the reference's web-trigger layer (L6/C20), real.

The reference system is "triggered by the web component of a full
information system" which submits model-training jobs with per-job feature
schemas to the cluster (reference Readme.md:4; the spark-submit contract,
reference cnn.py:2). This module is the TPU-native replacement for that
submission seam: a dependency-free HTTP daemon that accepts a JSON job
spec, runs ``train(config)`` on the accelerator, and writes the final
report next to the model artifact where the web component reads it
(SURVEY.md §3.2's implied flow).

API (JSON in/out):

- ``POST /jobs``        — submit a job spec; returns ``{"job_id", "status"}``
  (``429`` when the bounded queue is full).
- ``GET  /jobs``        — list all jobs (summaries).
- ``GET  /jobs/<id>``   — one job: status, spec, report or error; running
  jobs carry ``heartbeats`` (one per epoch boundary) and ``running_s`` —
  a stale heartbeat means the job is hung inside an epoch.
- ``DELETE /jobs/<id>`` — cancel: a queued job is cancelled immediately; a
  running job is cancelled cooperatively at its next epoch boundary
  (status ``cancelling`` until the worker observes it); terminal jobs
  return ``409``.
- ``POST /predict``     — serve a trained artifact synchronously:
  ``{"storagePath", "model", "data": <csv path>}`` or
  ``{"storagePath", "model", "columns": {name: [values...]}}`` →
  ``{"predictions": [...], "count"}``. Loaded artifacts are cached.
  When the artifact's checkpoint is missing/corrupt, answers degrade to
  the Gilbert physical baseline with ``degraded: true`` in the response
  (docs/resilience.md — the degraded-serving contract).
- ``POST /artifacts/reload`` — ``{"storagePath", "model"}``: drop the
  cached predictor so the next request loads the artifact fresh — the
  online loop's zero-downtime swap signal (tpuflow/online;
  docs/online.md). In-flight requests finish against the old instance.
- ``GET  /metrics``     — service counters: jobs
  submitted/done/failed/queued/running, predictor cache
  hits/loads/invalidations (+ degraded_requests/fallback_loads), uptime,
  per-request latency percentiles (p50/p99), and — with batching on —
  the coalesced-dispatch counters and batch-size histogram.
  ``?format=prometheus`` returns the same registry as Prometheus text
  exposition (tpuflow/obs; docs/observability.md has the scrape config),
  plus the process-wide default registry — including the training
  health-monitor families (``train_numerics_anomalies_total``,
  ``train_recompiles``, ``train_mfu``/``train_bound``) when this
  process also trains (the job-runner's children train out-of-process;
  their anomalies surface in the job report and forensics instead).

Concurrent /predict traffic can take the serving fast path (off by
default; ``--batch-predicts``, ``--warmup-buckets``,
``--donate-forward``, or the ``TPUFLOW_SERVE_*`` env vars): requests
for one artifact coalesce into shared pow-2-padded jitted dispatches,
with compiled-forward buckets pre-warmed at artifact load. Degraded
answers are never coalesced into model batches, and a retrain mid-flight
never scatters stale predictions (PredictService docstring;
docs/serving.md).
- ``GET  /health``      — liveness + degradation (``/healthz`` alias):
  ``status`` is ``ok`` or ``degraded``, with the artifacts currently
  served by the fallback.

The spec accepts the reference's camelCase submission fields
(``columnNames``, ``columnTypes``, ``targetColumn``, ``storagePath``,
``data``, ``epochs``, ``batchSize``) as well as any snake_case
``TrainJobConfig`` field. Jobs run ONE at a time on a background worker —
the chip is a serial resource; queued jobs wait their turn. The queue is
bounded (``JobRunner(max_queued=...)``, default 64): past that, POST
/jobs returns 429 instead of accepting unbounded backlog.

Per-job runtime budget: ``{"timeoutSeconds": N}`` (or
``timeout_seconds``) in the spec caps the job's RUNNING time — measured
from when the worker starts it, not submission — after which it fails
with a timeout error. ``JobRunner(default_timeout=...)`` applies one to
every job that doesn't set its own. Both cancellation and timeouts are
cooperative (checked between training epochs, and between the runs of a
compare/sweep): one enormous epoch or an XLA compile is not
interruptible, but a hung job no longer wedges the service forever.

Restart durability: ``--journal PATH`` (``JobRunner(journal_path=...)``)
appends every lifecycle event to a JSONL journal and replays it at
startup — terminal jobs come back as queryable history, jobs that never
started are requeued under their original ids, and a job that was
RUNNING when the daemon died is marked failed/lost rather than silently
re-run (its partial checkpoints exist; resubmit with ``resume: true`` to
continue). After replay the journal is compacted — event history is
archived to ``<journal>.archive`` and the live file is rewritten as one
snapshot line per job, so replay cost stays bounded by job count, not
by lifetime event count. This is the job-history half of the
``spark-submit`` cluster story (reference Readme.md:3-4) the service
replaces.

Two experiment job kinds ride the same queue (the reference's "tests ...
using multiple model types" workflow, Readme.md:13, web-triggered):

- ``{"compare": ["lstm", "static_mlp", ...], ...base fields}`` — train
  each family on the same data/seed; the report carries the ranked table.
- ``{"sweep": {"model_kwargs.hidden": [32, 64], ...}, ...base fields}``
  — grid over config fields; the report carries the ranked assignments.

On success the report is written to ``{storagePath}/models/{model}
.report.json`` (URI-aware — gs:// works), completing the loop where the
reference's web layer "reads artifact / reported loss".

Run: ``python -m tpuflow.serve --port 8700``; stop with SIGINT/SIGTERM.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import uuid
from dataclasses import fields as dataclass_fields
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from time import monotonic as _monotonic

from tpuflow.utils.paths import join_path, open_file

# reference cnn.py:2 argv contract + common web-JSON spellings.
_CAMEL_TO_CONFIG = {
    "columnNames": "column_names",
    "columnTypes": "column_types",
    "targetColumn": "target",
    "storagePath": "storage_path",
    "data": "data_path",
    "dataPath": "data_path",
    "epochs": "max_epochs",
    "maxEpochs": "max_epochs",
    "batchSize": "batch_size",
    "wellColumn": "well_column",
}


def spec_to_config(spec: dict):
    """Translate a JSON job spec into a TrainJobConfig.

    Unknown keys are rejected loudly — a typo'd field silently ignored
    would train the wrong job.
    """
    from tpuflow.api.config import TrainJobConfig

    valid = {f.name for f in dataclass_fields(TrainJobConfig)}
    kwargs = {}
    for key, value in spec.items():
        name = _CAMEL_TO_CONFIG.get(key, key)
        if name not in valid:
            raise ValueError(f"unknown job-spec field {key!r}")
        if name in kwargs:
            raise ValueError(
                f"job-spec field {key!r} duplicates another key for "
                f"config field {name!r}"
            )
        kwargs[name] = value
    kwargs.setdefault("verbose", False)
    return TrainJobConfig(**kwargs)


def _json_finite(value):
    """Stringify non-finite floats, recursively: a DIVERGED run's report
    is exactly where inf/nan losses appear (best_val_loss=inf when no
    epoch ever improved, an inf_loss anomaly's value), and ``json.dumps``
    would write RFC-8259-invalid ``Infinity``/``NaN`` tokens that break
    every strict reader of the job report."""
    import math

    if isinstance(value, float) and not math.isfinite(value):
        return repr(value)
    if isinstance(value, dict):
        return {k: _json_finite(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_finite(v) for v in value]
    return value


def report_to_dict(report) -> dict:
    """The JSON the web layer reads: the reference's elapsed-time +
    test-loss print (cnn.py:133-134), recorded — plus the health
    monitor's outcomes (a job that diverged under ``health="warn"`` or
    churned recompiles must say so in the report an operator reads, not
    only in the forensics file). Health keys are additive and
    getattr-guarded so a minimal report object (tests) still serializes;
    every value is JSON-finite (non-finite floats become strings).
    """
    out = {
        "test_loss": report.test_loss,
        "test_mae": report.test_mae,
        "gilbert_mae": report.gilbert_mae,
        "time_elapsed": report.time_elapsed,
        "samples_per_sec": report.samples_per_sec,
        "epochs_ran": report.result.epochs_ran,
        "best_val_loss": report.result.best_val_loss,
    }
    anomalies = getattr(report, "anomalies", None)
    if anomalies:
        out["numerics_anomalies"] = list(anomalies)
    recompiles = getattr(report, "recompiles", None)
    if recompiles:
        out["recompiles"] = dict(recompiles)
    autotune = getattr(report, "autotune", None)
    if autotune:
        out["autotune"] = dict(autotune)
    return _json_finite(out)


class JobRunner:
    """Serial job queue + registry. One worker thread drives the chip.

    ``on_artifact_change(storage_path, model)`` is called whenever a job
    that writes under ``storage_path`` reaches a terminal state — training
    writes save-best checkpoints as it goes, so even a failed job may have
    changed the artifact (the predict cache must drop it either way).
    """

    def __init__(
        self,
        on_artifact_change=None,
        max_queued: int = 64,
        default_timeout: float | None = None,
        journal_path: str | None = None,
        registry=None,
    ):
        from tpuflow.obs import Registry

        # Run-scoped metrics registry (tpuflow/obs): the job counters
        # live here and render into /metrics?format=prometheus; the
        # JSON metrics() view reads the same counters (keys unchanged).
        # Own instance by default so parallel runners (tests) never
        # bleed counts into each other.
        self.registry = registry if registry is not None else Registry()
        self._counters = {
            name: self.registry.counter(f"jobs_{name}_total", help)
            for name, help in (
                ("submitted", "jobs accepted into the queue"),
                ("done", "jobs finished successfully"),
                ("failed", "jobs that errored or timed out"),
                ("cancelled", "jobs cancelled while queued or running"),
            )
        }
        self.registry.gauge(
            "jobs_queued", "jobs waiting for the worker",
            fn=lambda: self._count_statuses()[0],
        )
        self.registry.gauge(
            "jobs_running", "jobs occupying the chip (incl. cancelling)",
            fn=lambda: self._count_statuses()[1],
        )
        # Unbounded Queue; admission control is by LIVE queued count in
        # submit() (under the lock), not Queue(maxsize=...): a cancelled
        # queued job leaves a stale entry in the Queue until the worker
        # pops it, and counting those against capacity would keep
        # returning 429 on a logically empty queue.
        self._queue: queue.Queue = queue.Queue()
        self.max_queued = max_queued
        self.default_timeout = default_timeout
        self._jobs: dict[str, dict] = {}
        self._cancel_events: dict[str, threading.Event] = {}
        self._lock = threading.Lock()
        self._on_artifact_change = on_artifact_change
        self._status_cache: tuple[float, tuple[int, int]] = (0.0, (0, 0))
        # Journal (JSONL, append-only): job lifecycle survives daemon
        # restarts — terminal jobs come back as history, never-started
        # jobs are requeued, and a job that was RUNNING at the crash is
        # marked failed/lost (re-running it could double side effects;
        # the client decides whether to resubmit with resume=true).
        # Replay happens before the worker starts, so requeued entries
        # are processed like fresh submissions. At startup the replayed
        # journal is COMPACTED: history is archived to ``<path>.archive``
        # and the live file is rewritten as one snapshot line per job,
        # so the journal (and replay time) stays bounded by the number
        # of jobs, not the number of lifecycle events ever seen.
        self._journal_file = None
        self._journal_lock = threading.Lock()  # serializes disk writes
        # Ordered event buffer: events are ENQUEUED under self._lock (a
        # cheap list append, atomic with the state change they record)
        # and FLUSHED to disk outside it — so a stalled journal
        # filesystem can never block GET /jobs behind self._lock, while
        # per-job event order still matches state-change order exactly.
        self._journal_buf: list[dict] = []
        self._journal_buf_lock = threading.Lock()
        if journal_path:
            # Exclusive: two daemons replaying one journal would each
            # requeue the other's queued jobs and run them twice.
            self._journal_file = self._flocked_append(journal_path)
            self._replay_journal(journal_path)
            self._compact_journal(journal_path)
        self._worker = threading.Thread(
            target=self._run, name="tpuflow-jobs", daemon=True
        )
        self._worker.start()

    # ---- journal ----

    @staticmethod
    def _flocked_append(path: str):
        """Open ``path`` for append holding an exclusive flock (the
        two-daemons-one-journal guard). Open-then-flock races with
        compaction's inode swap in another daemon: we might flock the
        orphaned pre-compaction inode just after it was replaced and
        released, passing the guard while the other daemon runs — so
        after locking, verify the fd still IS ``path`` and retry."""
        for _ in range(10):
            f = open(path, "a", encoding="utf-8")
            try:
                import fcntl

                fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                f.close()
                raise RuntimeError(
                    f"journal {path!r} is locked by another "
                    "running daemon; two daemons sharing one journal "
                    "would re-run each other's queued jobs"
                ) from None
            except ImportError:  # non-POSIX: proceed without the guard
                return f
            import os

            try:
                if os.fstat(f.fileno()).st_ino == os.stat(path).st_ino:
                    return f
            except OSError:
                pass  # path vanished mid-swap: retry
            f.close()  # locked a replaced inode: reopen the current one
        raise RuntimeError(
            f"journal {path!r} kept changing underneath the lock "
            "(another daemon compacting?); refusing to share it"
        )

    def _journal_enqueue(self, **rec) -> None:
        """Buffer one lifecycle event for the next flush. Call while
        holding ``self._lock`` so buffer order == state-change order
        (replay folds in file order; a terminal line landing before its
        job's submitted line would resurrect a cancelled job)."""
        if self._journal_file is None:
            return
        with self._journal_buf_lock:
            self._journal_buf.append(rec)

    def _journal_flush(self) -> None:
        """Write all buffered events to disk, in enqueue order. Call
        OUTSIDE ``self._lock``: this is the only journal code that does
        IO, so a stalled filesystem stalls only the flushing thread.
        Callers flush before reporting a state change to the client, so
        a response like "cancelled" implies the terminal line was
        (best-effort) durable first. The residual window — process death
        after the state change but before this flush — loses the
        buffered lines like any crash loses in-memory state; replay then
        requeues a still-'submitted' job the client may have seen
        cancelled. That caveat is inherent to best-effort journaling and
        is documented here rather than papered over.

        NEVER raises: the journal is best-effort durability, and a write
        failure (disk full, volume gone, a Python caller's non-JSON spec)
        propagating out of submit() would leave a ghost queued record, or
        out of the worker loop would kill the thread and wedge the whole
        service — the exact failure mode this module's error discipline
        forbids. A lost journal line means one job's history won't survive
        a restart; the running service stays correct."""
        if self._journal_file is None:
            return
        with self._journal_lock:
            # Drain under the write lock so concurrent flushers can't
            # interleave drained batches out of order.
            with self._journal_buf_lock:
                batch, self._journal_buf = self._journal_buf, []
            if not batch:
                return
            for rec in batch:
                # Per-record: one non-JSON-serializable spec (a Python
                # caller's object) must lose only ITS line, never drop a
                # neighboring job's terminal event from the same batch.
                try:
                    self._journal_file.write(json.dumps(rec) + "\n")
                except (OSError, TypeError, ValueError) as e:
                    import sys

                    print(
                        f"tpuflow.serve: journal write failed "
                        f"({type(e).__name__}: {e}); continuing without it",
                        file=sys.stderr,
                    )
            try:
                self._journal_file.flush()
            except (OSError, ValueError):
                pass  # already reported per-record or reported next write

    def _journal(self, **rec) -> None:
        """Enqueue + flush one event — for single-threaded paths (startup
        adjudication) and worker-side events already outside the lock."""
        self._journal_enqueue(**rec)
        self._journal_flush()

    def _replay_journal(self, path: str) -> None:
        import os

        if not os.path.exists(path):
            return
        events: dict[str, dict] = {}  # job_id -> folded state
        order: list[str] = []
        self._replay_saw_new_events = False
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue  # crash-truncated tail line
                if ev.get("event") != "snapshot":
                    self._replay_saw_new_events = True
                job_id = ev.get("job_id")
                if not job_id:
                    continue
                if job_id not in events:
                    events[job_id] = {"last": None}
                    order.append(job_id)
                st = events[job_id]
                kind = ev.get("event")
                if kind == "submitted":
                    st.update(
                        spec=ev.get("spec"), timeout_s=ev.get("timeout_s"),
                        last="submitted",
                    )
                elif kind == "started":
                    st["last"] = "started"
                elif kind == "terminal":
                    st.update(
                        last="terminal", status=ev.get("status", "failed"),
                        error=ev.get("error"), report=ev.get("report"),
                    )
                elif kind == "snapshot":
                    # A compacted journal: one line = one job's folded
                    # state as of the previous restart. Later append-mode
                    # lines (started/terminal) fold on top normally.
                    status = ev.get("status", "failed")
                    st.update(
                        spec=ev.get("spec"), timeout_s=ev.get("timeout_s"),
                        last="submitted" if status == "queued" else "terminal",
                        status=status, error=ev.get("error"),
                        report=ev.get("report"),
                    )
        lost: list[str] = []
        for job_id in order:
            st = events[job_id]
            spec = st.get("spec")
            if spec is None:
                continue  # journal from before this job's submitted line
            if st["last"] == "terminal":
                rec = {"job_id": job_id, "status": st["status"], "spec": spec}
                if st.get("error"):
                    rec["error"] = st["error"]
                if st.get("report") is not None:
                    rec["report"] = st["report"]
                self._jobs[job_id] = rec
                self._counters["submitted"].inc()
                self._counters.get(
                    st["status"], self._counters["failed"]
                ).inc()
            elif st["last"] == "started":
                # Mid-run at the crash: training side effects (partial
                # checkpoints) exist; don't silently re-run.
                self._jobs[job_id] = {
                    "job_id": job_id, "status": "failed", "spec": spec,
                    "error": "lost: daemon restarted mid-run (resubmit; "
                    "resume=true continues from the last run checkpoint)",
                }
                self._counters["submitted"].inc()
                self._counters["failed"].inc()
                lost.append(job_id)
            else:  # submitted, never started: safe to requeue as-is
                try:
                    kind, config, _ = self._parse_spec(spec)
                except Exception as e:
                    self._jobs[job_id] = {
                        "job_id": job_id, "status": "failed", "spec": spec,
                        "error": f"requeue after restart failed: "
                        f"{type(e).__name__}: {e}",
                    }
                    self._counters["submitted"].inc()
                    self._counters["failed"].inc()
                    lost.append(job_id)
                    continue
                self._jobs[job_id] = {
                    "job_id": job_id, "status": "queued", "spec": spec
                }
                self._cancel_events[job_id] = threading.Event()
                self._counters["submitted"].inc()
                self._queue.put((job_id, kind, config, st.get("timeout_s")))
        # Record the adjudications so the NEXT replay sees them terminal
        # (the flocked append handle is already open at this point).
        for job_id in lost:
            rec = self._jobs[job_id]
            self._journal(
                event="terminal", job_id=job_id,
                status=rec["status"], error=rec.get("error"),
            )
        self._replayed_timeouts = {
            job_id: st.get("timeout_s")
            for job_id, st in events.items()
        }

    def _compact_journal(self, path: str) -> None:
        """Rewrite the replayed journal as one snapshot line per job and
        archive the event history to ``<path>.archive``.

        Replay is O(journal file); without compaction the file grows
        with every lifecycle event across every restart forever. After
        compaction the live journal is bounded by the number of live +
        historical jobs, and subsequent restarts replay one line per
        job plus whatever ran since. Best-effort like all journal IO: a
        failure leaves the original (longer but correct) journal alone.
        """
        import os

        if self._journal_file is None or not self._jobs:
            return
        if not getattr(self, "_replay_saw_new_events", True):
            # Journal is already exactly the snapshot set (a restart with
            # no activity since the last compaction): rewriting it would
            # only append duplicate history to the archive every restart
            # of a crash-looping daemon.
            return
        tmp = path + ".tmp"
        new_handle = None
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for job_id, rec in self._jobs.items():
                    snap = {
                        "event": "snapshot", "job_id": job_id,
                        "status": rec["status"], "spec": rec.get("spec"),
                    }
                    timeout_s = getattr(self, "_replayed_timeouts", {}).get(
                        job_id
                    )
                    if timeout_s is not None:
                        snap["timeout_s"] = timeout_s
                    if rec.get("error"):
                        snap["error"] = rec["error"]
                    if rec.get("report") is not None:
                        snap["report"] = rec["report"]
                    f.write(json.dumps(snap) + "\n")
            # The new flocked append handle is opened on tmp (the flock
            # rides the inode through the rename) and a READ handle on
            # the old journal inode is taken before the promote; if
            # anything fails up to the promote, the original journal is
            # untouched and the old write handle stays live.
            with open(path, encoding="utf-8") as src:
                new_handle = self._flocked_append(tmp)
                from tpuflow.storage.local import replace_file

                replace_file(tmp, path)  # the single point of no return
                old, self._journal_file = self._journal_file, new_handle
                old.close()
                # Archive only AFTER a successful promote, and only the
                # EVENT lines: snapshot lines are compaction's own
                # output (rewritten each epoch), and re-archiving them
                # would grow the archive by O(all historical jobs) per
                # restart. Every epoch's event history accretes — never
                # clobbered. Failure here is tolerable (history lost,
                # live journal correct), so it must not trip the outer
                # rollback of an already-promoted journal.
                try:
                    with open(
                        path + ".archive", "a", encoding="utf-8"
                    ) as dst:
                        for line in src:
                            try:
                                is_snap = (
                                    json.loads(line).get("event")
                                    == "snapshot"
                                )
                            except (json.JSONDecodeError, AttributeError):
                                is_snap = False  # keep corrupt tails
                            if not is_snap:
                                dst.write(line)
                except OSError as e:
                    import sys

                    print(
                        f"tpuflow.serve: journal history not archived "
                        f"({type(e).__name__}: {e})",
                        file=sys.stderr,
                    )
        except (OSError, RuntimeError) as e:
            import sys

            if new_handle is not None and new_handle is not self._journal_file:
                new_handle.close()
            try:
                os.unlink(tmp)
            except OSError:
                pass
            print(
                f"tpuflow.serve: journal compaction skipped "
                f"({type(e).__name__}: {e})",
                file=sys.stderr,
            )

    # ---- submission ----

    def _parse_spec(self, spec: dict):
        """Validate a job spec -> (kind, config, timeout_s). Raises on any
        invalid field (typos fail at submission, not mid-queue)."""
        base = dict(spec)
        compare_models = base.pop("compare", None)
        sweep_grid = base.pop("sweep", None)
        timeout_s = base.pop("timeoutSeconds", None)
        if timeout_s is None:
            timeout_s = base.pop("timeout_seconds", None)
        else:
            base.pop("timeout_seconds", None)
        if timeout_s is None:
            timeout_s = self.default_timeout
        if timeout_s is not None:
            timeout_s = float(timeout_s)
            if timeout_s <= 0:
                raise ValueError(
                    f"timeoutSeconds must be > 0, got {timeout_s}"
                )
        if compare_models is not None and sweep_grid is not None:
            raise ValueError("a job is either 'compare' or 'sweep', not both")
        config = spec_to_config(base)  # validate before queueing
        # NOTE: deeper spec preflight deliberately does NOT run here —
        # the submission contract accepts any well-formed spec (202) and
        # reports semantic errors through the job's own lifecycle. The
        # worker's train() preflights on startup, so a malformed job
        # still fails in milliseconds with the full diagnostic in its
        # error field, without ever reading data or compiling.
        if compare_models is not None:
            if not isinstance(compare_models, list) or not compare_models:
                raise ValueError("'compare' must be a non-empty list of models")
            from tpuflow.models import MODELS

            unknown = [m for m in compare_models if m not in MODELS]
            if unknown:  # typos fail at submission, not as all-FAILED rows
                raise ValueError(
                    f"unknown compare models {unknown}; known: {sorted(MODELS)}"
                )
            kind = ("compare", tuple(compare_models))
        elif sweep_grid is not None:
            if not isinstance(sweep_grid, dict) or not sweep_grid:
                raise ValueError("'sweep' must be a non-empty grid object")
            from tpuflow.api.sweep import _validate_name

            for name, values in sweep_grid.items():
                _validate_name(name)  # typos fail at submission, not later
                if not isinstance(values, list) or not values:
                    # A bare string would be swept character-by-character.
                    raise ValueError(
                        f"sweep axis {name!r} must map to a non-empty list"
                    )
            kind = ("sweep", sweep_grid)
        else:
            kind = ("train", None)
        return kind, config, timeout_s

    def submit(self, spec: dict) -> dict:
        kind, config, timeout_s = self._parse_spec(spec)
        job_id = uuid.uuid4().hex[:12]
        record = {"job_id": job_id, "status": "queued", "spec": spec}
        with self._lock:
            queued = sum(
                1 for r in self._jobs.values() if r["status"] == "queued"
            )
            if queued >= self.max_queued:
                raise queue.Full(
                    f"job queue full ({queued} queued, max {self.max_queued})"
                )
            # The "submitted" event is ENQUEUED inside the lock, before
            # the record becomes visible: a cancel() (or the worker) can
            # only reach this job through self._jobs, so every other
            # journal event for it lands after this one in buffer (and
            # therefore file) order — replay folds in file order and a
            # terminal-before-submitted pair would resurrect a cancelled
            # job. The disk write happens in the flush below, OUTSIDE
            # the lock, so a stalled journal filesystem can't block
            # every GET behind self._lock.
            self._journal_enqueue(
                event="submitted", job_id=job_id, spec=spec,
                timeout_s=timeout_s,
            )
            self._jobs[job_id] = record
            self._cancel_events[job_id] = threading.Event()
            self._counters["submitted"].inc()
        self._queue.put((job_id, kind, config, timeout_s))
        self._journal_flush()
        return {"job_id": job_id, "status": "queued"}

    def cancel(self, job_id: str) -> dict | None:
        """Cancel a job. Queued: cancelled immediately (the worker skips
        the stale queue entry when it pops it). Running: the cancel event
        is set and the job stops cooperatively at its next epoch boundary
        (status ``cancelling`` meanwhile). Terminal: ``{"conflict": True}``
        — there is nothing left to cancel. Unknown id: ``None``."""
        with self._lock:
            rec = self._jobs.get(job_id)
            if rec is None:
                return None
            status = rec["status"]
            if status == "queued":
                rec.update(status="cancelled", error="cancelled while queued")
                self._counters["cancelled"].inc()
                self._cancel_events.pop(job_id, None)
                # Enqueued atomically with the state change: no later
                # flush can ever write this job's events in an order
                # that resurrects it on replay.
                self._journal_enqueue(
                    event="terminal", job_id=job_id, status="cancelled",
                    error="cancelled while queued",
                )
                result = {"job_id": job_id, "status": "cancelled"}
            elif status in ("running", "cancelling"):
                rec["status"] = "cancelling"
                event = self._cancel_events.get(job_id)
                if event is not None:
                    event.set()
                return {"job_id": job_id, "status": "cancelling"}
            else:
                return {"job_id": job_id, "status": status, "conflict": True}
        # Flushed before the client sees "cancelled" — durable first,
        # reported second (best-effort; see _journal_flush on the
        # residual crash window).
        self._journal_flush()
        return result

    def get(self, job_id: str) -> dict | None:
        with self._lock:
            rec = self._jobs.get(job_id)
            return dict(rec) if rec else None

    def list(self) -> list[dict]:
        with self._lock:
            return [
                {"job_id": r["job_id"], "status": r["status"]}
                for r in self._jobs.values()
            ]

    @staticmethod
    def _tally(statuses: list[str]) -> tuple[int, int]:
        """(queued, running) from a status list — THE one place the
        status semantics live, shared by the JSON metrics() view and
        the registry's pull gauges (a new status classified here shows
        up in both, never one). A job being cancelled is still
        occupying the chip."""
        return (
            statuses.count("queued"),
            statuses.count("running") + statuses.count("cancelling"),
        )

    def _count_statuses(self) -> tuple[int, int]:
        # Briefly memoized: the two pull gauges both call this per
        # Prometheus scrape, and _jobs keeps every terminal job for the
        # daemon's lifetime — one lock + one scan should serve both.
        # 0.25s of staleness is nothing against a scrape interval.
        import time as _time

        now = _time.monotonic()
        ts, tallies = self._status_cache
        if now - ts > 0.25:
            with self._lock:
                statuses = [r["status"] for r in self._jobs.values()]
            tallies = self._tally(statuses)
            self._status_cache = (now, tallies)
        return tallies

    def metrics(self) -> dict:
        """One consistent snapshot: counters and live-status tallies from
        the same lock acquisition, so submitted == done + failed +
        queued + running always holds in a /metrics response (counter
        increments happen under this same lock)."""
        with self._lock:
            statuses = [r["status"] for r in self._jobs.values()]
            counters = {
                name: int(c.value()) for name, c in self._counters.items()
            }
        queued, running = self._tally(statuses)
        return {**counters, "queued": queued, "running": running}

    def _run(self):
        import time as _time

        from tpuflow.train.loop import TrainingInterrupted

        while True:
            job_id, kind, config, timeout_s = self._queue.get()
            with self._lock:
                rec = self._jobs.get(job_id)
                if rec is None or rec["status"] == "cancelled":
                    continue  # cancelled while queued: stale entry
                rec["status"] = "running"
                cancel_event = self._cancel_events.setdefault(
                    job_id, threading.Event()
                )
            self._journal(event="started", job_id=job_id)
            t_started = _time.monotonic()
            deadline = (
                t_started + timeout_s if timeout_s is not None else None
            )

            def stop_fn(
                ev=cancel_event, deadline=deadline, t=timeout_s,
                job_id=job_id, t_started=t_started,
            ):
                # Polled at every epoch boundary — piggyback a heartbeat
                # so GET /jobs/<id> shows liveness and progress, and a
                # stale heartbeat_age exposes a job hung inside one epoch
                # (which cooperative cancellation cannot reach).
                with self._lock:
                    rec = self._jobs.get(job_id)
                    if rec is not None:
                        rec["heartbeats"] = rec.get("heartbeats", 0) + 1
                        rec["running_s"] = round(
                            _time.monotonic() - t_started, 1
                        )
                if ev.is_set():
                    return "cancelled"
                if deadline is not None and _time.monotonic() > deadline:
                    return f"timeout after {t:g}s"
                return None

            try:
                rep = self._execute(kind, config, stop_fn)
                # Inside the try: a failed report write (unwritable dir,
                # missing gs:// backend, ...) must fail THIS job, not kill
                # the worker thread and silently wedge the whole queue.
                if config.storage_path:
                    report_name = f"{kind[0]}.{config.model}.report.json" \
                        if kind[0] != "train" else f"{config.model}.report.json"
                    path = join_path(
                        config.storage_path, "models", report_name
                    )
                    with open_file(path, "w", encoding="utf-8") as f:
                        json.dump(rep, f, indent=2)
                    rep["report_path"] = path
            except TrainingInterrupted as e:
                # Partial checkpoints may already be on disk — evict the
                # predict cache exactly like any other terminal state.
                self._notify_artifact(config, kind)
                if e.reason == "cancelled":
                    status, error = "cancelled", "cancelled while running"
                else:  # timeout
                    status, error = "failed", f"TrainingInterrupted: {e}"
                # Durable first, visible second (the cancel() discipline):
                # once get() reports this terminal state, the journal line
                # is best-effort on disk — a crash right after a client
                # polled "cancelled"/"failed" can't replay the job as
                # lost. Per-job order is safe off-lock: this worker is
                # the only journal writer for a running job, and its
                # submitted/started lines are already buffered ahead.
                self._journal(
                    event="terminal", job_id=job_id, status=status,
                    error=error,
                )
                with self._lock:
                    self._cancel_events.pop(job_id, None)
                    self._jobs[job_id].update(status=status, error=error)
                    self._counters[
                        "cancelled" if status == "cancelled" else "failed"
                    ].inc()
                continue
            except Exception as e:
                # Evict BEFORE publishing the terminal status: a client
                # that polls to completion and immediately predicts must
                # never see the pre-retrain cache entry.
                self._notify_artifact(config, kind)
                error = f"{type(e).__name__}: {e}"
                self._journal(  # durable first, visible second
                    event="terminal", job_id=job_id, status="failed",
                    error=error,
                )
                with self._lock:  # status + counter move atomically
                    self._cancel_events.pop(job_id, None)
                    self._jobs[job_id].update(status="failed", error=error)
                    self._counters["failed"].inc()
                continue
            self._notify_artifact(config, kind)
            self._journal(  # durable first, visible second
                event="terminal", job_id=job_id, status="done", report=rep
            )
            with self._lock:
                self._cancel_events.pop(job_id, None)
                # A cancel that landed after the last epoch finished: the
                # work is done; report it done (the cancel was a no-op).
                self._jobs[job_id].update(status="done", report=rep)
                self._counters["done"].inc()

    @staticmethod
    def _failed_rows(rpt, ident) -> list[dict]:
        # RankedByMAE.failed is the single source of the failure predicate.
        return [{**ident(r), "error": reason} for r, reason in rpt.failed]

    def _execute(self, kind, config, stop_fn=None) -> dict:
        from tpuflow.resilience import fault_point

        # Registered fault site: a drill armed here fails THE JOB through
        # the worker's normal error path (status "failed", queue alive) —
        # proving job-level failure containment without a real crash.
        fault_point("serve.execute")
        name, arg = kind
        if name == "train":
            from tpuflow.api import train

            return report_to_dict(train(config, stop_fn=stop_fn))
        if name == "compare":
            from tpuflow.api import compare

            rpt = compare(arg, config, stop_fn=stop_fn)
            return {
                "table": rpt.table(),
                "ranked": [
                    {"model": r.model, "test_mae": r.test_mae,
                     "gilbert_mae": r.gilbert_mae}
                    for r in rpt.ranked
                ],
                # Machine-readable failure rows: without these, a compare
                # where every model fails polls to status "done" with
                # ranked=[] and the errors live only in the human table.
                "failed": self._failed_rows(rpt, lambda r: {"model": r.model}),
            }
        from tpuflow.api import sweep

        rpt = sweep(arg, config, stop_fn=stop_fn)
        return {
            "table": rpt.table(),
            "ranked": [
                {"assignment": r.assignment, "test_mae": r.test_mae}
                for r in rpt.ranked
            ],
            "failed": self._failed_rows(rpt, lambda r: {"assignment": r.assignment}),
        }

    def _models_trained(self, config, kind) -> tuple:
        """Every model name a job (re)writes under its storage path —
        compare jobs retrain each listed family, and a sweep whose grid
        includes 'model' retrains each of those."""
        name, arg = kind
        if name == "compare":
            return tuple(arg)
        if name == "sweep" and "model" in arg:
            return tuple(arg["model"])
        return (config.model,)

    def _notify_artifact(self, config, kind=("train", None)):
        if self._on_artifact_change and config.storage_path:
            for model in self._models_trained(config, kind):
                try:
                    self._on_artifact_change(config.storage_path, model)
                except Exception as e:
                    # Per-model so one crashing eviction can't leave the
                    # REMAINING models' stale cache entries alive, and a
                    # crashing callback must not kill the worker thread
                    # (the job would be stuck 'running', the queue wedged).
                    import sys

                    print(
                        f"tpuflow.serve: artifact-change callback failed "
                        f"for {model!r}: {type(e).__name__}: {e}",
                        file=sys.stderr,
                    )


def _clean_trace_id(raw: str | None) -> str | None:
    """Clamp a client-supplied X-Trace-Id: tokens only, bounded length
    — THE one copy now lives in tpuflow/obs/tracing.py (the elastic
    transport and the TPUFLOW_TRACE_ID validation share it); this alias
    keeps the serving stack's historical import path working."""
    from tpuflow.obs.tracing import clean_trace_id

    return clean_trace_id(raw)


# One validated env-knob implementation for every TPUFLOW_* family
# (tpuflow/utils/env.py); re-exported here because the serving stack and
# its tests historically import them from tpuflow.serve.
from tpuflow.utils.env import env_choice, env_flag, env_num  # noqa: F401, E402


class PredictService:
    """Synchronous serving over trained artifacts, with a Predictor cache
    (loading parses the sidecar + restores params — do it once per
    artifact, not per request).

    Graceful degradation (``gilbert_fallback=True``, the default): when
    an artifact fails to LOAD — checkpoint missing, corrupt, storage
    gone — requests are answered by the paper's own physical baseline
    (``resilience/degraded.py``: the Gilbert choke equation) instead of
    500s, with ``degraded: true`` in every response and the artifact
    listed in ``/healthz``. Two recovery paths: a retrain that rewrites
    the artifact invalidates the cache entry immediately, and every
    cached fallback expires after ``degraded_retry_seconds`` (30) so a
    TRANSIENT load failure (storage briefly unreachable) re-probes the
    real artifact on its own instead of serving physics forever.
    Request-shaped errors (bad columns, malformed specs) still fail
    loudly; only load failures degrade.

    Serving fast path (docs/serving.md), OFF by default so single-caller
    workloads keep today's semantics and latency:

    - ``batch_predicts=True`` coalesces concurrent requests per artifact
      into shared jitted dispatches (``tpuflow/microbatch.py``): each
      request's feature transform stays per-request, the forwards merge.
      Degraded (Gilbert) answers are NEVER coalesced into model batches,
      and a retrain mid-flight never scatters stale predictions — the
      batcher groups by predictor instance, not just artifact key.
    - ``batch_mode`` picks the coalescing engine: ``"micro"`` (the
      wait-then-dispatch timer) or ``"continuous"`` (per-artifact
      dispatch lanes, admit-into-next-in-flight-dispatch, deadline
      shedding — the async control plane's engine; docs/serving.md).
    - ``warmup_buckets=N`` pre-compiles the N largest pow-2 forward
      buckets at artifact load time, so the first requests after a cold
      load or retrain don't each eat an XLA compile.
    - ``donate_forward=True`` donates the input batch buffer to the
      jitted forward (safe on this path: batches are built fresh per
      dispatch and never reused).
    - ``max_resident=N`` bounds the predictor cache (the multi-artifact
      placement policy): past N resident artifacts the least-recently-
      used one is spilled (cache evicted + its dispatch lane retired;
      ``spills`` counts them) — the next request for it re-loads. 0 =
      unbounded (the single-artifact workloads' historical behavior).
    - ``replicas=R`` is the multi-replica data plane
      (``tpuflow/serve_replica.py``; continuous engine only): every
      successfully loaded artifact becomes a ReplicaSet of R predictor
      clones placed one-per-device, each with its own dispatch lane;
      enqueues join the shortest queue. Reload/spill retires ALL of an
      artifact's replica lanes (zero dropped); a count the devices
      cannot place fails at construction naming the device count.

    Knob resolution: explicit argument > env var (``TPUFLOW_SERVE_BATCH``,
    ``TPUFLOW_SERVE_BATCH_MODE``, ``TPUFLOW_SERVE_MAX_BATCH``,
    ``TPUFLOW_SERVE_MAX_WAIT_MS``, ``TPUFLOW_SERVE_WARMUP``,
    ``TPUFLOW_SERVE_DONATE``, ``TPUFLOW_SERVE_RESIDENT``,
    ``TPUFLOW_SERVE_REPLICAS``) > default (off). Env values are
    validated at read time — a malformed value raises a ValueError
    naming the variable and the expected form (:func:`env_num`; the
    ``TPUFLOW_RETRY_*`` precedent).
    """

    def __init__(
        self,
        gilbert_fallback: bool = True,
        degraded_retry_seconds: float = 30.0,
        batch_predicts: bool | None = None,
        batch_mode: str | None = None,
        batch_max_rows: int | None = None,
        batch_max_wait_ms: float | None = None,
        warmup_buckets: int | None = None,
        donate_forward: bool | None = None,
        max_resident: int | None = None,
        replicas: int | None = None,
        registry=None,
    ):
        from tpuflow.obs import Registry

        self._cache: dict[tuple[str, str], object] = {}
        self._lock = threading.Lock()  # guards the dicts, never held on load
        self._key_locks: dict[tuple[str, str], threading.Lock] = {}
        self.gilbert_fallback = gilbert_fallback
        # Run-scoped metrics registry (tpuflow/obs): the JSON metrics()
        # keys are unchanged but now read registry counters, and the
        # same registry renders into /metrics?format=prometheus. Own
        # instance by default so parallel services never share counts.
        self.registry = registry if registry is not None else Registry()
        self._counters = {
            name: self.registry.counter(f"predict_{name}_total", help)
            for name, help in (
                ("requests", "/predict requests served (incl. failed)"),
                ("cache_hits", "predictor cache hits"),
                ("loads", "artifact loads (successful)"),
                ("invalidations", "cache evictions after artifact rewrites"),
                ("spills", "LRU cache evictions past max_resident"),
                ("degraded_requests", "requests answered by the fallback"),
                ("fallback_loads", "loads that fell back to Gilbert"),
                ("warmed_buckets", "forward buckets pre-compiled at load"),
            )
        }
        # Invalidation generation per key: a load that STARTED before an
        # invalidate() must not re-cache its (stale) result after it.
        self._gen: dict[tuple[str, str], int] = {}
        self.degraded_retry_seconds = degraded_retry_seconds
        # Artifacts currently served degraded: key -> load-failure reason.
        self._degraded: dict[tuple[str, str], str] = {}
        # When each fallback entry was cached (monotonic), for the TTL.
        self._degraded_at: dict[tuple[str, str], float] = {}
        # ---- fast-path knobs (argument > env > off) ----
        if batch_predicts is None:
            batch_predicts = env_flag("TPUFLOW_SERVE_BATCH", False)
        if batch_mode is None:
            batch_mode = env_choice(
                "TPUFLOW_SERVE_BATCH_MODE", "micro", ("micro", "continuous")
            )
        if batch_mode not in ("micro", "continuous"):
            raise ValueError(
                f"batch_mode must be 'micro' or 'continuous', "
                f"got {batch_mode!r}"
            )
        if batch_max_rows is None:
            batch_max_rows = env_num(
                "TPUFLOW_SERVE_MAX_BATCH", 256, int, minimum=1
            )
        if batch_max_wait_ms is None:
            batch_max_wait_ms = env_num(
                "TPUFLOW_SERVE_MAX_WAIT_MS", 2.0, float
            )
        if warmup_buckets is None:
            warmup_buckets = env_num("TPUFLOW_SERVE_WARMUP", 0, int)
        if donate_forward is None:
            donate_forward = env_flag("TPUFLOW_SERVE_DONATE", False)
        if max_resident is None:
            max_resident = env_num("TPUFLOW_SERVE_RESIDENT", 0, int)
        if replicas is None:
            replicas = env_num(
                "TPUFLOW_SERVE_REPLICAS", 1, int, minimum=1,
                form="an integer replica count >= 1",
            )
        self.warmup_buckets = int(warmup_buckets)
        self.donate_forward = bool(donate_forward)
        self.batch_max_rows = int(batch_max_rows)
        self.batch_mode = batch_mode
        # Placement policy: 0 = unbounded; past the bound the LRU
        # artifact spills (cache + lane). _last_used is touched on every
        # hit/load under self._lock.
        self.max_resident = int(max_resident)
        self._last_used: dict[tuple[str, str], float] = {}
        from tpuflow.microbatch import LatencyStats

        self._latency = LatencyStats()
        # Pull-style summary: the existing reservoir renders into the
        # Prometheus view without double-recording every sample.
        self.registry.summary(
            "predict_latency_ms",
            "per-request /predict latency (ms)",
            fn=self._latency.summary,
        )
        # Replica data plane (tpuflow/serve_replica.py): N predictor
        # replicas per artifact, placed across devices, one dispatch
        # lane each, join-shortest-queue at enqueue. Validated EAGERLY
        # against the engine (replica lanes exist only in the
        # continuous batcher) and the hardware (a count the devices
        # cannot place fails here naming the device count — the
        # analysis pass gives the same diagnostic preflight-style).
        self.replicas = int(replicas)
        if self.replicas > 1:
            if not batch_predicts or batch_mode != "continuous":
                raise ValueError(
                    f"replicas={self.replicas} needs the continuous "
                    "batching engine (replica dispatch lanes); pass "
                    "batch_predicts=True with batch_mode='continuous' "
                    "or unset TPUFLOW_SERVE_BATCH/_BATCH_MODE"
                )
            from tpuflow.parallel.placement import replica_devices

            replica_devices(self.replicas)  # raises naming the count
        self._replica_metrics_ready = False
        self._replica_dispatches = None
        self._replica_requests = None
        self._batcher = None
        if batch_predicts and batch_mode == "continuous":
            from tpuflow.microbatch import ContinuousBatcher

            # Lane bound: at least the residency bound (every resident
            # artifact must be able to hold a lane — times its replica
            # lanes), floor 32, operator override via
            # TPUFLOW_SERVE_MAX_LANES — a deployment with 40 active
            # artifacts must not shed the last 8 forever.
            self._batcher = ContinuousBatcher(
                self._run_forward,
                max_batch_rows=self.batch_max_rows,
                max_lanes=env_num(
                    "TPUFLOW_SERVE_MAX_LANES",
                    max(
                        32,
                        self.max_resident * self.replicas,
                        self.replicas,
                    ),
                    int, minimum=1,
                    form="an integer lane bound >= 1",
                ),
                registry=self.registry,
            )
            if self.replicas > 1:
                self._ensure_replica_metrics()
        elif batch_predicts:
            from tpuflow.microbatch import MicroBatcher

            self._batcher = MicroBatcher(
                self._run_forward,
                max_batch_rows=self.batch_max_rows,
                max_wait_ms=float(batch_max_wait_ms),
                registry=self.registry,
            )

    @staticmethod
    def _run_forward(pred, x):
        # The batcher's one hook: a denormalized forward over prepared
        # rows (one output row per input row; pow-2 padded inside).
        # The serve.execute fault site fires here too (the coalesced-
        # dispatch drill): an injected failure must fail exactly this
        # dispatch's requests and leave the batcher serving the next —
        # the MicroBatcher's errors-scatter-too contract, made testable.
        from tpuflow.resilience import fault_point

        fault_point("serve.execute")
        return pred.forward_prepared(x)

    def close(self) -> None:
        """Stop the dispatcher thread (tests / benchmark hygiene)."""
        if self._batcher is not None:
            self._batcher.close()

    def metrics(self) -> dict:
        """Counter snapshot under the lock — one consistent view, matching
        JobRunner.metrics()'s discipline — plus the latency percentiles
        and (when batching is on) the coalescing counters. The same
        registry backs the Prometheus exposition; JSON keys unchanged."""
        with self._lock:
            out = {
                name: int(c.value()) for name, c in self._counters.items()
            }
        out["latency_ms"] = self._latency.snapshot()
        out["batching"] = (
            self._batcher.metrics()
            if self._batcher is not None
            else {"enabled": False}
        )
        return out

    def invalidate(self, storage_path: str, name: str) -> None:
        """Drop a cached artifact (called when a job rewrites it) —
        including a degraded fallback entry, so a successful retrain is
        the recovery path out of degraded mode."""
        key = (storage_path, name)
        with self._lock:
            self._cache.pop(key, None)
            self._degraded.pop(key, None)
            self._degraded_at.pop(key, None)
            self._last_used.pop(key, None)
            self._gen[key] = self._gen.get(key, 0) + 1
            self._counters["invalidations"].inc()
        self._close_lane(key)

    def _close_lane(self, key: tuple[str, str]) -> None:
        """Retire an evicted artifact's dispatch lane(s) (continuous
        mode only — the micro-batcher has one shared dispatcher).
        Replica-aware: the artifact key is the PREFIX of its replica
        lane keys, so one call drains the plain lane and every replica
        lane alike. In-flight entries still drain — a reload or spill
        never drops a request; a later request reopens fresh lanes."""
        if self._batcher is None:
            return
        if hasattr(self._batcher, "close_lanes_for"):
            self._batcher.close_lanes_for(key)
        elif hasattr(self._batcher, "close_lane"):
            self._batcher.close_lane(key)

    def _replica_lane_count(self) -> int:
        """Resident replica lanes (keys carrying a replica index) — the
        ``serve_replica_lanes`` gauge."""
        if self._batcher is None or not hasattr(self._batcher, "lane_keys"):
            return 0
        return sum(1 for k in self._batcher.lane_keys() if len(k) == 3)

    def _on_replica_dispatch(self, key, requests, rows) -> None:
        """Batcher lane-dispatch hook: count completed dispatches per
        replica index (plain artifact lanes carry no index and are
        already counted by the batcher's own families)."""
        if len(key) == 3:
            self._replica_dispatches.inc(replica=str(key[2]))

    def _ensure_replica_metrics(self) -> None:
        """Register the per-replica metric families and hook the
        batcher's lane-dispatch callback. Idempotent: called at
        construction when replicas > 1, and again by
        :meth:`set_replicas` when a runtime resize first crosses 1.
        Registered HERE (not first-touched by a metrics scrape or a
        ReplicaSet) so the families always carry their help text — the
        registry is first-registrant-wins, and an early /metrics scrape
        must not blank the HELP line for the life of the process."""
        if self._replica_metrics_ready or self._batcher is None:
            return
        self.registry.gauge(
            "serve_replica_lanes",
            "replica dispatch lanes currently resident "
            "(artifact lanes with a replica index)",
            fn=self._replica_lane_count,
        )
        self._replica_dispatches = self.registry.counter(
            "serve_replica_dispatches_total",
            "device dispatches completed per replica lane, by "
            "replica index",
        )
        self._replica_requests = self.registry.counter(
            "serve_replica_requests_total",
            "requests routed to a replica lane by join-"
            "shortest-queue, by replica index",
        )
        self._batcher.on_lane_dispatch = self._on_replica_dispatch
        self._replica_metrics_ready = True

    def set_replicas(self, n: int) -> int:
        """Runtime replica resize — the autoscaler's data-plane seam.
        Validates like ``__init__`` (n >= 1; the continuous engine and
        a placeable device count for n > 1, with the same diagnostics),
        then walks the resident cache: ReplicaSets :meth:`resize` in
        place (retired replica lanes drain synchronously before their
        params are released), plain non-degraded predictors are wrapped
        when the width crosses above 1 (their plain artifact lane
        drains too — new picks go to replica lanes). Degraded fallbacks
        stay unwrapped, as at load. Returns the new width."""
        n = int(n)
        if n < 1:
            raise ValueError(
                f"set_replicas(n={n}): need an integer replica "
                "count >= 1"
            )
        if n > 1:
            if self._batcher is None or self.batch_mode != "continuous":
                raise ValueError(
                    f"replicas={n} needs the continuous batching "
                    "engine (replica dispatch lanes); construct the "
                    "service with batch_predicts=True and "
                    "batch_mode='continuous'"
                )
            from tpuflow.parallel.placement import replica_devices

            replica_devices(n)  # raises naming the device count
            self._ensure_replica_metrics()
        with self._lock:
            if n == self.replicas:
                return n
            self.replicas = n
            entries = list(self._cache.items())
        from tpuflow.serve_replica import ReplicaSet

        retire: list[tuple] = []
        for key, pred in entries:
            if isinstance(pred, ReplicaSet):
                retire.extend(pred.resize(n))
            elif n > 1 and not getattr(pred, "degraded", False):
                wrapped = self._wrap_replicas(key, pred)
                with self._lock:
                    # Swap only if the entry is still the predictor we
                    # wrapped — a concurrent invalidate/reload wins.
                    if self._cache.get(key) is pred:
                        self._cache[key] = wrapped
                    else:
                        wrapped = None
                if wrapped is not None:
                    # The plain artifact lane stops receiving picks;
                    # drain what it already queued.
                    retire.append(key)
        if self._batcher is not None:
            for k in retire:
                if hasattr(self._batcher, "retire_lane"):
                    self._batcher.retire_lane(k)
                elif hasattr(self._batcher, "close_lane"):
                    self._batcher.close_lane(k)
        return n

    def _wrap_replicas(self, key: tuple[str, str], loaded):
        """Wrap a successfully loaded predictor in a ReplicaSet when the
        service is configured for more than one replica. Degraded
        fallbacks are never wrapped — physics answers take the
        unbatched path and replicating them buys nothing."""
        with self._lock:
            width = self.replicas
        if width <= 1 or getattr(loaded, "degraded", False):
            return loaded
        from tpuflow.serve_replica import ReplicaSet

        return ReplicaSet(loaded, key, width, registry=self.registry)

    def select_lane(self, key: tuple, pred) -> tuple[tuple, object]:
        """The enqueue-time lane decision: a ReplicaSet picks its
        least-loaded replica lane (join-shortest-queue); a plain
        predictor keeps its artifact lane. Returns ``(lane_key,
        predictor_instance)`` — what the batcher is handed."""
        pick = getattr(pred, "pick_lane", None)
        if pick is None:
            return key, pred
        return pick(self._batcher)

    def replica_metrics(self) -> dict:
        """The ``replicas`` /metrics section: configured width, lane
        residency, and the per-replica routing/dispatch/depth split
        (aggregated across artifacts — replica index i of every
        resident ReplicaSet shares a label)."""
        with self._lock:
            width = self.replicas
        out: dict = {
            "configured": width,
            "policy": "jsq",
            "lanes": self._replica_lane_count(),
            "requests_by_replica": {},
            "dispatches_by_replica": {},
            "queue_depth_rows": {},
        }
        if width <= 1 or self._batcher is None:
            return out
        if hasattr(self._batcher, "lane_stats"):
            for k, stats in self._batcher.lane_stats().items():
                if len(k) != 3:
                    continue
                r = str(k[2])
                out["queue_depth_rows"][r] = (
                    out["queue_depth_rows"].get(r, 0)
                    + stats["queued_rows"] + stats["inflight_rows"]
                )
        for labels in self._replica_requests.labels_seen():
            out["requests_by_replica"][labels.get("replica", "?")] = int(
                self._replica_requests.value(**labels)
            )
        for labels in self._replica_dispatches.labels_seen():
            out["dispatches_by_replica"][labels.get("replica", "?")] = (
                int(self._replica_dispatches.value(**labels))
            )
        return out

    def _spill_lru_locked(self) -> list[tuple[str, str]]:
        """Evict least-recently-used cache entries past ``max_resident``
        (caller holds ``self._lock``). Returns the spilled keys so the
        caller can retire their lanes OUTSIDE the lock. Spills don't
        bump the invalidation generation — the artifact on disk is
        unchanged, so a load already in flight for a spilled key may
        still cache its (current) result."""
        if self.max_resident <= 0:
            return []
        spilled = []
        while len(self._cache) > self.max_resident:
            key = min(
                self._cache, key=lambda k: self._last_used.get(k, 0.0)
            )
            self._cache.pop(key, None)
            self._degraded.pop(key, None)
            self._degraded_at.pop(key, None)
            self._last_used.pop(key, None)
            # Bound the per-key bookkeeping too: a rotating long tail of
            # once-touched artifacts must not leak a Lock + generation
            # per key for the process lifetime. A key lock currently
            # held by an in-flight load stays (with its generation, so
            # that load's cache-if-unchanged check still works); it is
            # pruned the next time the key spills idle.
            # Benign race: a loader that setdefault'd this lock but has
            # not acquired it yet may end up duplicating a cold load
            # against a fresh lock — the generation check keeps the
            # cache consistent either way; a rare wasted load is the
            # price of the bound.
            lock = self._key_locks.get(key)
            if lock is not None and not lock.locked():
                del self._key_locks[key]
                self._gen.pop(key, None)
            self._counters["spills"].inc()
            spilled.append(key)
        return spilled

    def degraded(self) -> list[dict]:
        """Artifacts currently answering in degraded (Gilbert) mode."""
        with self._lock:
            return [
                {"storage_path": sp, "model": name, "reason": reason}
                for (sp, name), reason in self._degraded.items()
            ]

    def _cached_locked(self, key):
        """Cache lookup under ``self._lock`` (caller holds it). A
        degraded entry past its TTL reads as a miss — and is evicted —
        so the next load re-probes the real artifact: a fallback cached
        during a transient storage outage must not outlive the outage."""
        cached = self._cache.get(key)
        if cached is None:
            return None
        if getattr(cached, "degraded", False):
            import time as _time

            at = self._degraded_at.get(key, 0.0)
            if _time.monotonic() - at > self.degraded_retry_seconds:
                self._cache.pop(key, None)
                self._degraded.pop(key, None)
                self._degraded_at.pop(key, None)
                # Keep the per-key bookkeeping bounded here too (the
                # spill/invalidate paths already do): a long tail of
                # once-degraded artifacts must not pin a timestamp per
                # key forever.
                self._last_used.pop(key, None)
                return None
        return cached

    def _predictor(self, storage_path: str, name: str):
        from tpuflow.api.predict_api import Predictor

        key = (storage_path, name)
        with self._lock:
            cached = self._cached_locked(key)
            if cached is not None:
                self._counters["cache_hits"].inc()
                self._last_used[key] = _monotonic()
                return cached
            key_lock = self._key_locks.setdefault(key, threading.Lock())
        # Load under the PER-KEY lock only: a cold (possibly seconds-long
        # gs:// restore) load must not serialize cache hits or loads of
        # other artifacts.
        with key_lock:
            with self._lock:
                cached = self._cached_locked(key)
                if cached is not None:
                    self._counters["cache_hits"].inc()
                    self._last_used[key] = _monotonic()
                    return cached
                gen = self._gen.get(key, 0)
            try:
                loaded = Predictor.load(
                    storage_path, name, donate_forward=self.donate_forward
                )
            except Exception as e:
                # Checkpoint missing/corrupt/unreachable — the
                # degradation trigger. try_fallback returns None when
                # the sidecar is gone too (nothing proves the artifact
                # ever existed; a typo'd model name must keep failing
                # loudly, not be silently answered by physics).
                if not self.gilbert_fallback:
                    raise
                from tpuflow.resilience import try_fallback

                reason = f"{type(e).__name__}: {e}"
                loaded = try_fallback(storage_path, name, reason)
                if loaded is None:
                    raise
                import sys

                print(
                    f"tpuflow.serve: artifact {name!r} failed to load "
                    f"({reason}); serving DEGRADED (Gilbert baseline)",
                    file=sys.stderr,
                )
                spilled = []
                with self._lock:
                    self._counters["fallback_loads"].inc()
                    if self._gen.get(key, 0) == gen:
                        # Cache the fallback too (no per-request load
                        # storm against dead storage); evicted by any
                        # retrain (invalidate) or by the degraded TTL —
                        # the two recovery paths.
                        self._cache[key] = loaded
                        self._degraded[key] = reason
                        self._degraded_at[key] = _monotonic()
                        self._last_used[key] = _monotonic()
                        spilled = self._spill_lru_locked()
                for sk in spilled:
                    self._close_lane(sk)
                return loaded
            # Replica placement happens BEFORE warmup so every
            # replica's device gets its executables compiled, and under
            # the per-key lock so concurrent cold requests build one
            # ReplicaSet, not R of them.
            loaded = self._wrap_replicas(key, loaded)
            warmed = 0
            if self.warmup_buckets > 0:
                # Pre-compile the top pow-2 forward buckets while still
                # under the per-key lock (other artifacts stay servable):
                # the first requests after this cold load — including the
                # reload after a retrain eviction — hit compiled code.
                # Best-effort: a warmup failure must not fail the load.
                try:
                    warmed = len(loaded.warmup(
                        top=self.warmup_buckets, max_rows=self.batch_max_rows
                    ))
                except Exception as e:
                    import sys

                    print(
                        f"tpuflow.serve: bucket warmup for {name!r} failed "
                        f"({type(e).__name__}: {e}); serving without it",
                        file=sys.stderr,
                    )
            spilled = []
            with self._lock:
                # ONE acquisition for the counter and the cache insert:
                # a concurrent metrics() snapshot must never see the
                # loads counter bumped while the entry is still missing
                # (or vice versa). Counted only AFTER a successful load —
                # a missing/corrupt artifact that raises must not inflate
                # the loads number.
                self._counters["loads"].inc()
                self._counters["warmed_buckets"].inc(warmed)
                if self._gen.get(key, 0) == gen:
                    self._cache[key] = loaded
                    self._last_used[key] = _monotonic()
                    # The placement policy: inserting past max_resident
                    # spills the LRU artifact(s); their lanes retire
                    # outside the lock.
                    spilled = self._spill_lru_locked()
                # else: the artifact was rewritten mid-load; serve this
                # request from what was loaded but don't poison the cache.
            for sk in spilled:
                self._close_lane(sk)
            return loaded

    def predict(self, spec: dict) -> dict:
        """One request, end to end; wall time (including any micro-batch
        queue wait) is recorded into the latency reservoir whether the
        request succeeds or raises — p99 must not hide the failures.

        Trace propagation: the caller's bound trace ID (the HTTP handler
        binds ``X-Trace-Id``; Python callers may ``use_trace`` their
        own) — or a fresh one — rides the request into the micro-batch
        dispatch and is echoed back as ``trace_id`` in the response, so
        one caller's answer is linkable to the coalesced device dispatch
        that produced it."""
        import time as _time

        from tpuflow.obs import current_trace_id, use_trace

        t0 = _time.perf_counter()
        with use_trace(current_trace_id()) as trace_id:
            try:
                out = self._predict(spec)
                out["trace_id"] = trace_id
                return out
            finally:
                self._latency.record(_time.perf_counter() - t0)

    # ---- the request pipeline, split so the async front end can run
    # ---- each blocking half on an executor with the coalesced forward
    # ---- awaited in between (tpuflow/serve_async.py)

    def begin_request(self, spec: dict):
        """Blocking first half of one /predict: count it, validate the
        spec shape, resolve the predictor (cache hit, cold load, or
        Gilbert fallback). Returns ``(key, pred, payload)`` where
        payload is ``("data", path)`` or ``("columns", {name: array})``;
        request-shaped errors raise ValueError here, before any batch
        the request might have joined."""
        import numpy as np

        with self._lock:
            self._counters["requests"].inc()
        storage = spec.get("storagePath") or spec.get("storage_path")
        name = spec.get("model") or spec.get("name")
        if not storage or not name:
            raise ValueError("predict needs storagePath and model")
        if "data" in spec:
            payload = ("data", spec["data"])
        elif "columns" in spec:
            payload = (
                "columns",
                {k: np.asarray(v) for k, v in spec["columns"].items()},
            )
        else:
            raise ValueError("predict needs data (csv path) or columns")
        pred = self._predictor(storage, name)
        return (storage, name), pred, payload

    @staticmethod
    def coalescable(pred) -> bool:
        """Degraded answers are NEVER coalesced into model batches: the
        fallback has no jitted forward to share, and mixing physics rows
        into a model dispatch would scatter baseline numbers to callers
        expecting model predictions."""
        return not getattr(pred, "degraded", False)

    @staticmethod
    def transform_request(pred, payload):
        """The per-request feature transform (blocking, CPU): raw
        payload -> model-ready rows for the coalesced forward."""
        kind, value = payload
        columns = pred.columns_from_csv(value) if kind == "data" else value
        x, _ = pred.prepare_columns(columns)
        return x

    @staticmethod
    def answer_unbatched(pred, payload):
        """The per-request path (degraded predictors, batching off):
        transform + forward in one blocking call."""
        kind, value = payload
        if kind == "data":
            return pred.predict_csv(value)
        return pred.predict_columns(value)

    def finish_response(self, pred, y) -> dict:
        """Shape the response dict (+ the degraded honesty flags)."""
        import numpy as np

        y = np.asarray(y)
        out = {"predictions": y.tolist(), "count": int(len(y))}
        if getattr(pred, "degraded", False):
            # The caller must be able to tell physics-fallback answers
            # from model answers — degraded mode is honest, not silent.
            out["degraded"] = True
            out["fallback"] = "gilbert"
            out["degraded_reason"] = pred.reason
            with self._lock:
                self._counters["degraded_requests"].inc()
        return out

    @property
    def batcher(self):
        """The coalescing engine (None with batching off) — the async
        front end enqueues into it directly, with deadlines."""
        return self._batcher

    def record_latency(self, seconds: float) -> None:
        """Record one request's wall time into the shared reservoir
        (the async front end's requests must show up in the same
        ``latency_ms`` percentiles the threaded ones do)."""
        self._latency.record(seconds)

    def _predict(self, spec: dict) -> dict:
        key, pred, payload = self.begin_request(spec)
        if self._batcher is not None and self.coalescable(pred):
            x = self.transform_request(pred, payload)
            if len(x) == 0:
                y = pred.forward_prepared(x)
            else:
                # The predictor instance rides with the entry so a
                # retrain mid-flight can't scatter another generation's
                # predictions to this caller. A ReplicaSet resolves to
                # its least-loaded replica lane here (JSQ).
                lane_key, lane_pred = self.select_lane(key, pred)
                y = self._batcher.submit(lane_key, lane_pred, x)
        else:
            y = self.answer_unbatched(pred, payload)
        return self.finish_response(pred, y)


def make_server(
    host: str = "127.0.0.1",
    port: int = 8700,
    max_queued: int = 64,
    default_timeout: float | None = None,
    journal_path: str | None = None,
    batch_predicts: bool | None = None,
    batch_mode: str | None = None,
    batch_max_rows: int | None = None,
    batch_max_wait_ms: float | None = None,
    warmup_buckets: int | None = None,
    donate_forward: bool | None = None,
    max_resident: int | None = None,
    trail_path: str | None = None,
    slo_objectives=None,
) -> ThreadingHTTPServer:
    """Build the HTTP server (caller drives serve_forever / shutdown).

    The ``batch_*`` / ``warmup_buckets`` / ``donate_forward`` knobs are
    the serving fast path (PredictService docstring; docs/serving.md);
    ``None`` defers to the ``TPUFLOW_SERVE_*`` env vars, default off.
    ``trail_path`` (also ``TPUFLOW_SERVE_TRAIL``) appends the daemon's
    lifecycle events as JSONL — its lane in ``python -m tpuflow.obs
    fleet``."""
    import time as _time

    from tpuflow.microbatch import QueueFull
    from tpuflow.obs import Registry, record_event, use_trace
    from tpuflow.obs.slo import SloEngine, serve_objectives

    started = _time.monotonic()  # immune to wall-clock steps
    # ONE run-scoped registry for the whole daemon: predictor, batcher,
    # and job-runner counters render in a single Prometheus scrape
    # (GET /metrics?format=prometheus), alongside the process-wide
    # default registry (fault injections, I/O retries, train loop).
    registry = Registry()
    registry.gauge(
        "uptime_seconds", "seconds since the daemon started",
        fn=lambda: _time.monotonic() - started,
    )
    # SLO engine (tpuflow/obs/slo.py): objectives scored at scrape time
    # from the daemon's own counters — the `slo` JSON section plus
    # slo_error_budget_remaining{objective=}/slo_burn_rate gauges in
    # the Prometheus exposition.
    slo = SloEngine(serve_objectives(slo_objectives), registry=registry)
    if trail_path is None:
        trail_path = os.environ.get("TPUFLOW_SERVE_TRAIL") or None
    trail = None
    if trail_path:
        from tpuflow.utils.logging import MetricsLogger

        trail = MetricsLogger(trail_path)
        trail.write(
            "serve_started", daemon="threaded", host=host, port=port,
        )
    # History + alerts (tpuflow/obs/history.py, alerts.py). The
    # threaded daemon runs NO sampler thread: each /metrics scrape
    # drives maybe_sample(), so history advances at scrape cadence
    # (bounded by TPUFLOW_OBS_HISTORY_INTERVAL_S) and an idle daemon
    # spends nothing. The SLO pre-sample hook refreshes the slo_*
    # gauges before every tick so burn-rate rules see current values.
    from tpuflow.obs.alerts import AlertEngine, rules_from_objectives
    from tpuflow.obs.history import MetricsHistory

    history = MetricsHistory(registry)
    history.add_pre_sample(lambda: slo.evaluate_registry(registry))
    alerts = AlertEngine(
        history,
        rules_from_objectives(
            serve_objectives(slo_objectives),
            for_s=env_num("TPUFLOW_SERVE_ALERT_FOR_S", 15.0, float),
        ),
        registry=registry,
        logger=trail,
    )
    alerts.attach()
    # Profiling plane + flight recorder (tpuflow/obs/profiler.py,
    # flight.py), env-gated off by default. The threaded daemon samples
    # the whole process (its stdlib handler threads carry no tpuflow
    # prefix to scope by); the recorder captures an atomic forensic
    # bundle on every firing alert transition.
    from tpuflow.obs.flight import flight_from_env
    from tpuflow.obs.profiler import profiler_from_env

    profiler = profiler_from_env(registry)
    flight = flight_from_env(
        history=history, profiler=profiler, registry=registry, logger=trail,
    )
    if flight is not None:
        flight.attach(alerts)
    if profiler is not None:
        profiler.start()
    predictor = PredictService(
        batch_predicts=batch_predicts,
        batch_mode=batch_mode,
        batch_max_rows=batch_max_rows,
        batch_max_wait_ms=batch_max_wait_ms,
        warmup_buckets=warmup_buckets,
        donate_forward=donate_forward,
        max_resident=max_resident,
        registry=registry,
    )
    # Retraining an artifact this process has served must evict the cached
    # Predictor, or /predict would keep returning the old model forever.
    runner = JobRunner(
        on_artifact_change=predictor.invalidate,
        max_queued=max_queued,
        default_timeout=default_timeout,
        journal_path=journal_path,
        registry=registry,
    )

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, payload: dict | list):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _route(self) -> str:
            # Query strings (cache-busting pollers etc.) are not routing.
            from urllib.parse import urlsplit

            return urlsplit(self.path).path.rstrip("/")

        def do_GET(self):
            route = self._route()
            parts = route.split("/")
            if route in ("", "/health", "/healthz"):
                # Liveness plus degradation: a load-balancer health poll
                # sees "degraded" (still 200 — the service IS answering,
                # from the physical baseline) and which artifacts fell
                # back, so degraded serving is operable, not invisible.
                deg = predictor.degraded()
                self._send(200, {
                    "status": "degraded" if deg else "ok",
                    "degraded": bool(deg),
                    "degraded_artifacts": deg,
                })
            elif route == "/jobs":
                self._send(200, runner.list())
            elif route == "/metrics":
                # ?format=prometheus: text exposition over the daemon's
                # run-scoped registry plus the process-wide default one
                # (fault-injection and I/O-retry counters). The JSON
                # view — and its keys — are unchanged.
                from urllib.parse import parse_qs, urlsplit

                fmt = parse_qs(urlsplit(self.path).query).get(
                    "format", [""]
                )[0]
                if fmt == "prometheus":
                    from tpuflow.obs import (
                        default_registry,
                        render_prometheus,
                    )

                    # Refresh the SLO gauges first: the slo_* families
                    # must reflect THIS scrape's counter state. The
                    # history tick (rate-limited to its cadence) also
                    # advances alert hold-down clocks, so the
                    # obs_alerts_firing gauges below are current.
                    slo.evaluate_registry(registry)
                    history.maybe_sample()
                    body = render_prometheus(
                        registry, default_registry()
                    ).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                history.maybe_sample()
                self._send(200, {
                    "jobs": runner.metrics(),
                    "predict": predictor.metrics(),
                    "slo": slo.evaluate_registry(registry),
                    "alerts": alerts.summary(),
                    "uptime_s": round(_time.monotonic() - started, 1),
                })
            elif len(parts) == 3 and parts[1] == "jobs":
                rec = runner.get(parts[2])
                if rec is None:
                    self._send(404, {"error": f"no job {parts[2]!r}"})
                else:
                    self._send(200, rec)
            else:
                self._send(404, {"error": f"no route {self.path!r}"})

        def _read_spec(self) -> dict:
            # Clamp: a negative Content-Length would turn read() into
            # read-to-EOF and hang the handler thread on keep-alive.
            length = max(0, int(self.headers.get("Content-Length", 0)))
            spec = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(spec, dict):
                raise ValueError("request body must be a JSON object")
            return spec

        def do_POST(self):
            route = self._route()
            if route == "/jobs":
                try:
                    self._send(202, runner.submit(self._read_spec()))
                except queue.Full:
                    self._send(429, {
                        "error": f"job queue full (max {runner.max_queued}); "
                        "retry after a job finishes"
                    })
                except (ValueError, TypeError, json.JSONDecodeError) as e:
                    self._send(400, {"error": str(e)})
            elif route == "/predict":
                try:
                    spec = self._read_spec()
                except (ValueError, TypeError, json.JSONDecodeError) as e:
                    self._send(400, {"error": str(e)})
                    return
                # The caller's X-Trace-Id (fresh when absent or not a
                # bounded token — see _clean_trace_id) rides the request
                # into the coalesced dispatch and back out as trace_id
                # in EVERY response — the failures are the responses one
                # most wants to correlate.
                with use_trace(
                    _clean_trace_id(self.headers.get("X-Trace-Id"))
                ) as tid:
                    try:
                        self._send(200, predictor.predict(spec))
                    except ValueError as e:
                        self._send(400, {"error": str(e), "trace_id": tid})
                    except QueueFull as e:
                        # Backpressure shed, not a server bug: the same
                        # 503 retry-with-backoff contract the async
                        # front end answers (microbatch.QueueFull).
                        self._send(503, {
                            "error": str(e), "shed": "queue",
                            "trace_id": tid,
                        })
                    except Exception as e:  # missing artifact, bad columns
                        self._send(500, {
                            "error": f"{type(e).__name__}: {e}",
                            "trace_id": tid,
                        })
            elif route == "/artifacts/reload":
                # The online loop's swap signal (tpuflow/online;
                # docs/online.md): drop the cached predictor so the next
                # request loads the just-promoted artifact. In-flight
                # requests finish against the old instance — the
                # batchers group by predictor INSTANCE — so a reload
                # never drops or cross-wires a request.
                try:
                    spec = self._read_spec()
                except (ValueError, TypeError, json.JSONDecodeError) as e:
                    self._send(400, {"error": str(e)})
                    return
                storage = spec.get("storagePath") or spec.get("storage_path")
                name = spec.get("model") or spec.get("name")
                if not storage or not name:
                    self._send(400, {
                        "error": "reload needs storagePath and model"
                    })
                    return
                # The online loop's lifecycle trace rides the nudge as
                # X-Trace-Id: the reload record carries it, closing the
                # drift -> retrain -> swap -> reload chain across the
                # process boundary (tpuflow/obs/tracing.py).
                with use_trace(
                    _clean_trace_id(self.headers.get("X-Trace-Id"))
                ) as tid:
                    predictor.invalidate(storage, name)
                    rec = record_event(
                        "serve_reload", daemon="threaded",
                        storage_path=storage, model=name,
                    )
                    if trail is not None:
                        trail.write(
                            "serve_reload",
                            **{k: v for k, v in rec.items()
                               if k not in ("event", "time")},
                        )
                self._send(200, {
                    "reloaded": True, "storage_path": storage, "model": name,
                    "trace_id": tid,
                })
            else:
                self._send(404, {"error": f"no route {self.path!r}"})

        def do_DELETE(self):
            route = self._route()
            parts = route.split("/")
            if len(parts) == 3 and parts[1] == "jobs":
                res = runner.cancel(parts[2])
                if res is None:
                    self._send(404, {"error": f"no job {parts[2]!r}"})
                elif res.pop("conflict", False):
                    self._send(409, {
                        **res,
                        "error": f"job already {res['status']}",
                    })
                else:
                    self._send(200, res)
            else:
                self._send(404, {"error": f"no route {self.path!r}"})

        def log_message(self, fmt, *args):  # quiet by default
            pass

    class Server(ThreadingHTTPServer):
        # http.server's default listen backlog is 5: under bursty
        # concurrent clients (each urllib request is a fresh TCP
        # connection) the 6th simultaneous connect gets RST. A deeper
        # accept queue is the first thing any fronting proxy would
        # assume; 128 matches common server defaults.
        request_queue_size = 128

        def shutdown(self):
            # The profiler's sampler (and its spill) must stop with the
            # daemon; everything else tears down in close_server paths.
            if profiler is not None:
                profiler.stop()
            super().shutdown()

    server = Server((host, port), Handler)
    server.runner = runner  # for tests / callers
    server.predictor = predictor
    server.history = history
    server.alerts = alerts
    server.profiler = profiler
    server.flight = flight
    return server


def main(argv=None) -> int:
    import argparse
    import signal

    p = argparse.ArgumentParser(
        prog="tpuflow.serve", description="tpuflow training job-runner service"
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8700)
    p.add_argument(
        "--max-queued", type=int, default=64,
        help="bounded job queue size; POST /jobs returns 429 past it",
    )
    p.add_argument(
        "--default-timeout", type=float, default=None,
        help="per-job runtime budget in seconds for jobs that don't set "
        "timeoutSeconds (cooperative, between epochs)",
    )
    p.add_argument(
        "--journal", default=None, metavar="PATH",
        help="JSONL job journal: job history survives restarts, "
        "never-started jobs are requeued, mid-run jobs marked lost",
    )
    p.add_argument(
        # BooleanOptionalAction: --no-batch-predicts must be able to
        # override TPUFLOW_SERVE_BATCH=1 back to off (argument > env).
        "--batch-predicts", action=argparse.BooleanOptionalAction,
        default=None,
        help="coalesce concurrent /predict requests per artifact into "
        "shared jitted dispatches (also TPUFLOW_SERVE_BATCH=1)",
    )
    p.add_argument(
        "--batch-max-rows", type=int, default=None, metavar="N",
        help="dispatch a coalesced batch once N rows accumulate "
        "(default 256; also TPUFLOW_SERVE_MAX_BATCH)",
    )
    p.add_argument(
        "--batch-max-wait-ms", type=float, default=None, metavar="MS",
        help="max time a request waits to be coalesced before its batch "
        "dispatches anyway (default 2.0; also TPUFLOW_SERVE_MAX_WAIT_MS)",
    )
    p.add_argument(
        "--warmup-buckets", type=int, default=None, metavar="K",
        help="pre-compile the K largest pow-2 forward buckets at artifact "
        "load time (default 0 = off; also TPUFLOW_SERVE_WARMUP)",
    )
    p.add_argument(
        "--donate-forward", action=argparse.BooleanOptionalAction,
        default=None,
        help="donate the input batch buffer to the jitted forward "
        "(also TPUFLOW_SERVE_DONATE=1)",
    )
    p.add_argument(
        "--trail", default=None, metavar="PATH",
        help="append lifecycle events (startup, trace-stamped "
        "/artifacts/reload records) as JSONL here — this daemon's lane "
        "in `python -m tpuflow.obs fleet` (also TPUFLOW_SERVE_TRAIL)",
    )
    args = p.parse_args(argv)

    server = make_server(
        args.host, args.port,
        max_queued=args.max_queued,
        default_timeout=args.default_timeout,
        journal_path=args.journal,
        batch_predicts=args.batch_predicts,
        batch_max_rows=args.batch_max_rows,
        batch_max_wait_ms=args.batch_max_wait_ms,
        warmup_buckets=args.warmup_buckets,
        donate_forward=args.donate_forward,
        trail_path=args.trail,
    )

    def _stop(signum, frame):
        threading.Thread(
            target=server.shutdown, name="tpuflow-serve-shutdown", daemon=True,
        ).start()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    print(f"tpuflow job server on http://{args.host}:{args.port}", flush=True)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
