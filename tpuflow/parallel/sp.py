"""Sequence (context) parallelism: time-axis sharding for long well logs.

The reference family's sequences are short 24-step windows, handled
on-chip by ``lax.scan`` (SURVEY.md §5.7) — but the framework is designed
for logs far longer than one chip's HBM can hold activations for. This
module shards the **time axis** of the LSTM recurrence across the mesh:

- each device owns a contiguous time chunk of the input projections
  (``xw [T/N, B, 4H]``) and materializes only its chunk's activations —
  an N-fold activation-memory reduction, the point of context
  parallelism for recurrent models;
- the carry ``(h, c)`` is handed around the device ring with
  ``lax.ppermute`` — one tiny [B, H]×2 transfer per round riding ICI;
- the wall-clock stays O(T) (an LSTM's dependency chain is inherently
  sequential — unlike attention, time cannot be parallelized away), so
  this trades idle compute for memory capacity. Shard batch for
  throughput, shard time for length (SURVEY.md §5.7's "shard batch,
  never time" is about throughput; this is the capacity story).

For the attention-free model family this is the honest TPU equivalent of
ring-attention-style context parallelism: same ring topology, same
carry-passing collective, applied to a recurrence.

The ring scan is **training-capable**: it differentiates through the
ppermute carry ring (tested against the on-chip scan's gradients). Take
gradients inside a ``with set_mesh(mesh):`` context (``tpuflow.parallel.set_mesh``) — the transpose
of the shard_map program needs the mesh to type its cotangents.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuflow.parallel.compat import shard_map
from tpuflow.parallel.collectives import ppermute_ring
from tpuflow.parallel.mesh import DATA_AXIS


def _lstm_chunk_scan(carry, xw_chunk, wh, b):
    """Plain lax.scan over one local time chunk. xw_chunk: [t, B, 4H].

    Cell math comes from ``tpuflow.models.lstm.lstm_step`` — the single
    source shared with the on-chip scan path.
    """
    from tpuflow.models.lstm import lstm_step

    return lax.scan(
        lambda c, xw_t: lstm_step(c, xw_t, wh, b), carry, xw_chunk
    )


def ring_lstm_scan(
    mesh: Mesh,
    xw: jnp.ndarray,
    wh: jnp.ndarray,
    b: jnp.ndarray,
    axis: str = DATA_AXIS,
):
    """Time-sharded LSTM scan over the mesh ring: ``xw [T,B,4H] -> hs [T,B,H]``.

    ``T`` must divide by the axis size. Device ``k`` owns timesteps
    ``[k*T/N, (k+1)*T/N)`` and stores only that chunk's activations. The
    ring runs ``N`` rounds; in round ``r`` device ``r``'s chunk is the
    active one and its final carry is ppermuted to device ``r+1``.

    Returns the full hidden sequence, sharded along time.
    """
    n = mesh.shape[axis]
    T = xw.shape[0]
    if T % n:
        raise ValueError(f"sequence length {T} not divisible by {axis}={n}")
    return _ring_scan_fn(mesh, axis)(xw, wh, b)


@functools.lru_cache(maxsize=32)
def _ring_scan_fn(mesh: Mesh, axis: str):
    """The jitted ring-scan program, cached per (mesh, axis): repeated
    calls (every training step) dispatch the compiled program instead of
    re-tracing a fresh shard_map closure each time."""
    n = mesh.shape[axis]

    def body(xw_local, wh, b):
        # xw_local: [T/n, B, 4H] — this device's time chunk.
        B, H = xw_local.shape[1], wh.shape[0]
        idx = lax.axis_index(axis)
        zero = (
            jnp.zeros((B, H), xw_local.dtype),
            jnp.zeros((B, H), xw_local.dtype),
        )
        hs_out = jnp.zeros(
            (xw_local.shape[0], B, H), xw_local.dtype
        )
        received = zero
        for r in range(n):
            start = received if r > 0 else zero
            # Every device runs its chunk scan each round (SPMD); only the
            # active device's round-r results are kept.
            carry_in = jax.tree_util.tree_map(
                lambda z, s: jnp.where(idx == r, s, z), zero, start
            )
            (h_end, c_end), hs = _lstm_chunk_scan(carry_in, xw_local, wh, b)
            active = idx == r
            hs_out = jnp.where(active, hs, hs_out)
            # Hand the active device's end-carry to its right neighbor.
            received = jax.tree_util.tree_map(
                lambda t: ppermute_ring(t, axis), (h_end, c_end)
            )
        return hs_out

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis), P(), P()),
            out_specs=P(axis),
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=32)
def make_sp_forward(
    mesh: Mesh, hidden: int, axis: str = DATA_AXIS
) -> Callable:
    """Jitted long-sequence LSTM forward: (params-tuple, x [B,T,F]) -> [B,T,H].

    Hoists the input projection (embarrassingly parallel along time, done
    sharded), then runs the ring scan. Params are the same (w_x, w_h, b)
    pytree an ``LSTMLayer`` learns — usable directly for sharded inference
    over logs too long for one chip.
    """

    def forward(w_x, w_h, b, x):
        B, T, F = x.shape
        xw = (x.reshape(B * T, F) @ w_x).reshape(B, T, 4 * hidden)
        xw = jnp.swapaxes(xw, 0, 1)  # [T, B, 4H]
        hs = ring_lstm_scan(mesh, xw, w_h, b, axis=axis)
        return jnp.swapaxes(hs, 0, 1)

    return jax.jit(forward)
