"""Device placement seam: the one place that asks jax for devices.

The compat module (``parallel/compat.py``) owns the APIs that move
across jax releases; this module owns the APIs that move across
*deployments* — how many devices exist, which one a value should live
on, what kind of chip is underneath. Serving replica placement
(``tpuflow/serve_replica.py``), mesh construction, prefetch, and the
roofline's device-kind probe all route through here, so "where does
work land" is answered in exactly one file:

- a laptop/CI host can fan a single CPU into N schedulable devices with
  ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (the host-side
  replica recipe, docs/serving.md) and every caller sees them;
- a future remote/multi-host placement policy changes this module, not
  a dozen ``jax.devices()`` call sites.

Lint rule TPF013 (``tpuflow/analysis/linter.py``) makes the seam
executable — the TPF008 compat-seam precedent: a direct
``jax.devices()`` / ``jax.device_put()`` reference outside
``tpuflow/parallel/`` fails the self-lint gate instead of scattering
placement decisions back across the tree.
"""

from __future__ import annotations

import jax


def local_devices() -> list:
    """The devices THIS process can dispatch to, in jax's stable order
    (the order every mesh/replica index refers to)."""
    return list(jax.devices())


def device_count() -> int:
    """How many devices :func:`local_devices` returns."""
    return len(local_devices())


def device_kind(default: str = "unknown") -> str:
    """The chip kind of device 0 (roofline peaks are keyed by it);
    ``default`` when the backend does not say."""
    devices = local_devices()
    if not devices:
        return default
    return getattr(devices[0], "device_kind", default)


def replica_devices(n: int, devices=None) -> list:
    """The first ``n`` devices, for ``n`` predictor replicas — one
    replica per device, never oversubscribed. Raises a ValueError that
    names the available count and the host-side recipe, so a replica
    count the hardware cannot place fails as configuration advice, not
    as a runtime crash deep in a device_put."""
    devices = local_devices() if devices is None else list(devices)
    if n < 1:
        raise ValueError(f"replica count must be >= 1, got {n}")
    if n > len(devices):
        raise ValueError(
            f"cannot place {n} replicas on {len(devices)} available "
            f"device(s); lower the replica count or add devices "
            "(host-side: XLA_FLAGS=--xla_force_host_platform_device_"
            f"count={n})"
        )
    return devices[:n]


def place(tree, device):
    """Commit a pytree to one device (committed semantics: computation
    over it runs THERE — the serving-replica placement primitive)."""
    return jax.device_put(tree, device)


def device_put(x, where=None):
    """``jax.device_put`` through the seam: default device when
    ``where`` is None, else the given device or sharding."""
    if where is None:
        return jax.device_put(x)
    return jax.device_put(x, where)
