"""Pipeline parallelism: GPipe-style microbatch pipelining over the mesh.

The reference has no pipeline parallelism (SURVEY.md §2 lists PP as out of
scope for parity — its models are KBs of params), but the framework keeps
every scaling-book axis *expressible* with the same explicit-collective
``shard_map`` vocabulary as the DP/TP/SP modules. This module is the PP
building block:

- the ``model`` axis holds one pipeline **stage** per device (each device
  owns only its stage's params — the memory win of PP);
- **microbatches** flow stage→stage around the device ring with
  ``lax.ppermute`` — one [B, F] activation transfer per tick riding ICI;
- the schedule is the classic GPipe fill/steady/drain: with S stages and
  M microbatches the pipeline runs ``M + S - 1`` ticks, bubble fraction
  ``(S-1)/(M+S-1)`` — raise M to amortize.

All stages must share one activation shape (in_dim == out_dim), the
standard homogeneous-stage pipeline; heterogeneous stages belong at the
XLA level, not this building block.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuflow.parallel.compat import shard_map
from tpuflow.parallel.mesh import MODEL_AXIS


def gpipe_schedule(axis: str, n_stages: int, chunk_fn: Callable, xs):
    """THE GPipe fill/steady/drain tick loop, shared by the forward block
    below and the trainer (``pp_train``): runs ``chunk_fn`` (this
    device's stage compute, already closed over its local params) for
    ``M + S - 1`` ticks, injecting microbatches at stage 0, banking the
    last stage's outputs, and rotating activations around the ring. One
    schedule, one place — a drain/fill fix here fixes every pipeline
    user. Call inside ``shard_map`` over ``axis``; ``xs`` is the local
    ``[M, B, F]`` microbatch stack; returns the last stage's outputs
    broadcast to every device of the ring (psum of one non-zero
    contribution)."""
    n_micro = xs.shape[0]
    stage = lax.axis_index(axis)
    zero = jnp.zeros(xs.shape[1:], xs.dtype)
    outputs = jnp.zeros_like(xs)
    ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(t, carry):
        received, outputs = carry
        # Stage 0 injects microbatch t during the fill/steady phase;
        # other stages consume what the ring delivered last tick.
        inject = xs[jnp.minimum(t, n_micro - 1)]
        feed = jnp.where((stage == 0) & (t < n_micro), inject, received)
        out = chunk_fn(feed)
        # The LAST stage emits microbatch t-(S-1) once the pipe fills.
        m = t - (n_stages - 1)
        valid = (stage == n_stages - 1) & (m >= 0)
        slot = jnp.maximum(m, 0)
        prev = lax.dynamic_index_in_dim(outputs, slot, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid, out, prev), slot, 0
        )
        received = lax.ppermute(out, axis, ring)
        return received, outputs

    (_, outputs) = lax.fori_loop(
        0, n_micro + n_stages - 1, tick, (zero, outputs)
    )
    mask = (stage == n_stages - 1).astype(xs.dtype)
    return lax.psum(outputs * mask, axis)


@functools.lru_cache(maxsize=32)
def _pipeline_fn(mesh: Mesh, axis: str, stage_fn: Callable):
    """Jitted pipeline program, cached per (mesh, axis, stage_fn) — the
    same repeated-calls-dispatch-don't-retrace pattern as tp.py. Shapes
    (M, B, F) stay dynamic to jit's own shape cache."""
    n_stages = mesh.shape[axis]

    def body(params_local, xs):
        # params_local: [1, ...] — this device's stage. xs: [M, B, F].
        params_one = jax.tree_util.tree_map(lambda p: p[0], params_local)
        return gpipe_schedule(
            axis, n_stages, lambda h: stage_fn(params_one, h), xs
        )

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=P(),
            check_vma=False,
        )
    )


def pipeline_forward(
    mesh: Mesh,
    stage_fn: Callable,
    stage_params,
    microbatches: jnp.ndarray,
    axis: str = MODEL_AXIS,
) -> jnp.ndarray:
    """Run ``stage_fn`` as an S-stage pipeline over M microbatches.

    Args:
      mesh: mesh whose ``axis`` dimension is the pipeline (S stages).
      stage_fn: ``(params_one_stage, x [B, F]) -> [B, F]`` — one stage's
        compute; applied by every device to its local stage params. Pass a
        module-level function (not a fresh lambda per call) so the cached
        compiled program is reused.
      stage_params: pytree of ``[S, ...]`` stacked per-stage params,
        sharded on the leading (stage) dim over ``axis``.
      microbatches: ``[M, B, F]`` replicated input microbatches.

    Returns:
      ``[M, B, F]`` outputs after all S stages, replicated.
    """
    return _pipeline_fn(mesh, axis, stage_fn)(stage_params, microbatches)
