"""Device-mesh construction and sharding helpers.

Axis conventions (scaling-book style):
- ``data``  — batch (data-parallel) axis; gradient all-reduce rides ICI.
- ``model`` — reserved tensor-parallel axis (size 1 for the flow models,
  which are far below the per-chip HBM limit, but the API keeps it
  expressible per SURVEY.md §2's TP note).

``make_mesh`` here is THE mesh factory: every strategy module (dp/tp/pp/
ep/sp, ring attention) and ``analysis/plan.py``'s divisibility rules
build on it (the arithmetic half is ``data_axis_size``, shared so a plan
rejected at preflight and a mesh rejected at construction are the same
rule). Version differences in the underlying jax API are absorbed by
``tpuflow.parallel.compat`` — nothing else in the package talks to
``jax.make_mesh`` directly (lint rule TPF008).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuflow.parallel import compat

DATA_AXIS = "data"
MODEL_AXIS = "model"


def data_axis_size(n_devices: int, n_model: int = 1) -> int:
    """Data-axis size of a ``(data, model)`` mesh over ``n_devices``.

    The one divisibility rule shared by ``make_mesh`` and the preflight
    plan checker (``analysis/plan.py``): the device count must tile the
    model axis exactly.
    """
    if n_model < 1:
        raise ValueError(f"model axis must be >= 1, got {n_model}")
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    if n_devices % n_model:
        raise ValueError(
            f"n_devices {n_devices} not divisible by model axis {n_model}"
        )
    return n_devices // n_model


def make_mesh(
    n_data: int | None = None,
    n_model: int = 1,
    devices=None,
    axis_types=None,
) -> Mesh:
    """Build a ``(data, model)`` mesh over the available devices.

    Defaults to all devices on the data axis — the reference family's only
    parallelism (SURVEY.md §2 "Parallelism strategies"). ``axis_types``
    passes through to the compat layer's mesh constructor (advisory: on a
    jax with explicit axis types it selects them; on one without, every
    mesh runs in the default GSPMD/auto mode and the hint is dropped —
    see ``tpuflow/parallel/compat.py``'s policy). The GSPMD
    tensor-parallel trainer passes Auto so the compiler propagates
    shardings through the model (see parallel/tp_train.py).
    """
    devices = devices if devices is not None else jax.devices()
    if n_data is None:
        n_data = data_axis_size(len(devices), n_model)
    if n_data * n_model != len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_model} != {len(devices)} devices"
        )
    return compat.make_mesh(
        (n_data, n_model),
        (DATA_AXIS, MODEL_AXIS),
        axis_types=axis_types,
        devices=devices,
    )


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-dim sharding: leading axis split over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
