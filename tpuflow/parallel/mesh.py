"""Device-mesh construction and sharding helpers.

Axis conventions (scaling-book style):
- ``data``  — batch (data-parallel) axis; gradient all-reduce rides ICI.
- ``model`` — reserved tensor-parallel axis (size 1 for the flow models,
  which are far below the per-chip HBM limit, but the API keeps it
  expressible per SURVEY.md §2's TP note).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    n_data: int | None = None,
    n_model: int = 1,
    devices=None,
    axis_types=None,
) -> Mesh:
    """Build a ``(data, model)`` mesh over the available devices.

    Defaults to all devices on the data axis — the reference family's only
    parallelism (SURVEY.md §2 "Parallelism strategies"). ``axis_types``
    passes through to ``jax.make_mesh`` (default: JAX's Explicit axes,
    right for the shard_map paths); the GSPMD tensor-parallel trainer
    passes Auto so the compiler propagates shardings through the model
    (see parallel/tp_train.py).
    """
    devices = devices if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devices) // n_model
    if n_data * n_model != len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_model} != {len(devices)} devices"
        )
    return jax.make_mesh(
        (n_data, n_model),
        (DATA_AXIS, MODEL_AXIS),
        axis_types=axis_types,
        devices=devices,
    )


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-dim sharding: leading axis split over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
