"""Tensor-parallel TRAINING for the MLP families (GSPMD, megatron layout).

``tp.py`` provides the explicit shard_map column->row blocks and their
grad-parity proofs; this module makes a model family actually *train*
with a model axis, reachable from ``train(config)`` via
``TrainJobConfig(tp=N)``. It uses the scaling-book recipe directly: build
a ``(data, model)`` mesh, annotate the param layout (alternating
column/row-parallel Dense kernels — the megatron pattern of
``tp.tp_mlp_forward``), and let XLA insert the collectives when the
ordinary train step is jitted over the mesh:

- batch sharded on ``data``  -> gradient all-reduce (DP),
- hidden dim sharded on ``model`` -> one activation all-reduce per
  column->row pair (TP), exactly the psum ``tp._mlp_fn`` writes by hand.

The reference has no TP (SURVEY.md §2: its models are KBs), so this is a
beyond-parity capability; it exists so a family that outgrows one chip's
HBM shards its feature dimensions without leaving ``fit()``. Multi-host:
``train(config)`` feeds per-process batch slices over the TP mesh's data
axis (the DP branch's recipe), provided every process's devices cover
whole data-axis rows (local device count divisible by tp); exercised by
a real 2-process run in ``tests/test_multiprocess.py``.
"""

from __future__ import annotations

import re
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuflow.core.losses import mae_clip
from tpuflow.parallel.mesh import MODEL_AXIS, make_mesh

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def make_tp_mesh(n_data: int, n_model: int, devices=None):
    """A ``(data, model)`` mesh with AUTO axis types: the trainer relies
    on GSPMD propagating the megatron param shardings through the model
    body (a jax line whose default is Explicit axes would instead demand
    per-op ``out_sharding`` annotations on the sharded contractions).
    ``AxisType`` comes from the compat layer — on a jax without explicit
    axis types the hint is dropped and the mesh runs in the default
    GSPMD/auto mode, which is the same behavior this function asks for.
    """
    from tpuflow.parallel.compat import AxisType

    return make_mesh(
        n_data=n_data,
        n_model=n_model,
        devices=devices,
        axis_types=(AxisType.Auto, AxisType.Auto),
    )

_DENSE = re.compile(r"^Dense_(\d+)$")


def mlp_tp_shardings(mesh: Mesh, params, axis: str = MODEL_AXIS):
    """Megatron layout for a Dense-stack params tree (Static/DynamicMLP).

    Hidden layers alternate column-parallel (kernel ``[F, H]`` sharded on
    H, bias sharded) and row-parallel (kernel ``[H, F]`` sharded on H,
    bias replicated); the final Dense (the scalar head) is replicated.
    Raises for non-Dense-stack trees — silently replicating everything
    would "work" while quietly not being tensor parallel at all.
    """
    n_model = mesh.shape[axis]
    names = list(params.keys())
    idx = {}
    for name in names:
        m = _DENSE.match(name)
        if m is None or set(params[name].keys()) - {"kernel", "bias"}:
            raise ValueError(
                f"tp training supports Dense-stack MLP families; got layer "
                f"{name!r} (params: {sorted(params[name].keys()) if hasattr(params[name], 'keys') else type(params[name])})"
            )
        idx[name] = int(m.group(1))
    ordered = sorted(names, key=idx.__getitem__)
    hidden, head = ordered[:-1], ordered[-1]
    if not hidden:
        raise ValueError(
            "tp training needs at least one hidden Dense layer to shard; "
            "a head-only MLP would silently train fully replicated"
        )

    rep = NamedSharding(mesh, P())
    out = {}
    for pos, name in enumerate(hidden):
        kernel = params[name]["kernel"]
        column = pos % 2 == 0
        dim = kernel.shape[1] if column else kernel.shape[0]
        if dim % n_model:
            raise ValueError(
                f"{name} hidden dim {dim} not divisible by {axis}={n_model}"
            )
        if column:
            out[name] = {
                "kernel": NamedSharding(mesh, P(None, axis)),
                "bias": NamedSharding(mesh, P(axis)),
            }
        else:
            out[name] = {
                "kernel": NamedSharding(mesh, P(axis, None)),
                "bias": rep,
            }
    out[head] = {"kernel": rep, "bias": rep}
    return out


def shard_state(mesh: Mesh, state, param_shardings):
    """Lay a TrainState out over the mesh: params (and every params-shaped
    optimizer buffer, e.g. the SGD momentum trace) in the TP layout,
    everything else replicated."""
    rep = NamedSharding(mesh, P())
    ptreedef = jax.tree.structure(state.params)

    params = jax.tree.map(jax.device_put, state.params, param_shardings)

    def _params_like(sub) -> bool:
        if isinstance(sub, jax.Array) or not hasattr(sub, "keys"):
            return False
        try:
            return jax.tree.structure(sub) == ptreedef
        except TypeError:
            return False

    def _put(sub):
        if _params_like(sub):
            # Momentum (etc.) must shard exactly like its params: a
            # replicated trace against sharded params would silently
            # all-gather every step.
            return jax.tree.map(jax.device_put, sub, param_shardings)
        return jax.device_put(sub, rep)

    opt_state = jax.tree.map(_put, state.opt_state, is_leaf=_params_like)
    return state.replace(
        step=jax.device_put(state.step, rep),
        params=params,
        opt_state=opt_state,
    )


def state_shardings(state):
    """The sharding pytree of an already-laid-out TrainState (for
    ``out_shardings``: the step must hand back the layout it received,
    never let GSPMD re-shard mid-run)."""
    return jax.tree.map(lambda x: x.sharding, state)


def make_tp_train_step(state, loss_fn: LossFn = mae_clip):
    """Jitted (state, x, y, rng) -> (state, metrics) over the state's mesh.

    The body is the ordinary single-chip step — no explicit collectives.
    GSPMD derives them from the shardings: pmean-equivalent gradient
    all-reduce over ``data``, the megatron activation psum over ``model``
    (the hand-written pattern in ``tp._mlp_fn``, compiler-inserted).
    ``state`` is the already-sharded TrainState (its shardings pin the
    output layout).
    """
    sh = state_shardings(state)
    mesh = jax.tree.leaves(sh)[0].mesh
    rep = NamedSharding(mesh, P())

    def step(state, x, y, rng):
        dropout_rng = jax.random.fold_in(rng, state.step)

        def loss_of(params):
            pred = state.apply_fn(
                {"params": params},
                x,
                deterministic=False,
                rngs={"dropout": dropout_rng},
            )
            return loss_fn(y, pred)

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss}

    return jax.jit(
        step,
        donate_argnums=(0,),
        out_shardings=(sh, {"loss": rep}),
    )


def make_masked_eval_step(forward: Callable, loss_fn: LossFn = mae_clip):
    """THE masked-sum eval step (same contract as train.make_eval_step),
    shared by every model-axis trainer: ``forward(state, x) -> pred``
    plugs in the strategy's forward (GSPMD apply for TP, the pipelined
    program for PP, the routed program for EP); the masked aggregation
    is written once."""

    def step(state, x, y, mask):
        pred = forward(state, x)
        per_loss = jax.vmap(loss_fn)(y, pred)
        per_mae = jnp.abs(y - pred).reshape(y.shape[0], -1).mean(axis=1)
        return {
            "loss_sum": jnp.sum(per_loss * mask),
            "mae_sum": jnp.sum(per_mae * mask),
            "count": jnp.sum(mask),
        }

    return jax.jit(step)


def make_tp_eval_step(loss_fn: LossFn = mae_clip):
    """Jitted masked-sum eval step; shardings propagate from the
    operands (GSPMD apply — the megatron layout needs no explicit
    collectives at eval either)."""
    return make_masked_eval_step(
        lambda state, x: state.apply_fn(
            {"params": state.params}, x, deterministic=True
        ),
        loss_fn,
    )
