"""Data-parallel SPMD train/eval steps.

The TPU-native equivalent of the reference's Spark-executor data
parallelism (SURVEY.md §2, §5.8): each device holds a replica of the
params and a shard of the batch; gradients are all-reduced with
``lax.pmean`` over the ``data`` mesh axis inside one compiled step. The
SPMD region is expressed with ``shard_map`` (the compat layer's
version-probed wrapper) — collectives are explicit
and auditable — then jitted, so XLA lays the all-reduce on ICI.

Per-device RNG is decorrelated by folding the device's axis index into the
dropout key.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuflow.core.losses import mae_clip
from tpuflow.parallel.compat import shard_map
from tpuflow.parallel.mesh import DATA_AXIS, data_sharding

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def make_dp_train_step(
    mesh: Mesh, loss_fn: LossFn = mae_clip, axis: str = DATA_AXIS
):
    """Jitted SPMD step: (state, x, y, rng) -> (state, metrics).

    ``state`` is replicated; ``x``/``y`` are sharded on the batch dim.
    """

    def body(state, x, y, rng):
        # Decorrelate dropout across devices and steps.
        local_rng = jax.random.fold_in(
            jax.random.fold_in(rng, state.step), lax.axis_index(axis)
        )

        def loss_of(params):
            pred = state.apply_fn(
                {"params": params},
                x,
                deterministic=False,
                rngs={"dropout": local_rng},
            )
            return loss_fn(y, pred)

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        # The DP collective: gradient all-reduce over ICI.
        grads = lax.pmean(grads, axis)
        loss = lax.pmean(loss, axis)
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss}

    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_dp_epoch_step(
    mesh: Mesh, loss_fn: LossFn = mae_clip, axis: str = DATA_AXIS
):
    """Jitted SPMD WHOLE-EPOCH step: (state, xs, ys, rng) -> (state, loss).

    The data-parallel counterpart of ``train.steps.make_epoch_step``:
    ``xs [n_batches, B, ...]`` / ``ys`` are the epoch's pre-batched data
    sharded on the batch dim (dim 1) over the data axis, and the batch loop
    is a ``lax.scan`` *inside* the shard_map body, so K train steps — each
    with its pmean gradient all-reduce on ICI — compile into ONE XLA
    program per dispatch. This removes the per-batch Python dispatch that
    otherwise bounds DP throughput at small batch sizes (the reference's
    batch of 20, cnn.py:128).

    Dropout rng folds (batch index, device index) like the single-chip
    epoch scan + the per-batch DP step combined.
    """

    def body(state, xs, ys, rng):
        dev = lax.axis_index(axis)

        def batch_step(state, batch):
            x, y, i = batch
            local_rng = jax.random.fold_in(jax.random.fold_in(rng, i), dev)

            def loss_of(params):
                pred = state.apply_fn(
                    {"params": params},
                    x,
                    deterministic=False,
                    rngs={"dropout": local_rng},
                )
                return loss_fn(y, pred)

            loss, grads = jax.value_and_grad(loss_of)(state.params)
            grads = lax.pmean(grads, axis)
            loss = lax.pmean(loss, axis)
            state = state.apply_gradients(grads=grads)
            return state, loss

        idx = jnp.arange(xs.shape[0])
        state, losses = lax.scan(batch_step, state, (xs, ys, idx))
        return state, jnp.mean(losses)

    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(None, axis), P(None, axis), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def epoch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding for stacked epoch arrays ``[n_batches, B, ...]``: the batch
    dim (dim 1) split over the data axis."""
    return NamedSharding(mesh, P(None, axis))


def _assemble(sharding: NamedSharding, *arrays):
    """Per-input routing shared by ``shard_batch``/``shard_epoch``:

    - jax.Arrays pass through with a no-op ``device_put`` — ``np.asarray``
      on a pod-global array would crash, never fetch it back to the host;
    - on a multi-process runtime, numpy inputs are THIS process's local
      slice, assembled into one pod-global array via
      ``make_array_from_process_local_data``;
    - single-host numpy inputs are ``device_put`` whole.
    """
    multi = jax.process_count() > 1

    def put(a):
        if isinstance(a, jax.Array):
            return jax.device_put(a, sharding)
        local = a if isinstance(a, np.ndarray) else np.asarray(a)
        if multi:
            return jax.make_array_from_process_local_data(sharding, local)
        return jax.device_put(local, sharding)

    out = tuple(put(a) for a in arrays)
    return out if len(out) > 1 else out[0]


def shard_epoch(mesh: Mesh, *arrays, axis: str = DATA_AXIS):
    """Assemble stacked epoch arrays ``[n_batches, B_local, ...]`` into
    mesh-sharded globals — the epoch-scan counterpart of ``shard_batch``
    (dim 1 is the batch dim; use ``process_batch_bounds`` over the global
    B to pick this process's slice). Routing per ``_assemble``.
    """
    return _assemble(epoch_sharding(mesh, axis), *arrays)


def make_dp_eval_step(
    mesh: Mesh, loss_fn: LossFn = mae_clip, axis: str = DATA_AXIS
):
    """Jitted SPMD eval step with masked sums (see train.steps.make_eval_step)."""

    def body(state, x, y, mask):
        pred = state.apply_fn({"params": state.params}, x, deterministic=True)
        per_loss = jax.vmap(loss_fn)(y, pred)
        per_mae = jnp.abs(y - pred).reshape(y.shape[0], -1).mean(axis=1)
        return {
            "loss_sum": lax.psum(jnp.sum(per_loss * mask), axis),
            "mae_sum": lax.psum(jnp.sum(per_mae * mask), axis),
            "count": lax.psum(jnp.sum(mask), axis),
        }

    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(axis)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)


def shard_batch(mesh: Mesh, *arrays):
    """Turn host batches into mesh-sharded global arrays (leading dim over
    the data axis).

    Single-host: a plain ``device_put`` of the full global batch. On a
    multi-host pod (``jax.process_count() > 1``) each process passes only
    its OWN slice of the global batch — the cluster-resident-data story
    (reference Readme.md:3): every host feeds its addressable chips, no
    host ever materializes the global batch — and the slices are assembled
    into one global jax.Array via ``make_array_from_process_local_data``.
    Use ``process_batch_bounds`` to decide which rows this process loads.
    Inputs that are already ``jax.Array``s (e.g. prefetched pre-sharded
    batches) pass through with a no-op ``device_put``, never fetched back
    to the host.

    The global batch size must divide the data-axis size (keep batches a
    multiple of the mesh; the host pipeline's drop_remainder guarantees
    this).
    """
    return _assemble(data_sharding(mesh), *arrays)


def make_process_fed_steps(mesh: Mesh, train_fn, eval_fn):
    """Wrap per-device (state, xs, ys, ...) step fns with THE per-process
    feeding recipe, shared by every multi-host-capable strategy branch
    (DP and TP today; PP/EP when they grow multi-host): single-host
    passes batches through whole; on a multi-process runtime each host
    slices its ``process_batch_bounds`` rows and ``shard_batch``
    assembles the slices into pod-global arrays over the mesh's data
    axis. Already-global ``jax.Array`` inputs (prefetched pre-sharded
    batches) pass through unsliced."""
    multi = jax.process_count() > 1

    def _local(*arrays):
        if not multi or isinstance(arrays[0], jax.Array):
            return arrays
        lo, hi = process_batch_bounds(len(arrays[0]))
        return tuple(a[lo:hi] for a in arrays)

    def train_step(state, x, y, rng):
        xs, ys = shard_batch(mesh, *_local(x, y))
        return train_fn(state, xs, ys, rng)

    def eval_step(state, x, y, mask):
        xs, ys, ms = shard_batch(mesh, *_local(x, y, mask))
        return eval_fn(state, xs, ys, ms)

    return train_step, eval_step


def process_batch_bounds(
    global_batch: int,
    process_id: int | None = None,
    process_count: int | None = None,
) -> tuple[int, int]:
    """[start, stop) rows of the global batch THIS process should load.

    The host-side half of the multi-host data path: each process reads
    only its contiguous slice (matching ``shard_batch``'s per-process
    assembly), so no host touches more than ``global_batch / processes``
    rows — HDFS-style cluster-resident reading, TPU-native.
    """
    pid = jax.process_index() if process_id is None else process_id
    n = jax.process_count() if process_count is None else process_count
    if global_batch % n:
        raise ValueError(
            f"global batch {global_batch} not divisible by {n} processes"
        )
    per = global_batch // n
    return pid * per, (pid + 1) * per


def replicate(mesh: Mesh, tree):
    """Replicate a pytree (e.g. TrainState) across the mesh.

    Single-host: a plain ``device_put``. On a multi-process runtime the
    mesh spans devices this process cannot address, which ``device_put``
    rejects — each process instead contributes its (identical, same-seed
    SPMD program) full copy through the per-process assembly path, the
    same route ``shard_batch`` uses for batch shards.
    """
    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)

    def put(leaf):
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(leaf)
        )

    return jax.tree_util.tree_map(put, tree)
