"""Named wrappers over XLA collectives.

The framework's communication vocabulary — psum / pmean / all_gather /
reduce_scatter / ring ppermute — compiled by XLA onto ICI (in-pod) or DCN
(cross-pod), replacing the reference's implicit Spark JVM shuffle/RPC
transport (SURVEY.md §5.8). All of these are meaningful only inside an
SPMD region (``shard_map``).
"""

from __future__ import annotations

from jax import lax

from tpuflow.parallel.compat import axis_size
from tpuflow.parallel.mesh import DATA_AXIS


def psum(x, axis: str = DATA_AXIS):
    return lax.psum(x, axis)


def pmean(x, axis: str = DATA_AXIS):
    return lax.pmean(x, axis)


def all_gather(x, axis: str = DATA_AXIS, *, tiled: bool = True):
    return lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x, axis: str = DATA_AXIS):
    """Sum-reduce across the axis, scattering equal chunks of the leading
    dim to each participant."""
    return lax.psum_scatter(x, axis, tiled=True)


def ppermute_ring(x, axis: str = DATA_AXIS, shift: int = 1):
    """Rotate shards around the mesh axis ring — the primitive under ring
    attention and pipeline schedules."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)
