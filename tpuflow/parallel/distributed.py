"""Multi-host initialization + the pod recipe.

On a multi-host pod, ``jax.distributed.initialize`` brings up the
cross-host control plane (DCN); in-pod collectives still ride ICI. This is
the moral equivalent of the reference's ``spark-submit`` cluster attach
(reference Readme.md:3) — one call, environment-driven, no-op when single
process.

The full multi-host recipe (every process runs the same program):

    init_distributed()                  # env-driven; no-op single-host
    mesh = make_mesh()                  # over jax.devices() = ALL hosts' chips
    state = replicate(mesh, create_state(...))
    step = make_dp_train_step(mesh)     # or make_dp_epoch_step
    lo, hi = process_batch_bounds(GLOBAL_BATCH)
    for x, y in my_loader(rows=slice(lo, hi)):   # read ONLY this host's slice
        xs, ys = shard_batch(mesh, x, y)  # per-process assembly on pods
        state, metrics = step(state, xs, ys, rng)

Each host loads only its ``GLOBAL_BATCH / process_count`` rows
(``process_batch_bounds``); ``shard_batch`` assembles the per-process
slices into one global array via ``make_array_from_process_local_data`` —
the cluster-resident-data story with no host ever holding the global
batch. Metrics come back replicated (pmean'd), identical on every host.
"""

from __future__ import annotations

import os

import jax


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize multi-host JAX if a cluster environment is present.

    Explicit args win; otherwise standard env vars
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``
    or a TPU pod's auto-detected environment) are used. Returns True if
    distributed mode was initialized.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])

    if coordinator_address is None and num_processes is None:
        return False  # single-process: nothing to do
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True
