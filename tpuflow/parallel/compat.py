"""The one jax-version seam for the parallelism stack.

Everything in tpuflow that builds a mesh, enters an SPMD region, names an
axis type, or pins a sharding goes through THIS module. The installed jax
moves these APIs around across releases — ``shard_map`` graduated from
``jax.experimental.shard_map`` (kwarg ``check_rep``) to ``jax.shard_map``
(kwarg ``check_vma``); ``jax.make_mesh`` grew an ``axis_types`` kwarg;
``jax.sharding.AxisType`` and ``jax.set_mesh``/``jax.sharding.reshard``
exist only on newer lines — and chasing those moves in every strategy
module is how the whole ``tpuflow/parallel/`` surface went dark for six
PRs (74 tier-1 failures of the ``make_mesh`` TypeError family).

Policy:

- **Probe once, at import.** Each capability is resolved from the
  installed jax's actual surface (``hasattr``/signature inspection), not
  from version-string comparisons — a backport or an internal build that
  has the API gets the modern path regardless of its version number.
- **One spelling for callers.** Strategy modules always write the modern
  spelling (``shard_map(..., check_vma=False)``,
  ``make_mesh(..., axis_types=...)``); this module translates or drops
  what the installed jax cannot express. ``axis_types`` in particular is
  advisory: a jax without explicit axis types runs every mesh in its
  default (GSPMD/auto) mode, which is exactly what the tp/pp/ep trainers
  want anyway.
- **No other module imports these names from jax directly.** Lint rule
  TPF008 (``tpuflow/analysis/linter.py``) makes the seam executable: a
  direct ``jax.make_mesh`` call or a raw ``shard_map`` import outside
  this file fails the self-lint gate instead of resurfacing as dozens of
  scattered runtime errors on the next jax move.
"""

from __future__ import annotations

import contextlib
import enum
import inspect

import jax
from jax.sharding import Mesh

__all__ = [
    "AXIS_TYPES_SUPPORTED",
    "AxisType",
    "SHARD_MAP_SOURCE",
    "make_mesh",
    "reshard",
    "set_mesh",
    "shard_map",
]


# --- shard_map -------------------------------------------------------------

if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    SHARD_MAP_SOURCE = "jax.shard_map"
else:  # pre-graduation line: the experimental module is the real one
    from jax.experimental.shard_map import shard_map as _shard_map

    SHARD_MAP_SOURCE = "jax.experimental.shard_map"

_SHARD_MAP_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
              **kwargs):
    """``shard_map`` under the modern spelling, on any supported jax.

    ``check_vma`` is the modern name of the replication-checking knob;
    on a jax whose shard_map still calls it ``check_rep`` the value is
    forwarded under that name (the semantics are the same: verify that
    outputs declared replicated really are).
    """
    if "check_vma" in _SHARD_MAP_PARAMS:
        kwargs.setdefault("check_vma", check_vma)
    elif "check_rep" in _SHARD_MAP_PARAMS:
        kwargs.setdefault("check_rep", check_vma)
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


# --- axis types ------------------------------------------------------------

try:
    from jax.sharding import AxisType  # type: ignore[attr-defined]

    AXIS_TYPES_SUPPORTED = True
except ImportError:
    class AxisType(enum.Enum):
        """Stand-in for ``jax.sharding.AxisType`` on jax lines that
        predate explicit axis types. Callers may name an axis type
        unconditionally (``make_mesh`` drops it when the installed jax
        cannot express it — every mesh then runs in the default
        GSPMD/auto mode, the pre-AxisType behavior)."""

        Auto = "auto"
        Explicit = "explicit"
        Manual = "manual"

    AXIS_TYPES_SUPPORTED = False


# --- mesh construction -----------------------------------------------------

_MAKE_MESH_PARAMS = (
    frozenset(inspect.signature(jax.make_mesh).parameters)
    if hasattr(jax, "make_mesh")
    else frozenset()
)


def make_mesh(axis_shapes, axis_names, *, devices=None, axis_types=None
              ) -> Mesh:
    """``jax.make_mesh`` under the modern signature, on any supported jax.

    ``axis_types`` passes through when the installed jax takes it and is
    dropped (not errored) when it does not — see the module policy. On a
    jax without ``jax.make_mesh`` at all, the mesh is assembled directly
    from the device list.
    """
    axis_shapes = tuple(int(n) for n in axis_shapes)
    axis_names = tuple(axis_names)
    if len(axis_shapes) != len(axis_names):
        raise ValueError(
            f"mesh axes mismatch: {len(axis_shapes)} shapes for "
            f"{len(axis_names)} names"
        )
    if _MAKE_MESH_PARAMS:
        kwargs = {"devices": devices}
        if axis_types is not None and "axis_types" in _MAKE_MESH_PARAMS:
            kwargs["axis_types"] = axis_types
        return jax.make_mesh(axis_shapes, axis_names, **kwargs)
    import numpy as np

    devices = jax.devices() if devices is None else list(devices)
    return Mesh(np.asarray(devices).reshape(axis_shapes), axis_names)


# --- ambient mesh context --------------------------------------------------

def set_mesh(mesh: Mesh):
    """Context manager making ``mesh`` ambient (``with set_mesh(m): ...``).

    Modern jax spells this ``jax.set_mesh``; older lines use the Mesh
    object's own context manager. Needed around transforms whose
    transpose/typing wants a mesh in scope (grads through shard_map ring
    programs).
    """
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    if hasattr(Mesh, "__enter__"):
        return mesh
    return contextlib.nullcontext(mesh)


# --- static axis size ------------------------------------------------------

def axis_size(axis: str) -> int:
    """STATIC size of a named mesh axis, inside an SPMD region.

    Modern jax spells this ``lax.axis_size``; older lines expose the
    same static value through the axis environment
    (``jax.core.axis_frame``). Always a Python int — ring schedules use
    it to build static permutation lists.
    """
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    import jax.core as core

    frame = core.axis_frame(axis)
    return frame if isinstance(frame, int) else frame.size


# --- sharding pin ----------------------------------------------------------

def reshard(x, sharding):
    """Pin ``x`` to ``sharding``, traceable under jit.

    ``jax.sharding.reshard`` where it exists; otherwise the classic
    ``with_sharding_constraint`` — both express "this value has exactly
    this sharding here" to the compiler.
    """
    if hasattr(jax.sharding, "reshard"):
        return jax.sharding.reshard(x, sharding)
    return jax.lax.with_sharding_constraint(x, sharding)
