"""Ring attention: sequence/context parallelism for attention models.

The counterpart of ``tpuflow.parallel.sp`` for the attention family: where
the LSTM ring hands a recurrence carry around the mesh, this hands **KV
blocks** around it. Each device owns a contiguous time chunk of Q/K/V and
computes exact softmax attention blockwise — online-softmax accumulators
(running max ``m``, normalizer ``l``, output ``o``) are updated as each
KV block arrives over the ``ppermute`` ring, so no device ever
materializes the full [T, T] score matrix or the full K/V sequence.
Activation memory per device is O(T/N) while the result is EXACT (parity
tested against full softmax attention, forward and gradients).

The reference family has no attention (its sequences are 24-step well-log
windows; SURVEY.md §5.7), but the framework treats long-context as
first-class: this module is the scale-out story for the attention-based
sequence regressor (``tpuflow.models.attention``) the same way
``ring_lstm_scan`` is for the LSTM family. Same ring topology, same
collective, applied to attention instead of a recurrence.

Differentiation goes straight through the python-unrolled ring (N static
rounds of jnp ops + ``ppermute``) — take gradients inside
``with jax.set_mesh(mesh):`` like the SP ring scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuflow.parallel.mesh import DATA_AXIS

# Additive mask value: large-but-finite so a fully-masked score row stays
# NaN-free through exp() (a true -inf max would make exp(-inf - -inf)).
_NEG = -1e30


def _block_update(q, k, v, m, l, o, allowed, scale):
    """One online-softmax update with KV block (k, v).

    q [B, Tq, D]; k, v [B, Tk, D]; m, l [B, Tq]; o [B, Tq, D];
    ``allowed`` [Tq, Tk] bool (True = may attend). Returns updated
    (m, l, o). Exactness: softmax(s) over the concatenation of all blocks
    equals the rescaled running sums (the flash-attention recurrence).
    """
    s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    s = jnp.where(allowed[None], s, _NEG)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # Masked entries contribute exactly 0 (explicit multiply — exp alone
    # would give 1 when an all-masked row keeps m_new at _NEG).
    p = jnp.exp(s - m_new[..., None]) * allowed[None]
    correction = jnp.exp(m - m_new)
    l = l * correction + jnp.sum(p, axis=-1)
    o = o * correction[..., None] + jnp.einsum("bqk,bkd->bqd", p, v)
    return m_new, l, o


def ring_attention(
    mesh: Mesh,
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis: str = DATA_AXIS,
    *,
    causal: bool = True,
    scale: float | None = None,
) -> jnp.ndarray:
    """Exact attention with the time axis sharded over the mesh ring.

    ``q, k, v: [B, T, D]`` (heads folded into B by the caller); ``T`` must
    divide by the axis size. Device ``i`` owns timesteps
    ``[i*T/N, (i+1)*T/N)`` of all three tensors; each of the N ring rounds
    attends the local Q chunk to the KV block currently held, then rotates
    the KV block to the right neighbor. Causal masking uses global
    positions, so the result equals single-device causal attention.
    """
    n = mesh.shape[axis]
    T = q.shape[1]
    if T % n:
        raise ValueError(f"sequence length {T} not divisible by {axis}={n}")
    if scale is None:
        scale = q.shape[-1] ** -0.5

    sharded = jax.shard_map(
        lambda ql, kl, vl: ring_attention_spmd(
            ql, kl, vl, axis=axis, causal=causal, scale=scale
        ),
        mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
    )
    return sharded(q, k, v)


def ring_attention_spmd(
    q_local: jnp.ndarray,
    k_local: jnp.ndarray,
    v_local: jnp.ndarray,
    axis: str = DATA_AXIS,
    *,
    causal: bool = True,
    scale: float | None = None,
) -> jnp.ndarray:
    """The ring-attention body, callable INSIDE an SPMD region.

    For composing whole time-sharded models under one ``shard_map``
    (``examples/long_context_cp.py``): the caller's shard_map owns the
    time axis; the locally-dense ops (projections, norms, MLPs) apply to
    the local chunk directly and this supplies the one cross-chunk op.
    ``q_local, k_local, v_local: [B, T/N, D]`` — this device's chunk.
    """
    if scale is None:
        scale = q_local.shape[-1] ** -0.5
    n = lax.axis_size(axis)
    B, Tl, D = q_local.shape
    idx = lax.axis_index(axis)
    q_pos = idx * Tl + jnp.arange(Tl)  # global positions of local Q
    m = jnp.full((B, Tl), _NEG, q_local.dtype)
    l = jnp.zeros((B, Tl), q_local.dtype)
    o = jnp.zeros((B, Tl, D), q_local.dtype)
    k_cur, v_cur = k_local, v_local
    for r in range(n):
        # After r rotations this device holds the block that started
        # on device (idx - r) mod n.
        src = (idx - r) % n
        k_pos = src * Tl + jnp.arange(Tl)
        if causal:
            allowed = k_pos[None, :] <= q_pos[:, None]
        else:
            allowed = jnp.ones((Tl, Tl), bool)
        m, l, o = _block_update(q_local, k_cur, v_cur, m, l, o, allowed, scale)
        if r + 1 < n:
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_cur = lax.ppermute(k_cur, axis, perm)
            v_cur = lax.ppermute(v_cur, axis, perm)
    # Causal attention guarantees l > 0 (each position sees itself);
    # the guard keeps a fully-masked row finite rather than NaN.
    return o / jnp.where(l == 0, 1.0, l)[..., None]


def full_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    scale: float | None = None,
) -> jnp.ndarray:
    """Single-device exact softmax attention — the on-chip path for short
    windows and the parity reference for ``ring_attention``.

    ``q, k, v: [B, T, D]`` (heads folded into B by the caller).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if causal:
        T = q.shape[1]
        allowed = jnp.arange(T)[None, :] <= jnp.arange(T)[:, None]
        s = jnp.where(allowed[None], s, _NEG)
    return jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(s, axis=-1), v)
