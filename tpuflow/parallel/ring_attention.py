"""Ring attention: sequence/context parallelism for attention models.

The counterpart of ``tpuflow.parallel.sp`` for the attention family: where
the LSTM ring hands a recurrence carry around the mesh, this hands **KV
blocks** around it. Each device owns a contiguous time chunk of Q/K/V and
computes exact softmax attention blockwise — online-softmax accumulators
(running max ``m``, normalizer ``l``, output ``o``) are updated as each
KV block arrives over the ``ppermute`` ring, so no device ever
materializes the full [T, T] score matrix or the full K/V sequence.
Activation memory per device is O(T/N) while the result is EXACT (parity
tested against full softmax attention, forward and gradients).

The reference family has no attention (its sequences are 24-step well-log
windows; SURVEY.md §5.7), but the framework treats long-context as
first-class: this module is the scale-out story for the attention-based
sequence regressor (``tpuflow.models.attention``) the same way
``ring_lstm_scan`` is for the LSTM family. Same ring topology, same
collective, applied to attention instead of a recurrence.

Training-capable with flash-grade memory: a custom VJP saves only
(q, k, v, out, lse) per device and the backward recomputes each round's
probabilities from the logsumexp while dK/dV accumulators ride the same
ppermute ring home — residuals are O(T/N), not the O(T^2/N) score blocks
plain autodiff through the unrolled ring would stash. Take gradients of
the ``ring_attention`` wrapper inside ``with set_mesh(mesh):``
(``tpuflow.parallel.set_mesh``) like
the SP ring scan (``ring_attention_spmd`` works directly inside your own
shard_map).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuflow.parallel.compat import axis_size, shard_map
from tpuflow.parallel.collectives import ppermute_ring
from tpuflow.parallel.mesh import DATA_AXIS

# Additive mask value: large-but-finite so a fully-masked score row stays
# NaN-free through exp() (a true -inf max would make exp(-inf - -inf)).
_NEG = -1e30


def _block_update(q, k, v, m, l, o, allowed, scale):
    """One online-softmax update with KV block (k, v).

    q [B, Tq, D]; k, v [B, Tk, D]; m, l [B, Tq]; o [B, Tq, D];
    ``allowed`` [Tq, Tk] bool (True = may attend). Returns updated
    (m, l, o). Exactness: softmax(s) over the concatenation of all blocks
    equals the rescaled running sums (the flash-attention recurrence).
    """
    s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    s = jnp.where(allowed[None], s, _NEG)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # Masked entries contribute exactly 0 (explicit multiply — exp alone
    # would give 1 when an all-masked row keeps m_new at _NEG).
    p = jnp.exp(s - m_new[..., None]) * allowed[None]
    correction = jnp.exp(m - m_new)
    l = l * correction + jnp.sum(p, axis=-1)
    o = o * correction[..., None] + jnp.einsum("bqk,bkd->bqd", p, v)
    return m_new, l, o


def ring_attention(
    mesh: Mesh,
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis: str = DATA_AXIS,
    *,
    causal: bool = True,
    scale: float | None = None,
    impl: str = "jnp",
) -> jnp.ndarray:
    """Exact attention with the time axis sharded over the mesh ring.

    ``q, k, v: [B, T, D]`` (heads folded into B by the caller); ``T`` must
    divide by the axis size. Device ``i`` owns timesteps
    ``[i*T/N, (i+1)*T/N)`` of all three tensors; each of the N ring rounds
    attends the local Q chunk to the KV block currently held, then rotates
    the KV block to the right neighbor. Causal masking uses global
    positions, so the result equals single-device causal attention.
    ``impl="flash"`` runs each round's block math in the Pallas
    ring-round kernels — ring outside, flash inside (causal only).
    """
    n = mesh.shape[axis]
    T = q.shape[1]
    if T % n:
        raise ValueError(f"sequence length {T} not divisible by {axis}={n}")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _ring_attn_fn(mesh, axis, causal, float(scale), impl)(q, k, v)


@functools.lru_cache(maxsize=32)
def _ring_attn_fn(mesh: Mesh, axis: str, causal: bool, scale: float, impl: str):
    """The jitted ring program, cached per configuration: repeated calls
    (every training step) dispatch the compiled program instead of
    re-tracing a fresh shard_map closure each time. Bounded (LRU 32, as
    are all mesh-keyed caches in this package): the key retains the Mesh
    and its compiled program, and a long-lived daemon building a fresh
    mesh per job must not grow memory without bound."""
    return jax.jit(
        shard_map(
            lambda ql, kl, vl: ring_attention_spmd(
                ql, kl, vl, axis=axis, causal=causal, scale=scale, impl=impl
            ),
            mesh=mesh,
            in_specs=(P(None, axis), P(None, axis), P(None, axis)),
            out_specs=P(None, axis),
            check_vma=False,
        )
    )


def _round_mask(idx, r, n, Tl, causal: bool):
    """[Tq, Tk] allowed-mask for ring round ``r`` on device ``idx`` —
    after ``r`` rotations the held block started on device (idx-r)%n."""
    if not causal:
        return jnp.ones((Tl, Tl), bool)
    q_pos = idx * Tl + jnp.arange(Tl)
    k_pos = ((idx - r) % n) * Tl + jnp.arange(Tl)
    return k_pos[None, :] <= q_pos[:, None]


def _rotate(args, axis):
    """Rotate every array one hop around the ring — the framework's
    named ``ppermute_ring`` collective, applied to a tuple."""
    return tuple(ppermute_ring(a, axis) for a in args)


def _ring_fwd_core(q_local, k_local, v_local, axis, causal, scale, impl="jnp"):
    """Forward ring pass; returns (out, lse) with lse = m + log(l).

    ``impl="flash"`` runs each round's block math in the Pallas
    ring-round kernel (``tpuflow.kernels.attention.ring_round_fwd``):
    scores stay in VMEM tiles instead of a materialized [Tl, Tl] array
    per round — ring outside, flash inside. Causal only.
    """
    n = axis_size(axis)
    B, Tl, D = q_local.shape
    idx = lax.axis_index(axis)
    k_cur, v_cur = k_local, v_local
    if impl == "flash":
        from tpuflow.kernels.attention import ring_round_fwd

        m = jnp.full((B, Tl), _NEG, jnp.float32)
        l = jnp.zeros((B, Tl), jnp.float32)
        acc = jnp.zeros((B, Tl, D), jnp.float32)
        q_off = idx * Tl
        for r in range(n):
            k_off = ((idx - r) % n) * Tl
            m, l, acc = ring_round_fwd(
                q_local, k_cur, v_cur, m, l, acc, q_off, k_off, scale
            )
            if r + 1 < n:
                k_cur, v_cur = _rotate((k_cur, v_cur), axis)
        l_safe = jnp.where(l == 0, 1.0, l)
        out = (acc / l_safe[..., None]).astype(q_local.dtype)
        return out, m + jnp.log(l_safe)
    m = jnp.full((B, Tl), _NEG, q_local.dtype)
    l = jnp.zeros((B, Tl), q_local.dtype)
    o = jnp.zeros((B, Tl, D), q_local.dtype)
    for r in range(n):
        allowed = _round_mask(idx, r, n, Tl, causal)
        m, l, o = _block_update(q_local, k_cur, v_cur, m, l, o, allowed, scale)
        if r + 1 < n:
            k_cur, v_cur = _rotate((k_cur, v_cur), axis)
    # Causal attention guarantees l > 0 (each position sees itself);
    # the guard keeps a fully-masked row finite rather than NaN.
    l_safe = jnp.where(l == 0, 1.0, l)
    return o / l_safe[..., None], m + jnp.log(l_safe)


def ring_attention_spmd(
    q_local: jnp.ndarray,
    k_local: jnp.ndarray,
    v_local: jnp.ndarray,
    axis: str = DATA_AXIS,
    *,
    causal: bool = True,
    scale: float | None = None,
    impl: str = "jnp",  # "jnp" | "flash" (Pallas round kernels; causal only)
) -> jnp.ndarray:
    """The ring-attention body, callable INSIDE an SPMD region.

    For composing whole time-sharded models under one ``shard_map``
    (``examples/long_context_cp.py``): the caller's shard_map owns the
    time axis; the locally-dense ops (projections, norms, MLPs) apply to
    the local chunk directly and this supplies the one cross-chunk op.
    ``q_local, k_local, v_local: [B, T/N, D]`` — this device's chunk.

    Training memory is flash-grade across the ring: a custom VJP saves
    only (q, k, v, out, lse) — O(T/N) per device — and the backward
    RECOMPUTES each round's probabilities from the logsumexp while the
    dK/dV accumulators ride the same ppermute ring home. (Autodiff
    through the unrolled loop would instead stash every round's [Tq, Tk]
    score block: O(T^2/N) per device.)
    """
    if scale is None:
        scale = q_local.shape[-1] ** -0.5
    if impl not in ("jnp", "flash"):
        # Silent fallback would report the materialized-jnp path as the
        # blockwise kernel path.
        raise ValueError(f'unknown impl {impl!r}; choose "jnp" or "flash"')
    if impl == "flash" and not causal:
        raise ValueError('impl="flash" supports causal attention only')
    return _ring_spmd(q_local, k_local, v_local, axis, causal, scale, impl)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_spmd(q_local, k_local, v_local, axis, causal, scale, impl):
    out, _ = _ring_fwd_core(
        q_local, k_local, v_local, axis, causal, scale, impl
    )
    return out


def _ring_spmd_fwd(q_local, k_local, v_local, axis, causal, scale, impl):
    out, lse = _ring_fwd_core(
        q_local, k_local, v_local, axis, causal, scale, impl
    )
    return out, (q_local, k_local, v_local, out, lse)


def _ring_spmd_bwd(axis, causal, scale, impl, res, do):
    q, k, v, out, lse = res
    if impl == "flash":
        return _ring_flash_bwd(q, k, v, out, lse, do, axis, scale)
    n = axis_size(axis)
    B, Tl, D = q.shape
    idx = lax.axis_index(axis)
    do = do.astype(q.dtype)
    # delta_i = sum_d do_i * out_i (the lse-form backward's row term).
    delta = jnp.sum(do * out, axis=-1)
    dq = jnp.zeros_like(q)
    # The KV block and ITS gradient accumulators tour the ring together:
    # each device adds its local q-chunk's contribution to the passing
    # block, and after n rotations (one per round, incl. the last) the
    # accumulated dK/dV arrive back at the block's owner.
    k_cur, v_cur = k, v
    dk_cur = jnp.zeros_like(k)
    dv_cur = jnp.zeros_like(v)
    for r in range(n):
        allowed = _round_mask(idx, r, n, Tl, causal)
        s = jnp.einsum("bqd,bkd->bqk", q, k_cur) * scale
        s = jnp.where(allowed[None], s, _NEG)
        # Recomputed probabilities: exp(s - lse) is the final softmax
        # (not the running partial), so every round's contribution is
        # already correctly normalized.
        p = jnp.exp(s - lse[..., None]) * allowed[None]
        dp = jnp.einsum("bqd,bkd->bqk", do, v_cur)
        ds = p * (dp - delta[..., None])
        dq = dq + scale * jnp.einsum("bqk,bkd->bqd", ds, k_cur)
        dk_cur = dk_cur + scale * jnp.einsum("bqk,bqd->bkd", ds, q)
        dv_cur = dv_cur + jnp.einsum("bqk,bqd->bkd", p, do)
        if r + 1 < n:
            k_cur, v_cur, dk_cur, dv_cur = _rotate(
                (k_cur, v_cur, dk_cur, dv_cur), axis
            )
        else:
            # Last round: only the accumulators still need to travel —
            # one final hop rides them home to their block's owner.
            dk_cur, dv_cur = _rotate((dk_cur, dv_cur), axis)
    return dq, dk_cur, dv_cur


def _ring_flash_bwd(q, k, v, out, lse, do, axis, scale):
    """Backward ring with the Pallas round kernel doing the block math —
    same accumulator-rides-the-ring schedule as the jnp path."""
    from tpuflow.kernels.attention import ring_round_bwd

    n = axis_size(axis)
    B, Tl, D = q.shape
    idx = lax.axis_index(axis)
    do = do.astype(q.dtype)
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    q_off = idx * Tl
    dq = jnp.zeros_like(q)
    k_cur, v_cur = k, v
    dk_cur = jnp.zeros_like(k)
    dv_cur = jnp.zeros_like(v)
    for r in range(n):
        k_off = ((idx - r) % n) * Tl
        dq_p, dk_p, dv_p = ring_round_bwd(
            q, k_cur, v_cur, do, lse, delta, q_off, k_off, scale
        )
        dq = dq + dq_p
        dk_cur = dk_cur + dk_p
        dv_cur = dv_cur + dv_p
        if r + 1 < n:
            k_cur, v_cur, dk_cur, dv_cur = _rotate(
                (k_cur, v_cur, dk_cur, dv_cur), axis
            )
        else:
            dk_cur, dv_cur = _rotate((dk_cur, dv_cur), axis)
    return dq, dk_cur, dv_cur


_ring_spmd.defvjp(_ring_spmd_fwd, _ring_spmd_bwd)


def full_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    scale: float | None = None,
) -> jnp.ndarray:
    """Single-device exact softmax attention — the on-chip path for short
    windows and the parity reference for ``ring_attention``.

    ``q, k, v: [B, T, D]`` (heads folded into B by the caller).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if causal:
        T = q.shape[1]
        allowed = jnp.arange(T)[None, :] <= jnp.arange(T)[:, None]
        s = jnp.where(allowed[None], s, _NEG)
    return jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(s, axis=-1), v)
