"""Distributed layer: device mesh, collectives, data-parallel training.

TPU-native replacement for the reference's entire distributed story —
Spark executors over a Hadoop cluster reached via ``spark-submit``
(reference Readme.md:3-4; SURVEY.md §5.8). Here the cluster runtime is the
XLA runtime itself: a ``jax.sharding.Mesh`` over TPU chips, SPMD train
steps compiled with ``shard_map``/``jit``, gradient all-reduce as
``lax.pmean`` riding ICI, and ``jax.distributed`` for multi-host pods over
DCN. No JVM, no shuffle service, no executor processes.
"""

from tpuflow.parallel.compat import (  # noqa: F401
    AxisType,
    reshard,
    set_mesh,
    shard_map,
)
from tpuflow.parallel.mesh import (  # noqa: F401
    data_axis_size,
    data_sharding,
    make_mesh,
    replicated,
)
from tpuflow.parallel.collectives import (  # noqa: F401
    all_gather,
    pmean,
    ppermute_ring,
    psum,
    reduce_scatter,
)
from tpuflow.parallel.dp import (  # noqa: F401
    epoch_sharding,
    make_dp_epoch_step,
    make_dp_eval_step,
    make_dp_train_step,
    make_process_fed_steps,
    process_batch_bounds,
    shard_batch,
    shard_epoch,
)
from tpuflow.parallel.distributed import init_distributed  # noqa: F401
from tpuflow.parallel.placement import (  # noqa: F401
    device_count,
    device_kind,
    local_devices,
    place,
    replica_devices,
)
from tpuflow.parallel.ep import moe_forward  # noqa: F401
from tpuflow.parallel.pp import pipeline_forward  # noqa: F401
from tpuflow.parallel.ring_attention import (  # noqa: F401
    full_attention,
    ring_attention,
)
from tpuflow.parallel.sp import make_sp_forward, ring_lstm_scan  # noqa: F401
from tpuflow.parallel.tp import (  # noqa: F401
    column_parallel_matmul,
    row_parallel_matmul,
    tp_mlp_forward,
)
from tpuflow.parallel.tp_train import (  # noqa: F401
    make_tp_eval_step,
    make_tp_mesh,
    make_tp_train_step,
    mlp_tp_shardings,
    shard_state,
)
