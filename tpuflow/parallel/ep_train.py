"""Expert-parallel TRAINING (top-1 MoE, experts sharded over the mesh).

``ep.py`` provides the one-expert-per-device MoE building block and its
router-gradient proofs; this module makes the ``moe_mlp`` family
actually *train* with an expert axis, reachable from ``train(config)``
via ``TrainJobConfig(ep=N)`` — the same block→trainer promotion as
``tp_train.py`` (model axis) and ``pp_train.py`` (pipeline axis).

Layout, TPU-first:

- the mesh is ``(data, model)``; each device column owns a CONTIGUOUS
  chunk of the stacked expert FFN bank (``P(model)`` on the expert dim
  — the memory win of EP: a device holds experts/N of the bank);
- routing is dense capacity-free top-1 (the block's strategy): every
  device computes its experts' outputs for all local tokens, masks to
  the tokens routed to it, and the weighted combine is one ``psum``
  over the expert axis — exact, no token dropping;
- the batch (token) dim is sharded over the data axis inside the same
  ``shard_map`` — DPxEP in one program;
- router gradients flow through the softmax gate weight (argmax picks
  the expert, the prob weights it), and shard_map's transpose inserts
  the data-axis psum for replicated params — no hand-written backward.

The reference has no MoE (SURVEY.md §2: its models are KBs); this
exists so the framework's expert axis is training-capable end to end.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuflow.core.losses import mae_clip
from tpuflow.parallel.compat import shard_map
from tpuflow.parallel.mesh import DATA_AXIS, MODEL_AXIS
from tpuflow.parallel.tp_train import make_tp_mesh, shard_state, state_shardings

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]

# EP rides the same AUTO-axis (data, model) mesh as TP/PP training.
make_ep_mesh = make_tp_mesh

_EP_TREE = {"embed", "gate", "expert_w1", "expert_w2", "head"}


def ep_shardings(mesh: Mesh, params, axis: str = MODEL_AXIS):
    """Expert layout for an ``MoEMLP`` params tree: the stacked expert
    bank sharded on the leading (expert) dim over ``axis`` — device d
    owns the contiguous experts [d*k, (d+1)*k) — embed/gate/head
    replicated. Raises for other families: silently replicating
    everything would "work" while quietly not being expert parallel.
    """
    keys = set(params.keys()) if hasattr(params, "keys") else set()
    if keys != _EP_TREE:
        raise ValueError(
            "ep training supports the moe_mlp family (stacked expert "
            f"bank); got params {sorted(keys) or type(params)}"
        )
    n_dev = mesh.shape[axis]
    E = params["expert_w1"].shape[0]
    if E % n_dev:
        raise ValueError(
            f"moe_mlp experts={E} not divisible by ep={n_dev} devices "
            "(each device owns an equal contiguous expert chunk)"
        )
    rep = NamedSharding(mesh, P())
    return {
        "embed": {"kernel": rep, "bias": rep},
        "head": {"kernel": rep, "bias": rep},
        "gate": rep,
        "expert_w1": NamedSharding(mesh, P(axis, None, None)),
        "expert_w2": NamedSharding(mesh, P(axis, None, None)),
    }


@functools.lru_cache(maxsize=32)
def _moe_body_fn(mesh: Mesh, axis: str, data_axis: str):
    """The routed expert program, cached per mesh: k experts per device,
    dense capacity-free top-1 dispatch, one psum combine over the expert
    axis; tokens sharded over the data axis (DPxEP in one shard_map)."""

    from tpuflow.parallel.ep import top1_gate

    def body(w1_local, w2_local, gate_w, h_local):
        # w1_local: [k, H, Ff], w2_local: [k, Ff, H] — this device's
        # contiguous expert chunk. h_local: [n_local, H].
        k = w1_local.shape[0]
        e0 = lax.axis_index(axis) * k
        choice, weight = top1_gate(h_local, gate_w)
        out = jnp.zeros_like(h_local)
        for i in range(k):  # static: experts-per-device chunk
            mine = (choice == e0 + i).astype(h_local.dtype)
            expert = jax.nn.relu(h_local @ w1_local[i]) @ w2_local[i]
            out = out + expert * (mine * weight)[:, None]
        return lax.psum(out, axis)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P(data_axis)),
        out_specs=P(data_axis),
        check_vma=False,
    )


def ep_forward(
    mesh: Mesh,
    params,
    x: jnp.ndarray,
    axis: str = MODEL_AXIS,
    data_axis: str = DATA_AXIS,
) -> jnp.ndarray:
    """The MoEMLP forward with its expert bank run expert-parallel:
    embed/gate/head are plain GSPMD ops; the routed FFNs run in the
    sharded program. Numerically identical to the module's dense
    ``__call__`` (same routing, same residual)."""
    h = jax.nn.relu(x @ params["embed"]["kernel"] + params["embed"]["bias"])
    moe = _moe_body_fn(mesh, axis, data_axis)(
        params["expert_w1"], params["expert_w2"], params["gate"], h
    )
    h = h + moe
    return (h @ params["head"]["kernel"] + params["head"]["bias"])[..., 0]


def make_ep_train_step(state, loss_fn: LossFn = mae_clip):
    """Jitted (state, x, y, rng) -> (state, metrics) over the state's
    mesh; ``state`` is the already-sharded TrainState (its shardings pin
    the output layout, as in tp_train/pp_train)."""
    sh = state_shardings(state)
    mesh = jax.tree.leaves(sh)[0].mesh
    rep = NamedSharding(mesh, P())

    def step(state, x, y, rng):
        def loss_of(params):
            pred = ep_forward(mesh, params, x)
            return loss_fn(y, pred)

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss}

    return jax.jit(
        step,
        donate_argnums=(0,),
        out_shardings=(sh, {"loss": rep}),
    )


def make_ep_eval_step(mesh: Mesh, loss_fn: LossFn = mae_clip):
    """Jitted masked-sum eval step (the shared ``make_masked_eval_step``
    aggregation) running the same expert-parallel forward as training."""
    from tpuflow.parallel.tp_train import make_masked_eval_step

    return make_masked_eval_step(
        lambda state, x: ep_forward(mesh, state.params, x), loss_fn
    )


__all__ = [
    "make_ep_mesh",
    "ep_shardings",
    "ep_forward",
    "make_ep_train_step",
    "make_ep_eval_step",
    "shard_state",
]
