"""Expert parallelism: a sharded mixture-of-experts building block.

The reference has no MoE (SURVEY.md §2 lists EP as out of scope for
parity), but the framework keeps the axis expressible with the same
explicit-collective ``shard_map`` vocabulary as DP/TP/SP/PP. One expert
lives on each device of the ``model`` axis; tokens are routed by a
learned gate.

Dispatch strategy: **dense (capacity-free)** — tokens are all-gathered
across the expert axis, each expert computes only its assigned tokens'
outputs (masked), and the weighted combine is a ``psum``. Exact (no
token dropping, no capacity tuning), at the cost of O(global tokens)
activation work per expert — the right trade for a building block whose
job is correctness and expressibility; a capacity-bucketed ``all_to_all``
dispatch is a drop-in upgrade behind the same signature.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuflow.parallel.compat import shard_map
from tpuflow.parallel.mesh import MODEL_AXIS


def top1_gate(x: jnp.ndarray, gate_w: jnp.ndarray):
    """THE top-1 router, shared by the forward block below and the
    trainer (``ep_train``): softmax over the gate logits, argmax picks
    the expert, the picked prob is the combine weight (the path router
    gradients flow through). One routing rule, one place — a routing
    change (e.g. the capacity-bucketed ``all_to_all`` upgrade) lands in
    every expert-parallel user at once. Returns ``(choice [N], weight
    [N])``."""
    logits = x @ gate_w  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    choice = jnp.argmax(logits, axis=-1)  # [N] top-1 expert ids
    weight = jnp.take_along_axis(probs, choice[:, None], axis=1)[:, 0]
    return choice, weight


@functools.lru_cache(maxsize=32)
def _moe_fn(mesh: Mesh, axis: str, expert_fn: Callable):
    """Jitted MoE program, cached per (mesh, axis, expert_fn) — tp.py's
    repeated-calls-dispatch-don't-retrace pattern."""

    def body(params_local, gate_w, x):
        eid = lax.axis_index(axis)
        params_one = jax.tree_util.tree_map(lambda p: p[0], params_local)
        choice, weight = top1_gate(x, gate_w)
        mine = (choice == eid).astype(x.dtype)  # [N] my tokens
        # Dense dispatch: compute all tokens, keep mine, weighted combine.
        out = expert_fn(params_one, x)  # [N, F]
        return lax.psum(out * (mine * weight)[:, None], axis)

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
    )


def moe_forward(
    mesh: Mesh,
    expert_fn: Callable,
    expert_params,
    gate_w: jnp.ndarray,
    x: jnp.ndarray,
    axis: str = MODEL_AXIS,
) -> jnp.ndarray:
    """Top-1 mixture-of-experts forward with experts sharded over ``axis``.

    Args:
      mesh: mesh whose ``axis`` dimension holds one expert per device.
      expert_fn: ``(params_one_expert, x [N, F]) -> [N, F]``. Pass a
        module-level function (not a fresh lambda per call) so the cached
        compiled program is reused.
      expert_params: pytree of ``[E, ...]`` stacked per-expert params,
        sharded on the leading (expert) dim.
      gate_w: ``[F, E]`` router weights, replicated.
      x: ``[N, F]`` tokens, replicated (shard the batch with the ``data``
        axis outside this block; the two axes compose).

    Returns:
      ``[N, F]`` combined outputs, replicated: softmax-top-1 gate weight
      times the chosen expert's output for every token.
    """
    n_experts = mesh.shape[axis]
    if gate_w.shape[1] != n_experts:
        raise ValueError(
            f"gate has {gate_w.shape[1]} outputs but {axis}={n_experts} experts"
        )
    return _moe_fn(mesh, axis, expert_fn)(expert_params, gate_w, x)
