"""Tensor parallelism building blocks over the ``model`` mesh axis.

The reference has no tensor parallelism (its models are KBs of params —
SURVEY.md §2 "Parallelism strategies"), but SURVEY's design note requires
the sharding API to keep TP *expressible*. This module provides the two
canonical scaling-book shardings as explicit-collective ``shard_map``
blocks, so a model family that outgrows one chip's HBM can shard its
feature dimensions with the same vocabulary the DP path uses:

- **column parallel**: ``W [F, H]`` sharded on H; every device computes
  its slice of the output, no communication (activations come out
  H-sharded).
- **row parallel**: ``W [H, F]`` sharded on H; device-local partial
  products are summed with ``psum`` — the matching second half, landing
  the activations replicated again.

``tp_mlp_forward`` composes the pair into the classic
column-then-row-parallel 2-layer block (one all-reduce per block).

The compiled shard_map programs are cached per (mesh, axis) — repeated
calls dispatch, they don't retrace.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuflow.parallel.compat import shard_map
from tpuflow.parallel.mesh import MODEL_AXIS


def _check_divisible(dim: int, mesh: Mesh, axis: str, what: str) -> None:
    n = mesh.shape[axis]
    if dim % n:
        raise ValueError(
            f"{what} dimension {dim} not divisible by {axis}={n}"
        )


@functools.lru_cache(maxsize=32)
def _column_fn(mesh: Mesh, axis: str):
    def body(x, w):
        return x @ w

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(None, axis)),
            out_specs=P(None, axis),
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=32)
def _row_fn(mesh: Mesh, axis: str):
    def body(x, w):
        return lax.psum(x @ w, axis)

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(None, axis), P(axis, None)),
            out_specs=P(),
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=32)
def _mlp_fn(mesh: Mesh, axis: str):
    def body(x, w1, w2):
        h = jax.nn.relu(x @ w1)  # local H-slice, no comm
        return lax.psum(h @ w2, axis)  # one all-reduce

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(None, axis), P(axis, None)),
            out_specs=P(),
            check_vma=False,
        )
    )


def column_parallel_matmul(
    mesh: Mesh, x: jnp.ndarray, w: jnp.ndarray, axis: str = MODEL_AXIS
) -> jnp.ndarray:
    """``x [B, F] @ w [F, H]`` with ``w`` (and the output) sharded on H.

    No communication: each device owns an output-column slice.
    """
    _check_divisible(w.shape[1], mesh, axis, "output (H)")
    return _column_fn(mesh, axis)(x, w)


def row_parallel_matmul(
    mesh: Mesh, x: jnp.ndarray, w: jnp.ndarray, axis: str = MODEL_AXIS
) -> jnp.ndarray:
    """``x [B, H] @ w [H, F]`` with ``x``/``w`` sharded on H; output
    replicated via ``psum`` over ICI (the block's single all-reduce)."""
    _check_divisible(w.shape[0], mesh, axis, "contraction (H)")
    return _row_fn(mesh, axis)(x, w)


def tp_mlp_forward(
    mesh: Mesh,
    x: jnp.ndarray,
    w1: jnp.ndarray,
    w2: jnp.ndarray,
    axis: str = MODEL_AXIS,
) -> jnp.ndarray:
    """Column→row-parallel 2-layer MLP block: ``relu(x @ w1) @ w2`` with
    the hidden dimension sharded across the model axis and exactly one
    ``psum`` at the block boundary (scaling-book megatron pattern)."""
    _check_divisible(w1.shape[1], mesh, axis, "hidden (H)")
    _check_divisible(w2.shape[0], mesh, axis, "hidden (H)")
    return _mlp_fn(mesh, axis)(x, w1, w2)
