"""Pipeline-parallel TRAINING (GPipe microbatch schedule, grads by AD).

``pp.py`` provides the pipeline building block and its grad-parity
proofs; this module makes the ``pipeline_mlp`` family actually *train*
with a pipeline axis, reachable from ``train(config)`` via
``TrainJobConfig(pp=N)`` — the same block→trainer promotion
``tp_train.py`` did for the model axis (round-4 verdict item 4).

Layout and schedule, TPU-first:

- the mesh is ``(data, model)``; each device column owns a CONTIGUOUS
  chunk of the model's stacked stage params (``P(model)`` on the stage
  dim — the memory win of PP: a device holds stages/N of the body);
- the batch is split into M microbatches that flow stage→stage around
  the model-axis ring with ``lax.ppermute`` (one [mb, H] activation hop
  per tick riding ICI), the classic GPipe fill/steady/drain of
  ``M + N - 1`` ticks;
- the batch dim is ALSO sharded over the data axis inside the same
  ``shard_map`` — DPxPP in one program;
- **microbatch gradient accumulation is automatic differentiation**:
  the loss sums over all microbatches of the step, so ``jax.grad``
  through the scheduled forward accumulates per-microbatch gradients
  exactly (no hand-rolled accumulator to get wrong), and shard_map's
  transpose inserts the data-axis psum for the DP reduction.

The reference has no PP (SURVEY.md §2: its models are KBs); this exists
so the framework's pipeline axis is training-capable end to end.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuflow.core.losses import mae_clip
from tpuflow.parallel.compat import shard_map
from tpuflow.parallel.mesh import DATA_AXIS, MODEL_AXIS
from tpuflow.parallel.tp_train import make_tp_mesh, shard_state, state_shardings

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]

# PP rides the same AUTO-axis (data, model) mesh as TP training; the
# pipeline program is explicit shard_map, the embed/head stay GSPMD.
make_pp_mesh = make_tp_mesh

_PP_TREE = {"embed", "head", "stage_kernels", "stage_biases"}


def pp_shardings(mesh: Mesh, params, axis: str = MODEL_AXIS):
    """Pipeline layout for a ``PipelineMLP`` params tree: stacked stage
    params sharded on the leading (stage) dim over ``axis`` — device d
    owns the contiguous stages [d*k, (d+1)*k) — embed/head replicated.
    Raises for other families: silently replicating everything would
    "work" while quietly not being pipeline parallel at all.
    """
    keys = set(params.keys()) if hasattr(params, "keys") else set()
    if keys != _PP_TREE:
        raise ValueError(
            "pp training supports the pipeline_mlp family (stacked "
            f"homogeneous stages); got params {sorted(keys) or type(params)}"
        )
    n_stages = mesh.shape[axis]
    S = params["stage_kernels"].shape[0]
    if S % n_stages:
        raise ValueError(
            f"pipeline_mlp stages={S} not divisible by pp={n_stages} "
            "devices (each device owns an equal contiguous stage chunk)"
        )
    rep = NamedSharding(mesh, P())
    return {
        "embed": {"kernel": rep, "bias": rep},
        "head": {"kernel": rep, "bias": rep},
        "stage_kernels": NamedSharding(mesh, P(axis, None, None)),
        "stage_biases": NamedSharding(mesh, P(axis, None)),
    }


@functools.lru_cache(maxsize=32)
def _pipeline_body_fn(mesh: Mesh, axis: str, data_axis: str):
    """The scheduled stage program, cached per mesh: microbatches ride
    the model-axis ring via the SHARED GPipe schedule (``pp.py``'s
    ``gpipe_schedule`` — one fill/steady/drain implementation for the
    block and the trainer), the batch dim is sharded over the data axis
    (DPxPP in one shard_map; shapes stay dynamic to jit's shape cache).
    """
    from tpuflow.parallel.pp import gpipe_schedule

    n_stages = mesh.shape[axis]

    def body(wk_local, bk_local, xs_local):
        # wk_local: [k, H, H], bk_local: [k, H] — this device's
        # contiguous stage chunk. xs_local: [M, mb_local, H].
        def chunk(h):
            # The device's k stages applied in order — "layers per
            # stage", the standard way S model stages ride N devices.
            for i in range(wk_local.shape[0]):
                h = jnp.tanh(h @ wk_local[i] + bk_local[i])
            return h

        return gpipe_schedule(axis, n_stages, chunk, xs_local)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(None, data_axis)),
        out_specs=P(None, data_axis),
        check_vma=False,
    )


def pp_forward(
    mesh: Mesh,
    params,
    x: jnp.ndarray,
    n_micro: int,
    axis: str = MODEL_AXIS,
    data_axis: str = DATA_AXIS,
) -> jnp.ndarray:
    """The PipelineMLP forward with its body run as a GPipe pipeline:
    embed and head are plain GSPMD ops (replicated params, sharded
    batch); the stage stack runs in the scheduled shard_map program.
    Numerically identical to the module's sequential ``__call__``.
    """
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(
            f"batch {B} not divisible by {n_micro} microbatches"
        )
    h = jax.nn.relu(x @ params["embed"]["kernel"] + params["embed"]["bias"])
    hm = h.reshape(n_micro, B // n_micro, h.shape[-1])
    out = _pipeline_body_fn(mesh, axis, data_axis)(
        params["stage_kernels"], params["stage_biases"], hm
    )
    h2 = out.reshape(B, -1)
    return (h2 @ params["head"]["kernel"] + params["head"]["bias"])[..., 0]


def make_pp_train_step(state, loss_fn: LossFn = mae_clip, n_micro: int = 0):
    """Jitted (state, x, y, rng) -> (state, metrics) over the state's
    mesh. The loss sums the whole microbatched step, so jax.grad IS the
    GPipe gradient accumulation; ``state`` is the already-sharded
    TrainState (its shardings pin the output layout, as in tp_train).
    """
    sh = state_shardings(state)
    mesh = jax.tree.leaves(sh)[0].mesh
    rep = NamedSharding(mesh, P())
    n_micro = n_micro or mesh.shape[MODEL_AXIS]

    def step(state, x, y, rng):
        def loss_of(params):
            pred = pp_forward(mesh, params, x, n_micro)
            return loss_fn(y, pred)

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss}

    return jax.jit(
        step,
        donate_argnums=(0,),
        out_shardings=(sh, {"loss": rep}),
    )


def make_pp_eval_step(mesh: Mesh, loss_fn: LossFn = mae_clip, n_micro: int = 0):
    """Jitted masked-sum eval step (the shared ``make_masked_eval_step``
    aggregation) running the same pipelined forward as training."""
    from tpuflow.parallel.tp_train import make_masked_eval_step

    n_micro = n_micro or mesh.shape[MODEL_AXIS]
    return make_masked_eval_step(
        lambda state, x: pp_forward(mesh, state.params, x, n_micro), loss_fn
    )


__all__ = [
    "make_pp_mesh",
    "pp_shardings",
    "pp_forward",
    "make_pp_train_step",
    "make_pp_eval_step",
    "shard_state",
]
