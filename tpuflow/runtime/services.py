"""ServiceSpec adapters for tpuflow's own long-lived components.

Each factory returns a :class:`~tpuflow.runtime.service.ServiceSpec`
wiring an existing component into the supervisor's three callables —
riding the liveness machinery the component already has instead of
inventing a parallel one:

- :func:`daemon_service` — the async serving daemon; liveness is its
  own ``/healthz`` (degraded artifacts report as ``degraded``), stop
  is drain-then-shutdown (the zero-500s contract).
- :func:`gang_service` — ``run_elastic`` in a thread; liveness is
  thread-aliveness plus the outcome box (a finished-ok gang is
  FINISHED, a raise is a death), stop sets the gang's cooperative
  ``stop_event`` and joins.
- :func:`online_service` — an ``OnlineTrainer.run`` thread; stop is
  ``request_stop()`` + join (the loop ends at a window boundary, so a
  mid-retrain drain completes the swap instead of stranding it).
- :func:`process_service` — an arbitrary child process; liveness is
  ``poll()``, stop reuses ``train/supervisor.py``'s
  ``terminate_gracefully`` SIGTERM→grace→SIGKILL escalation.
- :func:`thread_service` — the generic building block the gang and
  online adapters are built on.
"""

from __future__ import annotations

import threading

from tpuflow.runtime.service import ServiceSpec


class ThreadHandle:
    """A supervised worker thread plus its outcome box. ``result`` /
    ``error`` are written exactly once, by the worker thread, before it
    exits; readers only look after ``thread.is_alive()`` goes False —
    the happens-before edge is the thread's own termination."""

    def __init__(self, thread: threading.Thread, stop_event: threading.Event):
        self.thread = thread
        self.stop_event = stop_event
        self.result = None
        self.error: str | None = None


def thread_service(
    name: str,
    run,
    *,
    check=None,
    depends_on: tuple = (),
    grace: float = 5.0,
    **spec_kwargs,
) -> ServiceSpec:
    """A service backed by one worker thread running ``run(stop_event)``.

    ``run`` returns the service's result (stored on the handle) or
    raises (a death). ``check(result) -> (state, detail)`` optionally
    judges a COMPLETED run — e.g. a gang whose outcome says a worker
    crash-looped should read as dead, not finished; default is
    ``finished``. Stop sets ``stop_event`` and joins for ``grace``
    seconds; a thread that ignores its stop event cannot be killed
    (Python threads aren't), so it is recorded as ``abandoned`` —
    daemon=True means it cannot block process exit either.
    """

    def _start() -> ThreadHandle:
        stop_event = threading.Event()
        handle: ThreadHandle | None = None

        def _worker():
            try:
                result = run(stop_event)
                handle.result = result
            except BaseException as e:
                handle.error = f"{type(e).__name__}: {e}"

        thread = threading.Thread(
            target=_worker, name=f"tpuflow-runtime-{name}", daemon=True
        )
        handle = ThreadHandle(thread, stop_event)
        thread.start()
        return handle

    def _liveness(handle: ThreadHandle):
        if handle.thread.is_alive():
            return "ok", ""
        if handle.error is not None:
            return "dead", handle.error
        if check is not None:
            return check(handle.result)
        return "finished", ""

    def _stop(handle: ThreadHandle, grace_s: float):
        handle.stop_event.set()
        handle.thread.join(timeout=max(grace_s, 0.0))
        if handle.thread.is_alive():
            return "abandoned"  # unkillable; daemon=True caps the damage
        return "stopped" if handle.error is None else "died"

    return ServiceSpec(
        name=name, start=_start, stop=_stop, liveness=_liveness,
        depends_on=tuple(depends_on), grace=grace, **spec_kwargs,
    )


def daemon_service(
    name: str,
    server_factory,
    *,
    depends_on: tuple = (),
    grace: float = 10.0,
    probe_timeout: float = 2.0,
    **spec_kwargs,
) -> ServiceSpec:
    """The async serving daemon as a service. ``server_factory()``
    builds (but does not start) an ``AsyncServer``; liveness rides the
    daemon's own ``/healthz``; stop drains in-flight requests (the
    zero-500s contract) before ``shutdown()`` — ``killed_by`` records
    ``drained`` or ``abandoned-inflight``."""

    def _start():
        return server_factory().start()

    def _liveness(server):
        import urllib.request

        url = f"http://127.0.0.1:{server.port}/healthz"
        try:
            with urllib.request.urlopen(url, timeout=probe_timeout) as resp:
                import json

                doc = json.loads(resp.read().decode())
        except Exception as e:
            return "dead", f"/healthz unreachable: {type(e).__name__}: {e}"
        if doc.get("status") == "ok":
            return "ok", ""
        return "degraded", f"degraded artifacts: {doc.get('degraded_artifacts')}"

    def _stop(server, grace_s: float):
        drained = server.drain(timeout=grace_s)
        server.shutdown()
        return "drained" if drained else "abandoned-inflight"

    return ServiceSpec(
        name=name, start=_start, stop=_stop, liveness=_liveness,
        depends_on=tuple(depends_on), grace=grace, **spec_kwargs,
    )


def gang_service(
    name: str,
    spec: dict,
    n_workers: int,
    *,
    depends_on: tuple = (),
    grace: float = 15.0,
    allow_partial: bool = False,
    **run_kwargs,
) -> ServiceSpec:
    """An in-process elastic gang as a service: ``run_elastic`` on a
    worker thread with the cooperative ``stop_event`` plumbed through
    to every worker's epoch loop. ``allow_partial=True`` treats a gang
    that lost workers but still produced a final average as FINISHED
    (churn absorbed — the elastic contract); default demands every
    worker healthy."""

    def _run(stop_event):
        from tpuflow.elastic.runner import run_elastic

        return run_elastic(
            spec, n_workers, mode="inprocess", stop_event=stop_event,
            **run_kwargs,
        )

    def _check(result):
        if result is None:
            return "dead", "run_elastic returned nothing"
        if result.ok:
            return "finished", ""
        dead = [w.worker_id for w in result.workers if w.error]
        if (
            allow_partial
            and "error" not in result.coordinator
            and result.final_path is not None
            and any(w.report is not None for w in result.workers)
        ):
            return "finished", f"absorbed worker deaths: {dead}"
        return "dead", (
            result.coordinator.get("error")
            or f"workers died: {dead}"
        )

    return thread_service(
        name, _run, check=_check, depends_on=depends_on, grace=grace,
    )


def online_service(
    name: str,
    trainer_factory,
    *,
    depends_on: tuple = (),
    grace: float = 30.0,
    max_windows: int | None = None,
    **spec_kwargs,
) -> ServiceSpec:
    """The online controller as a service. ``trainer_factory()`` builds
    an ``OnlineTrainer``; stop is ``request_stop()`` (the loop ends at
    its next window boundary — a mid-retrain drain finishes the swap)
    plus the thread join. The run summary lands on the handle."""

    def _run(stop_event):
        trainer = trainer_factory()

        # request_stop on the trainer when the service's stop event
        # fires: a watcher thread, because run() blocks this one.
        def _watch():
            stop_event.wait()
            trainer.request_stop()

        watcher = threading.Thread(
            target=_watch, name=f"tpuflow-runtime-{name}-stop", daemon=True
        )
        watcher.start()
        try:
            return trainer.run(max_windows=max_windows)
        finally:
            stop_event.set()  # unblock the watcher so it exits
            watcher.join(timeout=1.0)

    return thread_service(
        name, _run, depends_on=depends_on, grace=grace, **spec_kwargs,
    )


def process_service(
    name: str,
    argv: list,
    *,
    depends_on: tuple = (),
    grace: float = 5.0,
    env: dict | None = None,
    cwd: str | None = None,
    **spec_kwargs,
) -> ServiceSpec:
    """An arbitrary child process as a service. Liveness is ``poll()``
    (exit 0 = FINISHED, anything else = dead); stop reuses the training
    supervisor's SIGTERM→grace→SIGKILL escalation, so ``killed_by``
    says whether teardown ran ("sigterm") or the child ignored it
    ("sigkill")."""

    def _start():
        import subprocess

        return subprocess.Popen(argv, env=env, cwd=cwd)

    def _liveness(proc):
        code = proc.poll()
        if code is None:
            return "ok", ""
        if code == 0:
            return "finished", "exit 0"
        return "dead", f"exit code {code}"

    def _stop(proc, grace_s: float):
        from tpuflow.train.supervisor import terminate_gracefully

        if proc.poll() is not None:
            return "already-exited"
        return terminate_gracefully(proc, grace_s)

    return ServiceSpec(
        name=name, start=_start, stop=_stop, liveness=_liveness,
        depends_on=tuple(depends_on), grace=grace, **spec_kwargs,
    )
