"""Shared-runtime supervisor: heterogeneous long-lived services under
one lifecycle, plus the seeded chaos soak that exercises them together.

- ``service.py`` — the declarative :class:`ServiceSpec` model and the
  managed lifecycle states.
- ``supervisor.py`` — :class:`RuntimeSupervisor`: dependency-ordered
  startup, liveness probing, per-service restart policy, graceful
  reverse-order shutdown, aggregated ``/healthz``.
- ``services.py`` — adapters wiring tpuflow's own components (async
  daemon, elastic gang, online controller, child processes) into specs.
- ``chaos.py`` — :class:`ChaosSchedule`: seedable correlated fault
  storms armed at declared soak phases.
- ``soak.py`` — :func:`run_soak`: the day-in-the-life scenario emitting
  one SLO report card (``obs/slo_report_card.schema.json``).

CLI: ``python -m tpuflow.runtime soak spec.json`` /
``python -m tpuflow.runtime run spec.json``.
"""

from tpuflow.runtime.chaos import ChaosPhase, ChaosSchedule
from tpuflow.runtime.service import (
    DEGRADED,
    FAILED,
    FINISHED,
    PENDING,
    RUNNING,
    STARTING,
    STATES,
    STOPPED,
    STOPPING,
    ManagedService,
    ServiceSpec,
)
from tpuflow.runtime.services import (
    daemon_service,
    gang_service,
    online_service,
    process_service,
    thread_service,
)
from tpuflow.runtime.soak import mini_soak_spec, run_soak
from tpuflow.runtime.supervisor import RuntimeSupervisor

__all__ = [
    "ChaosPhase",
    "ChaosSchedule",
    "ManagedService",
    "RuntimeSupervisor",
    "ServiceSpec",
    "STATES",
    "PENDING",
    "STARTING",
    "RUNNING",
    "DEGRADED",
    "FAILED",
    "STOPPING",
    "STOPPED",
    "FINISHED",
    "daemon_service",
    "gang_service",
    "online_service",
    "process_service",
    "thread_service",
    "mini_soak_spec",
    "run_soak",
]
