"""The day-in-the-life chaos soak: every subsystem as one organism.

Every subsystem has been hardened in isolation — elastic gangs survive
churn, the replica plane reloads under load, the online loop swaps with
zero drops. ``run_soak`` runs them TOGETHER, under one
:class:`~tpuflow.runtime.supervisor.RuntimeSupervisor`, through a
seeded cross-subsystem fault storm:

1. an elastic socket gang trains under churn (``gang`` service);
2. the async daemon serves open-loop Poisson traffic the whole time
   (``serving`` + ``traffic`` services);
3. mid-soak the stream regime-shifts; the online loop detects drift,
   warm-start retrains, and hot-swaps the serving artifact under load
   (``online`` service);
4. a :class:`~tpuflow.runtime.chaos.ChaosSchedule` arms correlated
   faults at declared phases — a worker death during averaging, a
   checkpoint flake during the retrain, a latency storm on the predict
   path;
5. the SLO-driven autoscaler (``autoscale`` service,
   ``tpuflow/serve_autoscale.py``) watches the daemon's burn-rate
   history the whole time and climbs its control ladders when the
   storm burns budget;
6. graceful dependency-aware shutdown (traffic → online → autoscale →
   serving drain → gang), then ONE SLO report card
   (``obs/slo_report_card.schema.json``) from the fleet's merged
   trails + the daemon's own registry: availability and its error
   budget, p99 latency, time-to-adapt, and the dropped-request count —
   which must be 0.

``mini_soak_spec`` is the tier-1 preset (2 workers, 1 storm phase,
tens of seconds); the ``slow``-marked full soak and the CLI
(``python -m tpuflow.runtime soak spec.json``) run bigger specs of the
same shape.
"""

from __future__ import annotations

import json
import os
import random
import time

NAMES = "pressure,choke,glr,temperature,water_cut,completion,flow"
TYPES = "float,float,float,float,float,string,float"
_COLS = NAMES.split(",")

# A request is DROPPED when it got neither an answer nor a deliberate
# shed: transport failures and 5xx other than the 503/504 shed codes.
# 429/503/504 are the admission/deadline policies doing their job —
# counted, reported, but not drops.
_SHED_CODES = {"429", "503", "504"}


def mini_soak_spec(root: str) -> dict:
    """The tier-1 mini-soak: 2 gang workers, one correlated storm
    phase, ~50 Poisson requests, one regime shift — small enough for
    the default suite, shaped exactly like the full soak."""
    return {
        "root": root,
        "deadline_s": 150.0,
        "gang": {
            "workers": 2, "epochs": 2,
            "synthetic_wells": 2, "synthetic_steps": 64,
            "heartbeat_timeout": 1.5, "round_timeout": 10.0,
        },
        "serving": {"max_epochs": 4, "hidden": [4]},
        "traffic": {
            "rate_rps": 25.0, "max_requests": 50, "seed": 11,
            "client_workers": 4, "timeout_s": 20.0,
        },
        "online": {
            "healthy_windows": 2, "shifted_windows": 6,
            "shift_scale": 3.0, "window_rows": 120, "seed": 7,
            "knobs": {
                "warmup_windows": 1, "threshold": 3.0,
                "replay_windows": 4, "eval_every": 3,
                "retrain_epochs": 2, "margin": 1000.0,
                "min_retrain_gap": 100, "rollback": False,
            },
        },
        "autoscale": {
            # Tight cadence so the mini-soak's tens-of-seconds window
            # yields real ticks; replica moves are capped off (the
            # tier-1 host places one device) — the controller still
            # reads burn, holds, and records every decision.
            "interval_s": 0.2, "window_s": 5.0,
            "warmup_ticks": 2, "hold_ticks": 2,
            "max_replicas": 1, "max_moves": 4,
        },
        "chaos": {
            "seed": 5,
            "phases": [{
                # ONE correlated storm: a worker death during
                # averaging, checkpoint I/O flaking under the retrain,
                # and a latency storm on the predict path — armed
                # together shortly after the fleet is up.
                "name": "storm", "at_s": 0.5, "duration_s": 10.0,
                "faults": [
                    "elastic.push,nth=2",
                    "checkpoint.save,p=0.35,transient=1",
                    "serve.execute,p=0.3,mode=delay,delay=0.02",
                ],
            }],
        },
        "objectives": [
            {"name": "availability", "kind": "availability",
             "target": 0.999,
             "good": ("serving_admitted_total",),
             "bad": ("serving_shed_total",)},
            {"name": "p99_latency", "kind": "latency_p99",
             "target": 2000.0},
            {"name": "time_to_adapt", "kind": "time_to_adapt",
             "target": 120.0},
        ],
    }


def _write_csv(path: str, table: dict) -> None:
    from tpuflow.storage.local import fsync_write

    rows = []
    for i in range(len(table["flow"])):
        rows.append(",".join(str(table[c][i]) for c in _COLS))
    fsync_write(path, ("\n".join(rows) + "\n").encode("utf-8"))


def _one_request(url: str, body: bytes, timeout_s: float) -> tuple:
    """(status_or_transport_tag, latency_s_or_None)."""
    import urllib.error
    import urllib.request

    t0 = time.monotonic()
    req = urllib.request.Request(
        url, data=body,
        headers={"Content-Type": "application/json", "x-client-id": "soak"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            resp.read()
            return str(resp.status), time.monotonic() - t0
    except urllib.error.HTTPError as e:
        return str(e.code), time.monotonic() - t0
    except Exception as e:
        return f"transport:{type(e).__name__}", None


def _traffic_result(results: list) -> dict:
    by_status: dict = {}
    latencies = []
    for status, latency in results:
        by_status[status] = by_status.get(status, 0) + 1
        if latency is not None:
            latencies.append(latency)
    dropped = sum(
        n for status, n in by_status.items()
        if status.startswith("transport:")
        or (status.isdigit() and status >= "500" and status not in _SHED_CODES)
    )
    latencies.sort()
    p99 = (
        latencies[min(len(latencies) - 1, int(0.99 * len(latencies)))]
        if latencies else None
    )
    return {
        "sent": len(results),
        "by_status": by_status,
        "dropped": dropped,
        "p99_s": p99,
    }


def run_soak(doc: dict) -> dict:
    """Run one day-in-the-life soak from a spec doc (``mini_soak_spec``
    shape); returns the result dict and writes
    ``{root}/soak_report.json``. ``result["ok"]`` requires: the report
    card validates against the committed schema, dropped == 0, the
    workload services all FINISHED, and serving drained cleanly."""
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from tpuflow.api import TrainJobConfig, train
    from tpuflow.data import wells_to_table
    from tpuflow.data.synthetic import generate_wells
    from tpuflow.obs import Registry
    from tpuflow.obs.fleet import read_fleet
    from tpuflow.obs.slo import report_card, validate_report_card
    from tpuflow.online.controller import OnlineTrainer
    from tpuflow.runtime.chaos import ChaosSchedule
    from tpuflow.runtime.services import (
        daemon_service,
        gang_service,
        online_service,
        thread_service,
    )
    from tpuflow.runtime.supervisor import RuntimeSupervisor
    from tpuflow.utils.paths import atomic_write_json

    root = doc.get("root")
    if not root:
        raise ValueError("soak spec needs 'root' (the storage root)")
    os.makedirs(root, exist_ok=True)
    wall0 = time.monotonic()
    deadline_s = float(doc.get("deadline_s", 150.0))
    gang_doc = dict(doc.get("gang") or {})
    serving_doc = dict(doc.get("serving") or {})
    traffic_doc = dict(doc.get("traffic") or {})
    online_doc = dict(doc.get("online") or {})
    autoscale_doc = dict(doc.get("autoscale") or {})

    # --- the shared data + the initial serving artifact ---------------
    table = wells_to_table(generate_wells(n_wells=4, steps=200, seed=3))
    base_csv = os.path.join(root, "base.csv")
    _write_csv(base_csv, {c: list(np.asarray(table[c])) for c in _COLS})
    serving_dir = os.path.join(root, "serving")

    def _serving_config(**over):
        kw = dict(
            column_names=NAMES, column_types=TYPES, target="flow",
            storage_path=serving_dir, data_path=base_csv,
            model="static_mlp",
            model_kwargs={"hidden": list(serving_doc.get("hidden", [4]))},
            max_epochs=int(serving_doc.get("max_epochs", 4)),
            patience=100, batch_size=64, verbose=False, health="off",
        )
        kw.update(over)
        return TrainJobConfig(**kw)

    train(_serving_config(
        metrics_path=os.path.join(serving_dir, "metrics.jsonl")
    ))

    # --- chaos schedule (started only once the fleet is up) -----------
    chaos = None
    if doc.get("chaos"):
        chaos = ChaosSchedule.from_dict(doc["chaos"])

    # --- services ------------------------------------------------------
    box: dict = {}  # "server": the running AsyncServer (set at start)

    def _server_factory():
        from tpuflow.serve_async import AsyncServer

        server = AsyncServer(
            "127.0.0.1", 0, enable_jobs=False,
            trail_path=os.path.join(root, "serve-metrics.jsonl"),
        )
        box["server"] = server
        return server

    gang_spec = {
        "model": "static_mlp", "model_kwargs": {"hidden": []},
        "epochs": int(gang_doc.get("epochs", 2)),
        "batchSize": 32, "patience": 100, "loss": "mse",
        "synthetic_wells": int(gang_doc.get("synthetic_wells", 2)),
        "synthetic_steps": int(gang_doc.get("synthetic_steps", 64)),
        "n_devices": 1, "verbose": False,
        "storagePath": os.path.join(root, "gang"),
    }
    gang = gang_service(
        "gang", gang_spec, int(gang_doc.get("workers", 2)),
        transport="socket",
        heartbeat_timeout=float(gang_doc.get("heartbeat_timeout", 1.5)),
        round_timeout=float(gang_doc.get("round_timeout", 10.0)),
        # The storm WANTS a worker death absorbed, not reported as a
        # fleet failure — the elastic contract under churn.
        allow_partial=True,
        grace=20.0,
    )
    # serving depends_on gang is the SHUTDOWN-ordering contract, not a
    # data dependency: stop order is reverse topo, so serving drains
    # BEFORE the gang is touched (ISSUE 16's drill).
    serving = daemon_service("serving", _server_factory,
                             depends_on=("gang",), grace=15.0)

    # The regime-shifted stream: healthy windows, then scaled ones. The
    # generator fires the chaos "regime_shift" hook as the first
    # shifted window is consumed — the storm phases declared
    # on_event="regime_shift" open exactly when drift begins.
    healthy = int(online_doc.get("healthy_windows", 2))
    shifted = int(online_doc.get("shifted_windows", 6))
    scale = float(online_doc.get("shift_scale", 3.0))
    window_rows = int(online_doc.get("window_rows", 120))
    rng = np.random.default_rng(int(online_doc.get("seed", 7)))
    n_rows = len(table["flow"])

    def _chunk(chunk_scale):
        idx = rng.integers(0, n_rows, window_rows)
        return {
            c: (
                np.asarray(table[c])[idx] if c == "completion"
                else np.asarray(table[c], np.float64)[idx]
                * (chunk_scale if c in ("pressure", "flow") else 1.0)
            )
            for c in _COLS
        }

    chunks = [_chunk(1.0) for _ in range(healthy)] + \
        [_chunk(scale) for _ in range(shifted)]

    def _chunks_with_hook():
        for i, chunk in enumerate(chunks):
            if i == healthy and chaos is not None:
                chaos.fire_event("regime_shift")
            yield chunk

    def _trainer_factory():
        server = box["server"]
        knobs = dict(online_doc.get("knobs") or {})
        knobs["daemon_url"] = f"http://127.0.0.1:{server.port}"
        cfg = _serving_config(online=knobs)
        return OnlineTrainer(
            cfg, source=_chunks_with_hook(), registry=Registry(),
        )

    online = online_service(
        "online", _trainer_factory, depends_on=("serving",), grace=60.0,
    )

    def _traffic_run(stop_event):
        server = box["server"]
        url = f"http://127.0.0.1:{server.port}/predict"
        probe = {
            c: [
                float(v) if c != "completion" else str(v)
                for v in np.asarray(table[c][:16])
            ]
            for c in _COLS if c != "flow"
        }
        body = json.dumps({
            "storagePath": serving_dir, "model": "static_mlp",
            "columns": probe,
        }).encode()
        rate = float(traffic_doc.get("rate_rps", 25.0))
        max_requests = int(traffic_doc.get("max_requests", 50))
        timeout_s = float(traffic_doc.get("timeout_s", 20.0))
        poisson = random.Random(int(traffic_doc.get("seed", 11)))
        pool = ThreadPoolExecutor(
            max_workers=int(traffic_doc.get("client_workers", 4)),
            thread_name_prefix="tpuflow-soak-client",
        )
        futures = []
        # Open loop: arrivals follow the seeded exponential gaps no
        # matter how slow responses are — load does NOT back off when
        # the server struggles, which is the honest way to grade it.
        while len(futures) < max_requests and not stop_event.is_set():
            if stop_event.wait(poisson.expovariate(rate)):
                break
            futures.append(
                pool.submit(_one_request, url, body, timeout_s)
            )
        results = [f.result() for f in futures]
        pool.shutdown(wait=True)
        return _traffic_result(results)

    traffic = thread_service(
        "traffic", _traffic_run, depends_on=("serving",), grace=30.0,
    )

    def _autoscale_run(stop_event):
        from tpuflow.serve_autoscale import ObservingController

        server = box["server"]
        controller = ObservingController(
            server, server.history,
            registry=server.registry,
            block=autoscale_doc,
            logger=server._trail,
        )
        # run() paces on the stop event and returns summary() — the
        # service handle's result, folded into the report card source.
        return controller.run(stop_event)

    autoscale = thread_service(
        "autoscale", _autoscale_run, depends_on=("serving",), grace=10.0,
    )

    # Env-gated flight recorder for crash verdicts (TPUFLOW_OBS_FLIGHT):
    # the serving daemon attaches its own alert-triggered recorder; this
    # one covers the supervisor's FAILED path, same bundle dir.
    from tpuflow.obs.flight import flight_from_env

    flight = flight_from_env(default_root=os.path.join(root, "flight"))
    supervisor = RuntimeSupervisor(
        [gang, serving, autoscale, online, traffic],
        trail_path=os.path.join(root, "runtime-metrics.jsonl"),
        flight=flight,
    )
    supervisor.start()
    healthz_port = supervisor.serve_healthz()
    if chaos is not None:
        chaos.start()

    # --- the day in the life -------------------------------------------
    workload = ("gang", "online", "traffic")
    deadline = wall0 + deadline_s
    while time.monotonic() < deadline:
        snap = supervisor.healthz()["services"]
        if all(
            snap[n]["state"] in ("finished", "failed", "stopped")
            for n in workload
        ):
            break
        time.sleep(0.1)

    chaos_summary = chaos.stop() if chaos is not None else None
    gang_handle = supervisor.service_handle("gang")
    online_handle = supervisor.service_handle("online")
    traffic_handle = supervisor.service_handle("traffic")
    autoscale_handle = supervisor.service_handle("autoscale")
    final = supervisor.shutdown()

    # --- the report card -----------------------------------------------
    server = box.get("server")
    _trails, events = read_fleet([root])
    traffic_summary = traffic_handle.result if traffic_handle else None
    gang_result = gang_handle.result if gang_handle else None
    online_summary = online_handle.result if online_handle else None
    dropped = (traffic_summary or {}).get("dropped")
    source = {
        "scenario": "day-in-the-life soak",
        "root": root,
        "traffic": traffic_summary,
        "chaos": chaos_summary,
        "online": online_summary,
        "autoscale": autoscale_handle.result if autoscale_handle else None,
        "gang": gang_result.summary() if gang_result is not None else None,
        "services": final["services"],
        "wall_s": round(time.monotonic() - wall0, 3),
    }
    card = report_card(
        events,
        doc.get("objectives") or mini_soak_spec(root)["objectives"],
        registry=server.registry if server is not None else None,
        source=source,
    )
    card_error = None
    try:
        validate_report_card(card)
    except ValueError as e:
        card_error = str(e)
    rows = {r["name"]: r for r in card.get("objectives", ())}
    adapt = rows.get("time_to_adapt") or {}
    states = {n: final["services"][n]["state"] for n in final["services"]}
    ok = (
        card_error is None
        and dropped == 0
        and all(states.get(n) in ("finished", "stopped") for n in workload)
        and final["services"]["serving"].get("killed_by") == "drained"
    )
    result = {
        "ok": ok,
        "root": root,
        "dropped": dropped,
        "card_error": card_error,
        "time_to_adapt_s": adapt.get("measured"),
        "healthz_port": healthz_port,
        "card": card,
    }
    atomic_write_json(os.path.join(root, "soak_report.json"), result)
    return result
