"""CLI for the shared runtime.

``python -m tpuflow.runtime soak spec.json [-o out.json]``
    Run the day-in-the-life chaos soak (``soak.run_soak``); prints the
    verdict summary and exits 0 iff ``ok`` (card valid, dropped == 0,
    workload finished, serving drained).

``python -m tpuflow.runtime run spec.json``
    Stand up a declarative service fleet under a
    :class:`RuntimeSupervisor` and hold it until SIGTERM/SIGINT (or
    until every service is terminal), then shut down gracefully in
    reverse dependency order. Writes ``{root}/runtime-ready.json``
    (ports) once up and ``{root}/runtime-final.json`` (per-service
    ``state``/``killed_by``/``stop_index``) after shutdown — the
    graceful-shutdown drill's forensics.

Run-spec service types::

    {"root": "...", "healthz": true, "services": [
        {"type": "process", "name": "gang",
         "argv": ["python", "-c", "..."], "grace": 5.0},
        {"type": "daemon", "name": "serving", "depends_on": ["gang"],
         "grace": 10.0},
    ]}
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading


def _build_service(doc: dict, root: str, servers: dict):
    from tpuflow.runtime.services import daemon_service, process_service

    kind = doc.get("type")
    name = doc.get("name")
    if not name:
        raise SystemExit(f"run spec: service entry needs a name: {doc}")
    depends_on = tuple(doc.get("depends_on") or ())
    grace = float(doc.get("grace", 10.0))
    if kind == "daemon":

        def _factory():
            from tpuflow.serve_async import AsyncServer

            server = AsyncServer(
                doc.get("host", "127.0.0.1"), int(doc.get("port", 0)),
                enable_jobs=bool(doc.get("enable_jobs", False)),
                trail_path=os.path.join(root, f"{name}-metrics.jsonl"),
            )
            servers[name] = server
            return server

        return daemon_service(
            name, _factory, depends_on=depends_on, grace=grace,
        )
    if kind == "process":
        argv = doc.get("argv")
        if not argv:
            raise SystemExit(f"run spec: process service {name!r} needs argv")
        env = None
        if doc.get("env"):
            env = dict(os.environ)
            env.update({str(k): str(v) for k, v in doc["env"].items()})
        return process_service(
            name, list(argv), depends_on=depends_on, grace=grace, env=env,
        )
    raise SystemExit(
        f"run spec: unknown service type {kind!r} for {name!r} "
        "(expected 'daemon' or 'process')"
    )


def _cmd_run(spec_path: str) -> int:
    from tpuflow.runtime.supervisor import RuntimeSupervisor
    from tpuflow.storage import read_json
    from tpuflow.utils.paths import atomic_write_json

    doc = read_json(spec_path)
    root = doc.get("root")
    if not root:
        raise SystemExit("run spec needs 'root'")
    os.makedirs(root, exist_ok=True)
    servers: dict = {}
    specs = [
        _build_service(sdoc, root, servers)
        for sdoc in doc.get("services") or []
    ]
    supervisor = RuntimeSupervisor(
        specs, trail_path=os.path.join(root, "runtime-metrics.jsonl"),
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    supervisor.start()
    healthz_port = (
        supervisor.serve_healthz() if doc.get("healthz", True) else None
    )
    atomic_write_json(os.path.join(root, "runtime-ready.json"), {
        "pid": os.getpid(),
        "healthz_port": healthz_port,
        "ports": {name: server.port for name, server in servers.items()},
    })
    terminal = ("finished", "failed", "stopped")
    while not stop.is_set():
        if stop.wait(0.2):
            break
        states = supervisor.healthz()["services"]
        if all(s["state"] in terminal for s in states.values()):
            break
    final = supervisor.shutdown()
    atomic_write_json(os.path.join(root, "runtime-final.json"), final)
    failed = [
        n for n, s in final["services"].items() if s["state"] == "failed"
    ]
    return 1 if failed else 0


def _cmd_soak(spec_path: str, out: str | None) -> int:
    from tpuflow.runtime.soak import run_soak
    from tpuflow.storage import read_json

    doc = read_json(spec_path)
    result = run_soak(doc)
    if out:
        from tpuflow.utils.paths import atomic_write_json

        atomic_write_json(out, result)
    print(json.dumps({
        "ok": result["ok"],
        "dropped": result["dropped"],
        "time_to_adapt_s": result["time_to_adapt_s"],
        "card_error": result["card_error"],
        "root": result["root"],
    }, indent=2))
    return 0 if result["ok"] else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpuflow.runtime",
        description="shared-runtime supervisor CLI (module docstring)",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    p_soak = sub.add_parser(
        "soak", help="run the day-in-the-life chaos soak from a spec",
    )
    p_soak.add_argument("spec", help="soak spec JSON (soak.mini_soak_spec shape)")
    p_soak.add_argument("-o", "--out", default=None,
                        help="also write the full result JSON here")
    p_run = sub.add_parser(
        "run", help="supervise a declarative service fleet until SIGTERM",
    )
    p_run.add_argument("spec", help="run spec JSON (module docstring)")
    args = parser.parse_args(argv)
    if args.command == "soak":
        return _cmd_soak(args.spec, args.out)
    return _cmd_run(args.spec)


if __name__ == "__main__":
    sys.exit(main())
