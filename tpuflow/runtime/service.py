"""The service model for the shared-runtime supervisor.

A **service** is one long-lived component of the day-in-the-life fleet
— the async serving daemon, the elastic gang, the online controller, an
arbitrary child process — declared as a :class:`ServiceSpec`: how to
start it, how to probe it, how to stop it, what it depends on, and what
its restart budget is. The spec is pure declaration (three callables
and some numbers); the supervisor (``runtime/supervisor.py``) owns the
lifecycle, and the adapters (``runtime/services.py``) build specs for
tpuflow's own components so a soak is a list of specs, not a script.

The callables' contracts:

- ``start() -> handle`` — launch the component, return whatever
  ``liveness``/``stop`` need (a server object, a thread box, a Popen).
  A raise here is a failed start: the supervisor applies the restart
  policy exactly as for a death — starting and staying up are the same
  promise.
- ``liveness(handle) -> (state, detail)`` — one cheap probe. ``state``
  is one of ``ok`` (healthy), ``degraded`` (up but impaired — reported,
  never restarted: a degraded service is still doing work a restart
  would destroy), ``dead`` (gone; the restart policy decides what
  happens next), ``finished`` (exited on purpose — a gang that trained
  to completion is done, not dead).
- ``stop(handle, grace) -> killed_by | None`` — graceful stop with a
  bounded grace window, escalating however the component requires
  (drain then close; Event then join; SIGTERM then SIGKILL). The
  return value records HOW it died ("sigterm", "sigkill", "drained",
  "abandoned", ...) for the shutdown forensics.

States a managed service moves through::

    PENDING -> STARTING -> RUNNING <-> DEGRADED
                              |            |
                              v            v
               FINISHED    (death) -> restart or FAILED
                              |
            STOPPING -> STOPPED          (shutdown path)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

PENDING = "pending"
STARTING = "starting"
RUNNING = "running"
DEGRADED = "degraded"
FAILED = "failed"
STOPPING = "stopping"
STOPPED = "stopped"
FINISHED = "finished"

# Every state a managed service can occupy — the runtime_services gauge
# emits one labeled sample per state so a scrape sees zeros, not
# missing series, for the states nothing is in.
STATES = (
    PENDING, STARTING, RUNNING, DEGRADED, FAILED,
    STOPPING, STOPPED, FINISHED,
)

# What liveness() may return.
PROBE_STATES = ("ok", "degraded", "dead", "finished")


@dataclass
class ServiceSpec:
    """One declaratively-specced service (see the module docstring)."""

    name: str
    start: Callable[[], object]
    stop: Callable[[object, float], str | None]
    liveness: Callable[[object], tuple]
    depends_on: tuple = ()
    grace: float = 5.0  # seconds stop() gets before escalation
    max_restarts: int = 0
    # A service that dies faster than min_uptime after a (re)start is a
    # fast death; crash_loop_threshold consecutive fast deaths classify
    # a crash loop and fail the service even with restart budget left —
    # the train/supervisor.py precedent: restarting into the same
    # immediate death burns budget without buying recovery.
    min_uptime: float = 1.0
    crash_loop_threshold: int = 2
    backoff_base: float = 0.05
    backoff_max: float = 2.0
    backoff_jitter: float = 0.0
    backoff_seed: int | None = None

    def __post_init__(self):
        if not self.name or not isinstance(self.name, str):
            raise ValueError(f"service name must be a non-empty string, "
                             f"got {self.name!r}")
        if self.grace < 0:
            raise ValueError(
                f"service {self.name!r}: grace must be >= 0 seconds, "
                f"got {self.grace}"
            )
        if self.max_restarts < 0:
            raise ValueError(
                f"service {self.name!r}: max_restarts must be >= 0, "
                f"got {self.max_restarts}"
            )
        if self.crash_loop_threshold < 1:
            raise ValueError(
                f"service {self.name!r}: crash_loop_threshold must be "
                f">= 1, got {self.crash_loop_threshold}"
            )
        self.depends_on = tuple(self.depends_on)


@dataclass
class ManagedService:
    """The supervisor's mutable record for one service. All fields
    after ``spec`` are guarded by the supervisor's lock."""

    spec: ServiceSpec
    state: str = PENDING
    handle: object = None
    detail: str = ""
    restarts: int = 0
    failures: list = field(default_factory=list)
    killed_by: str | None = None
    started_at: float | None = None  # monotonic, last (re)start
    fast_deaths: int = 0  # consecutive deaths under min_uptime
    stop_index: int | None = None  # position in the shutdown order

    def snapshot_locked(self) -> dict:
        """A JSON-safe copy of the record (caller holds the lock)."""
        return {
            "name": self.spec.name,
            "state": self.state,
            "detail": self.detail,
            "depends_on": list(self.spec.depends_on),
            "restarts": self.restarts,
            "failures": list(self.failures),
            "killed_by": self.killed_by,
            "stop_index": self.stop_index,
        }
