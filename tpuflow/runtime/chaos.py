"""Seedable chaos schedule: correlated cross-subsystem fault storms.

A single armed :class:`~tpuflow.resilience.faults.FaultSpec` exercises
one site; distributed stacks break where failures CORRELATE — a
checkpoint flake while a swap is mid-flight, a worker death during an
averaging round, a latency storm during a retrain. A
:class:`ChaosSchedule` arms a *phase* — a named SET of fault specs —
together, at a declared moment of the soak: either ``at_s`` seconds
after ``start()`` (disarmed again after ``duration_s``), or when the
scenario driver calls :func:`ChaosSchedule.fire_event` with the
phase's ``on_event`` name (the "regime shift just happened" hook).

Determinism: the schedule ``seed`` derives a per-entry seed for every
probabilistic (``p=``) fault that does not pin its own ``seed=`` —
``f(schedule_seed, phase_index, entry_index)`` — so one seed replays
the ENTIRE storm's coin flips identically; the regression drill diffs
two replays' ``faults_injected_total`` series. (Cross-process
determinism — a storm surviving a supervised child's restart — is the
``TPUFLOW_FAULTS_CURSOR`` mechanism in ``resilience/faults.py``; this
schedule arms in-process specs, which also take precedence over env
specs at a shared site.)

Phase grammar (``from_dict``; the soak spec's ``chaos`` block)::

    {"seed": 7, "phases": [
        {"name": "storm", "at_s": 1.5, "duration_s": 6.0,
         "faults": ["elastic.push,nth=2",
                    "checkpoint.save,p=0.4,transient=1",
                    "serve.execute,p=0.3,mode=delay,delay=0.02"]},
        {"name": "drift-flake", "on_event": "regime_shift",
         "duration_s": 4.0,
         "faults": ["online.swap,nth=1"]},
    ]}
"""

from __future__ import annotations

import dataclasses
import threading
import time

from tpuflow.resilience import faults as _faults


@dataclasses.dataclass
class ChaosPhase:
    """One named storm: the fault entries armed together, and when."""

    name: str
    faults: tuple
    at_s: float | None = None  # arm this long after start()
    on_event: str | None = None  # ... or when fire_event(name) matches
    duration_s: float | None = None  # disarm after; None = until stop()

    def __post_init__(self):
        if not self.name:
            raise ValueError("chaos phase needs a name")
        if not self.faults:
            raise ValueError(f"chaos phase {self.name!r} has no faults")
        if (self.at_s is None) == (self.on_event is None):
            raise ValueError(
                f"chaos phase {self.name!r} needs exactly one trigger: "
                "at_s= (a clock moment) or on_event= (a scenario hook)"
            )
        if self.at_s is not None and self.at_s < 0:
            raise ValueError(
                f"chaos phase {self.name!r}: at_s must be >= 0"
            )
        if self.duration_s is not None and self.duration_s <= 0:
            raise ValueError(
                f"chaos phase {self.name!r}: duration_s must be > 0"
            )
        self.faults = tuple(self.faults)


def _derive_seed(schedule_seed: int, phase_idx: int, entry_idx: int) -> int:
    """Deterministic per-entry seed — one schedule seed pins every
    probabilistic entry's private stream."""
    return (
        schedule_seed * 1_000_003 + phase_idx * 10_007 + entry_idx * 101 + 1
    ) & 0x7FFFFFFF


class ChaosSchedule:
    """Arm/disarm phases of correlated faults on the shared registry
    (module docstring). ``start()`` launches the timer thread for
    ``at_s`` phases; ``fire_event()`` triggers ``on_event`` phases;
    ``stop()`` disarms everything still armed.

    Lock discipline: ``_lock`` guards the mutable collections
    (``_armed``, ``_armed_ever``, ``_expires``, ``_trail``) only; the
    fault-registry arm/disarm calls and observability writes run
    outside it (they take their own locks)."""

    def __init__(self, phases, *, seed: int = 0, registry=None,
                 clock=time.monotonic, tick: float = 0.02):
        from tpuflow.obs import default_registry

        self.phases = [
            p if isinstance(p, ChaosPhase) else ChaosPhase(**p)
            for p in phases
        ]
        names = [p.name for p in self.phases]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate chaos phase names in {names}")
        self.seed = int(seed)
        self._clock = clock
        self._tick = float(tick)
        registry = registry or default_registry()
        self._phases_total = registry.counter(
            "runtime_chaos_phases_total",
            "chaos-schedule phase transitions by phase and action",
        )
        # Parse + seed-derive up front: a typo'd entry fails at
        # schedule construction, not mid-soak. These are validated
        # PROTOTYPES — arming copies them, so hit counters and
        # probability streams start at zero at arm time (the storm's
        # randomness depends only on the seed and the sites' hit
        # sequence, not on how long the fleet ran before the phase).
        self._proto: dict[str, list] = {}
        for pi, phase in enumerate(self.phases):
            protos = []
            for ei, entry in enumerate(phase.faults):
                spec = _faults.parse_fault_spec(entry)
                if spec.p and "seed=" not in entry.replace(" ", ""):
                    spec = dataclasses.replace(
                        spec, seed=_derive_seed(self.seed, pi, ei)
                    )
                protos.append(spec)
            self._proto[phase.name] = protos
        self._lock = threading.Lock()
        self._armed: dict[str, list] = {}  # phase name -> live FaultSpecs
        self._armed_ever: set = set()  # one arming per phase, ever
        self._expires: dict[str, float] = {}  # phase name -> clock moment
        self._trail: list[dict] = []  # arm/disarm records, in order
        self._t0: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # --- triggers ------------------------------------------------------

    def start(self) -> "ChaosSchedule":
        self._t0 = self._clock()
        self._thread = threading.Thread(
            target=self._timer_loop, name="tpuflow-runtime-chaos",
            daemon=True,
        )
        self._thread.start()
        return self

    def fire_event(self, event: str) -> list:
        """Arm every not-yet-armed phase declared ``on_event=event``;
        returns the phase names armed."""
        armed = []
        for phase in self.phases:
            if phase.on_event == event and self._arm_phase(phase):
                armed.append(phase.name)
        return armed

    def stop(self) -> dict:
        """Stop the timer and disarm every armed phase; returns the
        arm/disarm trail (the storm's own forensics)."""
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            self._thread = None
        with self._lock:
            names = list(self._armed)
        for name in names:
            self._disarm_phase(name, why="schedule stopped")
        return self.summary()

    def summary(self) -> dict:
        phase_names = [p.name for p in self.phases]
        with self._lock:
            trail = list(self._trail)
        return {"seed": self.seed, "phases": phase_names, "trail": trail}

    # --- internals -----------------------------------------------------

    def _timer_loop(self) -> None:
        while not self._stop.wait(self._tick):
            now = self._clock()
            elapsed = now - self._t0
            for phase in self.phases:
                if phase.at_s is not None and elapsed >= phase.at_s:
                    self._arm_phase(phase)
            with self._lock:
                expired = [
                    name for name, at in self._expires.items()
                    if now >= at
                ]
            for name in expired:
                self._disarm_phase(name, why="duration elapsed")

    def _arm_phase(self, phase: ChaosPhase) -> bool:
        """Arm a phase exactly once (idempotent across timer ticks and
        racing event fires)."""
        with self._lock:
            if phase.name in self._armed_ever:
                return False
            self._armed_ever.add(phase.name)
        specs = [
            dataclasses.replace(proto) for proto in self._proto[phase.name]
        ]
        expiry = None
        if phase.duration_s is not None:
            expiry = self._clock() + phase.duration_s
        with self._lock:
            self._armed[phase.name] = specs
            if expiry is not None:
                self._expires[phase.name] = expiry
            self._trail.append({
                "phase": phase.name, "action": "armed",
                "faults": [s.describe() for s in specs],
            })
        for spec in specs:
            _faults.arm(spec)
        self._phases_total.inc(phase=phase.name, action="armed")
        from tpuflow.obs import record_event

        record_event(
            "chaos_phase", phase=phase.name, action="armed",
            faults=list(phase.faults),
        )
        return True

    def _disarm_phase(self, name: str, *, why: str) -> None:
        with self._lock:
            specs = self._armed.pop(name, None)
            self._expires.pop(name, None)
        if specs is None:
            return
        for spec in specs:
            _faults.disarm(spec)  # one-shots that fired already self-removed
        fired = sum(s.fired for s in specs)
        with self._lock:
            self._trail.append({
                "phase": name, "action": "disarmed", "why": why,
                "fired": fired,
            })
        self._phases_total.inc(phase=name, action="disarmed")
        from tpuflow.obs import record_event

        record_event(
            "chaos_phase", phase=name, action="disarmed", why=why,
            fired=fired,
        )

    @classmethod
    def from_dict(cls, doc: dict, *, registry=None) -> "ChaosSchedule":
        if not isinstance(doc, dict):
            raise ValueError(
                f"chaos block must be an object, got {type(doc).__name__}"
            )
        phases = doc.get("phases") or []
        return cls(phases, seed=int(doc.get("seed", 0)), registry=registry)
