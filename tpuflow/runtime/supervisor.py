"""The shared-runtime service supervisor.

One process, many long-lived heterogeneous components — the async
serving daemon, an elastic gang, the online controller, child
processes — owned as declaratively-specced services
(:class:`~tpuflow.runtime.service.ServiceSpec`) with:

- **dependency-ordered startup**: services start in topological order
  of ``depends_on`` (a cycle fails at construction); a failed start
  stops the already-started prefix in reverse before re-raising, so a
  half-started fleet never leaks.
- **liveness probing**: one daemon probe thread polls each service's
  ``liveness`` callable — riding whatever machinery the component
  already has (``/healthz`` for the daemon, thread aliveness + result
  boxes for gangs and loops, ``poll()`` for processes).
- **per-service restart policy**: a dead service is restarted under
  its spec's budget with ``resilience.RetryPolicy`` backoff; deaths
  faster than ``min_uptime`` accumulate toward crash-loop
  classification (the ``train/supervisor.py`` precedent) and fail the
  service even with budget left.
- **dependency-aware graceful shutdown**: reverse topological order —
  a service stops before everything it depends on (drain serving
  before killing the gang it fronts), each through its spec's
  ``stop(handle, grace)`` with the escalation recorded as
  ``killed_by``.

Observability: a ``runtime_services{state=}`` gauge (default registry
by default) holds the per-state service counts; every transition lands
in the forensics ring (``runtime_service_state``) and, when
``trail_path`` is set, on the fleet timeline; ``serve_healthz()``
exposes the aggregated rollup over HTTP for external orchestrators.
"""

from __future__ import annotations

import json
import random
import threading
import time

from tpuflow.resilience.retry import RetryPolicy
from tpuflow.runtime.service import (
    DEGRADED,
    FAILED,
    FINISHED,
    PENDING,
    RUNNING,
    STARTING,
    STATES,
    STOPPED,
    STOPPING,
    ManagedService,
    ServiceSpec,
)


def _topo_order(specs: list[ServiceSpec]) -> list[str]:
    """Kahn's algorithm over ``depends_on``; deterministic (declaration
    order breaks ties); raises on duplicates, unknown deps, cycles."""
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        dupes = sorted({n for n in names if names.count(n) > 1})
        raise ValueError(f"duplicate service names: {dupes}")
    by_name = {s.name: s for s in specs}
    for s in specs:
        for dep in s.depends_on:
            if dep not in by_name:
                raise ValueError(
                    f"service {s.name!r} depends on unknown service "
                    f"{dep!r}; declared: {sorted(by_name)}"
                )
            if dep == s.name:
                raise ValueError(f"service {s.name!r} depends on itself")
    remaining = dict(by_name)
    order: list[str] = []
    placed: set = set()
    progress = True
    while remaining and progress:
        progress = False
        for name in list(names):
            if name not in remaining:
                continue
            if all(d in placed for d in remaining[name].depends_on):
                order.append(name)
                placed.add(name)
                del remaining[name]
                progress = True
    if remaining:
        raise ValueError(
            f"service dependency cycle among {sorted(remaining)} — "
            "depends_on must be a DAG"
        )
    return order


class RuntimeSupervisor:
    """Own a fleet of :class:`ServiceSpec` services (module docstring).

    Lifecycle: ``start()`` → (work happens; ``wait()`` to watch for
    quiescence) → ``shutdown()``. ``healthz()``/``snapshot()`` are
    callable from any thread at any point.
    """

    def __init__(
        self,
        specs,
        *,
        registry=None,
        probe_interval: float = 0.25,
        trail_path: str | None = None,
        clock=time.monotonic,
        flight=None,
    ):
        from tpuflow.obs import default_registry

        specs = list(specs)
        if not specs:
            raise ValueError("RuntimeSupervisor needs at least one service")
        if probe_interval <= 0:
            raise ValueError(
                f"probe_interval must be > 0 seconds, got {probe_interval}"
            )
        self._order = _topo_order(specs)  # startup order; stop reverses it
        self._specs = {s.name: s for s in specs}
        self._services = {s.name: ManagedService(spec=s) for s in specs}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._probe_thread: threading.Thread | None = None
        self._healthz_server = None
        self._healthz_thread: threading.Thread | None = None
        self.probe_interval = float(probe_interval)
        self._clock = clock
        self.registry = registry or default_registry()
        self._gauge = self.registry.gauge(
            "runtime_services",
            "runtime-supervised services by lifecycle state",
        )
        self._restarts_total = self.registry.counter(
            "runtime_service_restarts_total",
            "runtime-supervised service restarts by service",
        )
        self._trail = None
        if trail_path:
            from tpuflow.utils.logging import MetricsLogger

            self._trail = MetricsLogger(trail_path)
        # Optional FlightRecorder (tpuflow/obs/flight.py): a service
        # declared FAILED captures a forensic bundle at the moment of
        # the verdict — forced past the alert rate limit, because crash
        # verdicts are rare and each one deserves its evidence.
        self._flight = flight
        # Every state gets a sample from the first scrape on — zeros,
        # not missing series, for the states nothing occupies yet.
        for state in STATES:
            self._gauge.set(
                float(len(specs)) if state == PENDING else 0.0, state=state
            )

    # --- transitions ---------------------------------------------------

    def _transition(self, name: str, state: str, detail: str = "") -> None:
        self._transition_if(name, None, state, detail)

    def _transition_if(
        self, name: str, from_states, state: str, detail: str = "",
    ) -> bool:
        """Move ``name`` to ``state`` (only from ``from_states`` when
        given); refresh the per-state gauge; mirror to the forensics
        ring and the trail. Returns whether the transition applied."""
        with self._lock:
            svc = self._services[name]
            if from_states is not None and svc.state not in from_states:
                return False
            old = svc.state
            svc.state = state
            if detail:
                svc.detail = detail
            counts = self._state_counts_locked()
        # Gauge/ring/trail updates run OUTSIDE the lock: none of them
        # may ever block a probe or a shutdown pass.
        for st in STATES:
            self._gauge.set(float(counts.get(st, 0)), state=st)
        from tpuflow.obs import record_event

        record_event(
            "runtime_service_state",
            service=name, state=state, previous=old, detail=detail,
        )
        if self._trail is not None:
            self._trail.write(
                "runtime_service_state",
                service=name, state=state, previous=old, detail=detail,
            )
        if state == FAILED and self._flight is not None:
            self._flight.capture(
                "crash",
                reason=f"service {name} failed: {detail}" if detail
                else f"service {name} failed",
                force=True,
            )
        return True

    def _state_counts_locked(self) -> dict:
        counts: dict = {}
        for svc in self._services.values():
            counts[svc.state] = counts.get(svc.state, 0) + 1
        return counts

    # --- startup -------------------------------------------------------

    def start(self) -> "RuntimeSupervisor":
        """Start every service in dependency order, then the probe
        thread. A start failure stops the started prefix (reverse
        order) and re-raises — all-or-nothing."""
        started: list[str] = []
        try:
            for name in self._order:
                spec = self._specs[name]
                self._transition(name, STARTING)
                handle = spec.start()
                now = self._clock()
                with self._lock:
                    svc = self._services[name]
                    svc.handle = handle
                    svc.started_at = now
                self._transition(name, RUNNING)
                started.append(name)
        except BaseException:
            for name in reversed(started):
                try:
                    self._stop_service(name)
                except Exception:
                    pass  # best-effort unwind; the start error wins
            raise
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="tpuflow-runtime-probe",
            daemon=True,
        )
        self._probe_thread.start()
        return self

    # --- liveness + restart --------------------------------------------

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            self._probe_once()

    def _probe_once(self) -> None:
        with self._lock:
            targets = [
                (svc.spec.name, svc.spec, svc.handle)
                for svc in self._services.values()
                if svc.state in (RUNNING, DEGRADED)
            ]
        for name, spec, handle in targets:
            try:
                probe, detail = spec.liveness(handle)
            except Exception as e:  # a broken probe reads as a death
                probe, detail = "dead", f"liveness probe raised: {e!r}"
            if probe == "ok":
                self._transition_if(name, (DEGRADED,), RUNNING, detail)
            elif probe == "degraded":
                self._transition_if(
                    name, (RUNNING, DEGRADED), DEGRADED, detail
                )
            elif probe == "finished":
                self._transition_if(
                    name, (RUNNING, DEGRADED), FINISHED, detail
                )
            elif probe == "dead":
                self._handle_death(name, detail)
            else:
                self._handle_death(
                    name,
                    f"liveness returned unknown state {probe!r} "
                    f"(detail: {detail})",
                )

    def _handle_death(self, name: str, detail: str) -> None:
        """Classify a death and apply the restart policy. Runs on the
        probe thread; backoff sleeps happen here, outside the lock —
        bounded by the spec's backoff_max."""
        spec = self._specs[name]
        rng = (
            random.Random(spec.backoff_seed)
            if spec.backoff_seed is not None else random
        )
        policy = RetryPolicy(
            base_delay=spec.backoff_base, max_delay=spec.backoff_max,
            jitter=spec.backoff_jitter,
        )
        while not self._stop.is_set():
            now = self._clock()
            with self._lock:
                svc = self._services[name]
                if svc.state not in (RUNNING, DEGRADED, STARTING):
                    return  # shutdown (or a FAILED verdict) raced us
                uptime = (
                    now - svc.started_at
                    if svc.started_at is not None else 0.0
                )
                svc.failures.append({
                    "detail": detail, "uptime_s": round(uptime, 3),
                })
                if uptime < spec.min_uptime:
                    svc.fast_deaths += 1
                else:
                    svc.fast_deaths = 0
                crash_loop = svc.fast_deaths >= spec.crash_loop_threshold
                exhausted = svc.restarts >= spec.max_restarts
                attempt = None
                if not crash_loop and not exhausted:
                    svc.restarts += 1
                    attempt = svc.restarts
            if attempt is None:
                why = (
                    f"crash loop ({spec.crash_loop_threshold} consecutive "
                    f"deaths under min_uptime={spec.min_uptime}s)"
                    if crash_loop
                    else f"restart budget exhausted "
                    f"(max_restarts={spec.max_restarts})"
                )
                self._transition(name, FAILED, f"{detail} — {why}")
                return
            self._restarts_total.inc(service=name)
            self._transition(
                name, STARTING,
                f"restart {attempt}/{spec.max_restarts} after: {detail}",
            )
            delay = policy.delay(attempt, rng)
            if delay > 0:
                time.sleep(delay)
            try:
                handle = spec.start()
            except Exception as e:
                detail = f"restart {attempt} failed to start: {e}"
                continue  # re-classify: a failed start is a fast death
            now = self._clock()
            with self._lock:
                svc = self._services[name]
                svc.handle = handle
                svc.started_at = now
            if not self._transition_if(
                name, (STARTING,), RUNNING, f"restarted (attempt {attempt})"
            ):
                return  # shutdown raced the restart; stop pass owns it
            return

    # --- health --------------------------------------------------------

    def healthz(self) -> dict:
        """The aggregated rollup: ``failed`` beats ``degraded`` beats
        ``ok``; FINISHED/STOPPED are terminal-but-healthy (a gang that
        trained to completion does not degrade the fleet)."""
        with self._lock:
            snaps = [
                svc.snapshot_locked() for svc in self._services.values()
            ]
        states = {s["state"] for s in snaps}
        if FAILED in states:
            status = "failed"
        elif states & {DEGRADED, STARTING, PENDING}:
            status = "degraded"
        else:
            status = "ok"
        return {
            "status": status,
            "services": {s["name"]: s for s in snaps},
        }

    def snapshot(self) -> dict:
        return self.healthz()

    def service_handle(self, name: str):
        """The live handle ``start()`` returned for ``name`` (the
        server object, thread box, or Popen) — how a scenario driver
        reads a finished service's result after shutdown."""
        with self._lock:
            return self._services[name].handle

    def serve_healthz(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Expose ``healthz()`` over HTTP (GET /healthz); returns the
        bound port. 200 while the fleet is ok/degraded, 503 once any
        service is FAILED — the signal an external orchestrator keys
        its replace-the-whole-runtime decision on."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        supervisor = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] not in ("/", "/healthz"):
                    self.send_error(404)
                    return
                doc = supervisor.healthz()
                body = json.dumps(doc).encode()
                code = 503 if doc["status"] == "failed" else 200
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet by default
                pass

        self._healthz_server = ThreadingHTTPServer((host, port), _Handler)
        self._healthz_thread = threading.Thread(
            target=self._healthz_server.serve_forever,
            name="tpuflow-runtime-healthz", daemon=True,
        )
        self._healthz_thread.start()
        return self._healthz_server.server_address[1]

    # --- wait + shutdown -----------------------------------------------

    def wait(self, timeout: float, poll: float = 0.05) -> bool:
        """Block until every service is terminal (FINISHED, FAILED, or
        STOPPED) or ``timeout`` elapses; returns whether the fleet
        quiesced. The soak's main loop."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                states = [s.state for s in self._services.values()]
            if all(st in (FINISHED, FAILED, STOPPED) for st in states):
                return True
            time.sleep(poll)
        return False

    def _stop_service(self, name: str) -> None:
        spec = self._specs[name]
        with self._lock:
            svc = self._services[name]
            state = svc.state
            handle = svc.handle
        if state in (PENDING, STOPPED, FAILED):
            return  # nothing running to stop
        self._transition(name, STOPPING)
        try:
            killed_by = spec.stop(handle, spec.grace)
        except Exception as e:
            killed_by = f"stop-error: {type(e).__name__}: {e}"
        with self._lock:
            self._services[name].killed_by = (
                killed_by if isinstance(killed_by, str) else None
            )
        self._transition(name, STOPPED)

    def shutdown(self) -> dict:
        """Dependency-aware graceful shutdown: reverse startup order, so
        every service stops BEFORE the services it depends on (the
        serving daemon drains before the gang it fronts is touched).
        Records each service's ``stop_index`` (its position in the
        shutdown sequence) and ``killed_by``. Idempotent; returns the
        final snapshot."""
        self._stop.set()
        probe = self._probe_thread
        if probe is not None:
            probe.join(timeout=10)
            self._probe_thread = None
        for idx, name in enumerate(reversed(self._order)):
            with self._lock:
                already = self._services[name].stop_index is not None
                if not already:
                    self._services[name].stop_index = idx
            if not already:
                self._stop_service(name)
        # The healthz endpoint answers THROUGH the drain (an
        # orchestrator watches the shutdown happen) and closes last.
        server = self._healthz_server
        if server is not None:
            server.shutdown()
            server.server_close()
            self._healthz_server = None
        thread = self._healthz_thread
        if thread is not None:
            thread.join(timeout=5)
            self._healthz_thread = None
        return self.snapshot()
